"""Declarative op table: the single source of truth for oracle coverage.

Reference analogue: paddle/phi/api/yaml/ops.yaml + the OpTest suites
(python/paddle/fluid/tests/unittests/test_*_op.py) — one declarative spec
per op drives both the API surface check and the numpy-oracle tests
(tests/test_optable_oracle.py parameterizes directly over TABLE).

Each row: (name, variant, inputs, attrs, ref, tol, call). `inputs` is an
ordered dict of numpy generators (fresh seeded rng per case); `ref` maps
the generated numpy inputs to the expected output (array or tuple);
`call` optionally overrides the default `op(*tensors, **attrs)` calling
convention (list-taking ops, method calls, inplace variants).
"""
from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["TABLE", "OpCase", "coverage_names"]


@dataclasses.dataclass
class OpCase:
    name: str                  # public op name in the paddle_tpu namespace
    variant: str               # case id suffix
    inputs: dict               # arg name -> numpy generator ()->array
    attrs: dict                # static kwargs
    ref: callable              # (*np arrays) -> np array | tuple
    atol: float = 1e-5
    rtol: float = 1e-5
    call: callable = None      # (op, tensors: list, attrs) -> output

    @property
    def case_id(self):
        return f"{self.name}:{self.variant}" if self.variant else self.name


TABLE: list[OpCase] = []


def _add(name, ref, inputs, attrs=None, variant="", atol=1e-5, rtol=1e-5,
         call=None):
    TABLE.append(OpCase(name, variant, inputs, dict(attrs or {}), ref,
                        atol, rtol, call))


def _rng(seed):
    return np.random.RandomState(seed)


def F(seed=0, shape=(4, 6), lo=-2.0, hi=2.0, dtype=np.float32):
    return lambda: _rng(seed).uniform(lo, hi, shape).astype(dtype)


def FP(seed=0, shape=(4, 6)):   # positive
    return F(seed, shape, 0.3, 3.0)


def FU(seed=0, shape=(4, 6)):   # in (-0.9, 0.9)
    return F(seed, shape, -0.9, 0.9)


def I(seed=0, shape=(4, 6), lo=0, hi=8, dtype=np.int64):
    return lambda: _rng(seed).randint(lo, hi, shape).astype(dtype)


def B(seed=0, shape=(4, 6)):
    return lambda: _rng(seed).rand(*shape) > 0.5


# =============================================================== unary

try:
    import scipy.special as _sps
except ImportError:          # pragma: no cover
    _sps = None

_UNARY = [
    ("abs", np.abs, F), ("exp", np.exp, FU), ("expm1", np.expm1, FU),
    ("log", np.log, FP), ("log2", np.log2, FP), ("log10", np.log10, FP),
    ("log1p", np.log1p, FP), ("sqrt", np.sqrt, FP),
    ("rsqrt", lambda v: 1 / np.sqrt(v), FP), ("square", np.square, F),
    ("sin", np.sin, F), ("cos", np.cos, F), ("tan", np.tan, FU),
    ("asin", np.arcsin, FU), ("acos", np.arccos, FU),
    ("atan", np.arctan, F), ("sinh", np.sinh, F), ("cosh", np.cosh, F),
    ("tanh", np.tanh, F), ("asinh", np.arcsinh, F),
    ("acosh", np.arccosh, lambda s=0, **k: F(s, (4, 6), 1.1, 3.0)),
    ("atanh", np.arctanh, FU), ("ceil", np.ceil, F),
    ("floor", np.floor, F), ("round", np.round, F),
    ("trunc", np.trunc, F), ("sign", np.sign, F),
    ("neg", np.negative, F), ("reciprocal", np.reciprocal, FP),
    ("sigmoid", lambda v: 1 / (1 + np.exp(-v)), F),
    ("frac", lambda v: v - np.trunc(v), F),
    ("relu", lambda v: np.maximum(v, 0), F),
    ("relu6", lambda v: np.clip(v, 0, 6), F),
    ("silu", lambda v: v / (1 + np.exp(-v)), F),
    ("softsign", lambda v: v / (1 + np.abs(v)), F),
    ("softplus", lambda v: np.log1p(np.exp(-np.abs(v))) + np.maximum(v, 0),
     F),
    ("hardsigmoid", lambda v: np.clip(v / 6 + 0.5, 0, 1), F),
    ("hardswish", lambda v: v * np.clip(v + 3, 0, 6) / 6, F),
    ("hardtanh", lambda v: np.clip(v, -1, 1), F),
    ("leaky_relu", lambda v: np.where(v > 0, v, 0.01 * v), F),
    ("elu", lambda v: np.where(v > 0, v, np.expm1(v)), F),
    ("celu", lambda v: np.where(v > 0, v, np.expm1(v)), F),
    ("selu", lambda v: 1.0507009873554805 * np.where(
        v > 0, v, 1.6732632423543772 * np.expm1(v)), F),
    ("mish", lambda v: v * np.tanh(np.log1p(np.exp(-np.abs(v)))
                                   + np.maximum(v, 0)), F),
    ("gelu", (lambda v: 0.5 * v * (1 + _sps.erf(v / np.sqrt(2.0))))
     if _sps else None, F),
    ("logsigmoid", lambda v: -(np.log1p(np.exp(-np.abs(v)))
                               + np.maximum(-v, 0)), F),
    ("tanhshrink", lambda v: v - np.tanh(v), F),
    ("softshrink", lambda v: np.where(v > 0.5, v - 0.5,
                                      np.where(v < -0.5, v + 0.5, 0)), F),
    ("hardshrink", lambda v: np.where(np.abs(v) > 0.5, v, 0), F),
]
if _sps is not None:
    _UNARY += [
        ("erf", _sps.erf, F), ("erfinv", _sps.erfinv, FU),
        ("lgamma", _sps.gammaln, FP), ("digamma", _sps.digamma, FP),
        ("logit", _sps.logit, lambda s=0, **k: F(s, (4, 6), 0.1, 0.9)),
        ("log_softmax",
         lambda v: v - _sps.logsumexp(v, axis=-1, keepdims=True), F),
    ]

for i, (nm, ref, gen) in enumerate(_UNARY):
    _add(nm, ref, {"x": gen(i)}, atol=3e-5, rtol=3e-5)

# second shape variant for a representative subset (3-d input)
for i, nm in enumerate(["exp", "tanh", "relu", "sigmoid", "abs", "sqrt",
                        "log", "sin", "gelu", "softplus"]):
    ref = dict((n, r) for n, r, _ in _UNARY)[nm]
    gen = FP(100 + i, (2, 3, 4)) if nm in ("sqrt", "log") \
        else F(100 + i, (2, 3, 4))
    _add(nm, ref, {"x": gen}, variant="3d", atol=3e-5, rtol=3e-5)

# =============================================================== binary

_BIN = [
    ("add", np.add), ("subtract", np.subtract),
    ("multiply", np.multiply), ("maximum", np.maximum),
    ("minimum", np.minimum), ("fmax", np.fmax), ("fmin", np.fmin),
    ("atan2", np.arctan2), ("hypot", np.hypot),
    ("logaddexp", np.logaddexp), ("heaviside", np.heaviside),
    ("copysign", np.copysign),
]
for i, (nm, ref) in enumerate(_BIN):
    _add(nm, ref, {"x": F(2 * i), "y": F(2 * i + 1)})
    _add(nm, ref, {"x": F(2 * i, (4, 6)), "y": F(2 * i + 1, (6,))},
         variant="bcast")

_add("divide", np.divide, {"x": F(40), "y": FP(41)})
_add("pow", np.power, {"x": FP(42), "y": F(43)}, atol=1e-4, rtol=1e-4)
_add("remainder", np.remainder, {"x": F(44), "y": FP(45)})
_add("mod", np.mod, {"x": F(46), "y": FP(47)})
_add("floor_divide", np.floor_divide, {"x": F(48), "y": FP(49)})
_add("gcd", np.gcd, {"x": I(50, hi=30), "y": I(51, lo=1, hi=30)})
_add("lcm", np.lcm, {"x": I(52, lo=1, hi=12), "y": I(53, lo=1, hi=12)})
_add("lerp", lambda x, y, w: x + w * (y - x),
     {"x": F(54), "y": F(55), "weight": F(56, (1,), 0.0, 1.0)})

# comparisons & logical
for i, (nm, ref) in enumerate([
        ("equal", np.equal), ("not_equal", np.not_equal),
        ("greater_than", np.greater), ("greater_equal", np.greater_equal),
        ("less_than", np.less), ("less_equal", np.less_equal)]):
    _add(nm, ref, {"x": I(60 + i, hi=4), "y": I(70 + i, hi=4)})
for i, (nm, ref) in enumerate([
        ("logical_and", np.logical_and), ("logical_or", np.logical_or),
        ("logical_xor", np.logical_xor)]):
    _add(nm, ref, {"x": B(80 + i), "y": B(90 + i)})
_add("logical_not", np.logical_not, {"x": B(99)})
for i, (nm, ref) in enumerate([
        ("bitwise_and", np.bitwise_and), ("bitwise_or", np.bitwise_or),
        ("bitwise_xor", np.bitwise_xor)]):
    _add(nm, ref, {"x": I(100 + i, dtype=np.int32),
                   "y": I(110 + i, dtype=np.int32)})
_add("bitwise_not", np.invert, {"x": I(119, dtype=np.int32)})
_add("bitwise_left_shift", np.left_shift,
     {"x": I(120, dtype=np.int32), "y": I(121, hi=4, dtype=np.int32)})
_add("bitwise_right_shift", np.right_shift,
     {"x": I(122, dtype=np.int32), "y": I(123, hi=4, dtype=np.int32)})
_add("isnan", np.isnan, {"x": F(124)})
_add("isinf", np.isinf, {"x": F(125)})
_add("isfinite", np.isfinite, {"x": F(126)})
_add("isclose", np.isclose, {"x": F(127), "y": F(127)})
_add("nan_to_num", np.nan_to_num,
     {"x": lambda: np.array([[1.0, np.nan, np.inf, -np.inf]],
                            np.float32)})

# =============================================================== reduce

_RED = [("sum", np.sum), ("mean", np.mean), ("max", np.max),
        ("min", np.min), ("amax", np.amax), ("amin", np.amin),
        ("prod", np.prod), ("nansum", np.nansum), ("nanmean", np.nanmean)]
for i, (nm, ref) in enumerate(_RED):
    gen = FP(130 + i, (4, 6))
    _add(nm, lambda v, r=ref: r(v), {"x": gen}, atol=1e-4, rtol=1e-4)
    _add(nm, lambda v, r=ref: r(v, axis=0), {"x": gen},
         attrs={"axis": 0}, variant="ax0", atol=1e-4, rtol=1e-4)
    _add(nm, lambda v, r=ref: r(v, axis=-1), {"x": gen},
         attrs={"axis": -1}, variant="axm1", atol=1e-4, rtol=1e-4)
    _add(nm, lambda v, r=ref: r(v, axis=1, keepdims=True), {"x": gen},
         attrs={"axis": 1, "keepdim": True}, variant="keep",
         atol=1e-4, rtol=1e-4)
_add("var", lambda v: np.var(v, ddof=1), {"x": F(140)}, atol=1e-4)
_add("var", lambda v: np.var(v, axis=1, ddof=0), {"x": F(141)},
     attrs={"axis": 1, "unbiased": False}, variant="ax1", atol=1e-4)
_add("std", lambda v: np.std(v, ddof=1), {"x": F(142)}, atol=1e-4)
_add("logsumexp",
     (lambda v: _sps.logsumexp(v, axis=-1)) if _sps else None,
     {"x": F(143)}, attrs={"axis": -1}, atol=1e-4)
_add("count_nonzero", np.count_nonzero, {"x": I(144, hi=3)})
_add("all", lambda v: np.all(v, axis=1), {"x": B(145)},
     attrs={"axis": 1})
_add("any", lambda v: np.any(v, axis=1), {"x": B(146)},
     attrs={"axis": 1})
_add("median", lambda v: np.median(v, axis=-1), {"x": F(147, (4, 5))},
     attrs={"axis": -1}, atol=1e-5)

# ========================================================== cumulative

_add("cumsum", lambda v: np.cumsum(v, 1), {"x": F(150)},
     attrs={"axis": 1})
_add("cumsum", lambda v: np.cumsum(v, 0), {"x": F(151)},
     attrs={"axis": 0}, variant="ax0")
_add("cumprod", lambda v: np.cumprod(v, 1), {"x": FU(152)},
     attrs={"dim": 1})
_add("cummax", lambda v: np.maximum.accumulate(v, 1), {"x": F(153)},
     attrs={"axis": 1},
     call=lambda op, ts, at: op(*ts, **at)[0])
_add("cummin", lambda v: np.minimum.accumulate(v, 1), {"x": F(154)},
     attrs={"axis": 1},
     call=lambda op, ts, at: op(*ts, **at)[0])
_add("logcumsumexp",
     (lambda v: np.log(np.cumsum(np.exp(v), 1))) if True else None,
     {"x": FU(155)}, attrs={"axis": 1}, atol=1e-4)

# =================================================== sorting/searching

_add("sort", lambda v: np.sort(v, 1), {"x": F(160)}, attrs={"axis": 1})
_add("sort", lambda v: -np.sort(-v, 1), {"x": F(161)},
     attrs={"axis": 1, "descending": True}, variant="desc")
_add("argsort", lambda v: np.argsort(v, 1, kind="stable"), {"x": F(162)},
     attrs={"axis": 1})
_add("argmax", lambda v: np.argmax(v, 1), {"x": F(163)},
     attrs={"axis": 1})
_add("argmin", lambda v: np.argmin(v, 0), {"x": F(164)},
     attrs={"axis": 0})
_add("topk", lambda v: -np.sort(-v, -1)[..., :3], {"x": F(165)},
     attrs={"k": 3}, call=lambda op, ts, at: op(*ts, **at)[0])
_add("kthvalue", lambda v: np.sort(v, -1)[..., 1], {"x": F(166)},
     attrs={"k": 2}, call=lambda op, ts, at: op(*ts, **at)[0])
_add("mode", lambda v: np.array([1.0, 1.0], np.float32),
     {"x": lambda: np.tile(np.array([[3.0, 1.0, 1.0]], np.float32),
                           (2, 1))},
     call=lambda op, ts, at: op(*ts, **at)[0])
_add("searchsorted",
     lambda s, v: np.searchsorted(s[0], v[0])[None],
     {"sorted_sequence": lambda: np.sort(
         _rng(168).uniform(-2, 2, (1, 8)).astype(np.float32), -1),
      "values": lambda: _rng(169).uniform(-2, 2, (1, 5)).astype(
          np.float32)})
_add("bucketize",
     lambda v, s: np.searchsorted(s, v),
     {"x": F(170), "sorted_sequence": lambda: np.array(
         [-1.0, 0.0, 1.0], np.float32)})
_add("nonzero", lambda v: np.stack(np.nonzero(v), 1),
     {"x": lambda: np.array([[0.0, 1.0], [2.0, 0.0]], np.float32)})
_add("where", np.where, {"condition": B(171), "x": F(172), "y": F(173)})
_add("masked_select", lambda v, m: v[m],
     {"x": lambda: np.arange(12, dtype=np.float32).reshape(3, 4),
      "mask": lambda: (np.arange(12).reshape(3, 4) % 2 == 0)})
_add("masked_fill", lambda v, m: np.where(m, 7.0, v).astype(np.float32),
     {"x": F(174), "mask": B(175)}, attrs={"value": 7.0})
_add("unique", lambda v: np.unique(v),
     {"x": lambda: np.array([3.0, 1.0, 1.0, 2.0], np.float32)},
     call=lambda op, ts, at: op(*ts, **at))
_add("unique_consecutive", lambda v: np.array([1.0, 2.0, 1.0],
                                              np.float32),
     {"x": lambda: np.array([1.0, 1.0, 2.0, 2.0, 1.0], np.float32)},
     call=lambda op, ts, at: op(*ts, **at)[0] if isinstance(
         op(*ts, **at), (tuple, list)) else op(*ts, **at))

# ======================================================== manipulation

A34 = lambda s=180: F(s, (3, 4))
_add("reshape", lambda v: v.reshape(6, 2), {"x": A34()},
     attrs={"shape": [6, 2]})
_add("reshape", lambda v: v.reshape(-1), {"x": A34(181)},
     attrs={"shape": [-1]}, variant="flat")
_add("transpose", lambda v: v.T, {"x": A34(182)}, attrs={"perm": [1, 0]})
_add("t", lambda v: v.T, {"x": A34(183)})
_add("flip", lambda v: np.flip(v, 0), {"x": A34(184)}, attrs={"axis": 0})
_add("roll", lambda v: np.roll(v, 2, 1), {"x": A34(185)},
     attrs={"shifts": 2, "axis": 1})
_add("tile", lambda v: np.tile(v, (2, 1)), {"x": A34(186)},
     attrs={"repeat_times": [2, 1]})
_add("squeeze", lambda v: v.squeeze(1),
     {"x": lambda: _rng(187).randn(3, 1, 4).astype(np.float32)},
     attrs={"axis": 1})
_add("unsqueeze", lambda v: v[:, None], {"x": A34(188)},
     attrs={"axis": 1})
_add("expand", lambda v: np.broadcast_to(v, (3, 4)),
     {"x": lambda: _rng(189).randn(1, 4).astype(np.float32)},
     attrs={"shape": [3, 4]})
_add("broadcast_to", lambda v: np.broadcast_to(v, (3, 4)),
     {"x": lambda: _rng(190).randn(1, 4).astype(np.float32)},
     attrs={"shape": [3, 4]})
_add("moveaxis", lambda v: np.moveaxis(v, 0, 1), {"x": A34(191)},
     attrs={"source": 0, "destination": 1})
_add("swapaxes", lambda v: np.swapaxes(v, 0, 1), {"x": A34(192)},
     attrs={"axis1": 0, "axis2": 1})
_add("rot90", lambda v: np.rot90(v), {"x": A34(193)})
_add("flatten", lambda v: v.reshape(-1),
     {"x": lambda: _rng(194).randn(2, 3, 4).astype(np.float32)},
     attrs={"start_axis": 0, "stop_axis": -1})
_add("tril", np.tril, {"x": A34(195)})
_add("triu", np.triu, {"x": A34(196)})
_add("diag", np.diag, {"x": lambda: _rng(197).randn(4).astype(
    np.float32)})
_add("diagonal", lambda v: np.diagonal(v, 0, 0, 1),
     {"x": lambda: _rng(198).randn(4, 4).astype(np.float32)})
_add("diagflat", np.diagflat, {"x": lambda: _rng(199).randn(3).astype(
    np.float32)})
_add("diag_embed", lambda v: np.stack([np.diag(r) for r in v]),
     {"x": A34(200)})
_add("repeat_interleave", lambda v: np.repeat(v, 2, 1), {"x": A34(201)},
     attrs={"repeats": 2, "axis": 1})
_add("index_select", lambda v, i: v[i],
     {"x": A34(202), "index": lambda: np.array([2, 0], np.int64)},
     attrs={"axis": 0})
_add("gather", lambda v, i: v[i],
     {"x": A34(203), "index": lambda: np.array([1, 2], np.int64)})
_add("take_along_axis", lambda v, i: np.take_along_axis(v, i, 1),
     {"arr": A34(204),
      "indices": lambda: np.argsort(_rng(204).uniform(
          -2, 2, (3, 4)).astype(np.float32), 1)},
     attrs={"axis": 1})
def _index_add_ref(v, i, s):
    out = v.copy()
    out[i] += s
    return out


_add("index_add", _index_add_ref,
     {"x": lambda: np.zeros((3, 4), np.float32),
      "index": lambda: np.array([0, 2], np.int64),
      "value": lambda: np.ones((2, 4), np.float32)},
     attrs={"axis": 0},
     call=lambda op, ts, at: op(ts[0], ts[1], at["axis"], ts[2]))
_add("pad", lambda v: np.pad(v, ((1, 1), (2, 2))), {"x": A34(205)},
     attrs={"pad": [1, 1, 2, 2]})
_add("one_hot", lambda i: np.eye(5, dtype=np.float32)[i],
     {"x": lambda: np.array([0, 3, 4], np.int64)},
     attrs={"num_classes": 5})
_add("crop", lambda v: v[1:3, 1:3],
     {"x": lambda: _rng(206).randn(4, 4).astype(np.float32)},
     attrs={"shape": [2, 2], "offsets": [1, 1]})
_add("slice", lambda v: v[1:3],
     {"x": lambda: _rng(207).randn(4, 4).astype(np.float32)},
     attrs={"axes": [0], "starts": [1], "ends": [3]})
_add("strided_slice", lambda v: v[0:4:2],
     {"x": lambda: _rng(208).randn(4, 4).astype(np.float32)},
     attrs={"axes": [0], "starts": [0], "ends": [4], "strides": [2]})

# =============================================================== linalg

SQ = lambda s: (lambda: (_rng(s).randn(3, 3) + 3 * np.eye(3)).astype(
    np.float32))
SPD = lambda s: (lambda: (lambda a: (a @ a.T + 3 * np.eye(3)).astype(
    np.float32))(_rng(s).randn(3, 3)))

_add("matmul", lambda a, b: a @ b,
     {"x": F(210, (3, 4)), "y": F(211, (4, 5))}, atol=1e-4)
_add("matmul", lambda a, b: a @ b,
     {"x": F(212, (2, 3, 4)), "y": F(213, (2, 4, 5))}, variant="batch",
     atol=1e-4)
_add("mm", lambda a, b: a @ b, {"x": F(214, (3, 4)), "y": F(215, (4, 5))},
     atol=1e-4)
_add("bmm", lambda a, b: a @ b,
     {"x": F(216, (2, 3, 4)), "y": F(217, (2, 4, 5))}, atol=1e-4)
_add("mv", lambda a, v: a @ v, {"x": F(218, (3, 4)), "vec": F(219, (4,))},
     atol=1e-4)
_add("dot", np.dot, {"x": F(220, (5,)), "y": F(221, (5,))}, atol=1e-4)
_add("inner", np.inner, {"x": F(222, (3, 4)), "y": F(223, (5, 4))},
     atol=1e-4)
_add("outer", np.outer, {"x": F(224, (3,)), "y": F(225, (4,))}, atol=1e-4)
_add("kron", np.kron, {"x": F(226, (2, 2)), "y": F(227, (2, 3))},
     atol=1e-4)
_add("cross", lambda a, b: np.cross(a, b),
     {"x": F(228, (4, 3)), "y": F(229, (4, 3))}, atol=1e-4)
_add("trace", np.trace, {"x": SQ(230)}, atol=1e-4)
_add("inverse", np.linalg.inv, {"x": SQ(231)}, atol=1e-3, rtol=1e-3)
_add("det", np.linalg.det, {"x": SQ(232)}, atol=1e-3, rtol=1e-3)
_add("slogdet", lambda a: np.stack(np.linalg.slogdet(a)), {"x": SPD(233)},
     atol=1e-3, rtol=1e-3)
_add("matrix_power", lambda a: np.linalg.matrix_power(a, 3),
     {"x": SQ(234)}, attrs={"n": 3}, atol=1e-3, rtol=1e-3)
_add("cholesky", np.linalg.cholesky, {"x": SPD(235)}, atol=1e-3)
_add("solve", lambda a, b: np.linalg.solve(a, b),
     {"x": SQ(236), "y": F(237, (3, 2))}, atol=1e-3, rtol=1e-3)
_add("triangular_solve",
     lambda a, b: np.linalg.solve(np.triu(a), b),
     {"x": lambda: (np.triu(_rng(238).randn(3, 3)) + 3 * np.eye(3)
                    ).astype(np.float32),
      "y": F(239, (3, 2))}, attrs={"upper": True}, atol=1e-3, rtol=1e-3)
_add("cholesky_solve",
     lambda b, l: np.linalg.solve(l @ l.T, b),
     {"x": F(240, (3, 2)),
      "y": lambda: np.linalg.cholesky(SPD(241)()).astype(np.float32)},
     attrs={"upper": False}, atol=1e-3, rtol=1e-3)
_add("pinv", np.linalg.pinv, {"x": F(242, (4, 3))}, atol=1e-3, rtol=1e-3)
_add("matrix_rank", lambda a: np.linalg.matrix_rank(a), {"x": SPD(243)})
_add("norm", lambda v: np.linalg.norm(v), {"x": F(244)}, atol=1e-4)
_add("norm", lambda v: np.linalg.norm(v, axis=1), {"x": F(245)},
     attrs={"axis": 1}, variant="ax1", atol=1e-4)
_add("norm", lambda v: np.abs(v).sum(axis=1), {"x": F(246)},
     attrs={"p": 1, "axis": 1}, variant="l1", atol=1e-4)
_add("vector_norm", lambda v: np.linalg.norm(v.reshape(-1)),
     {"x": F(247)}, atol=1e-4)
_add("matrix_norm", lambda v: np.linalg.norm(v, "fro"), {"x": F(248)},
     attrs={"p": "fro"}, atol=1e-4)
_add("multi_dot", lambda a, b, c: a @ b @ c,
     {"x": F(249, (2, 3)), "y": F(250, (3, 4)), "z": F(251, (4, 2))},
     call=lambda op, ts, at: op(ts), atol=1e-4)
_add("histogram", lambda v: np.histogram(v, bins=4, range=(-2, 2))[0],
     {"x": F(252, (20,))}, attrs={"bins": 4, "min": -2, "max": 2})
_add("bincount", lambda v: np.bincount(v),
     {"x": lambda: np.array([0, 1, 1, 3], np.int64)})
_add("cov", lambda v: np.cov(v), {"x": F(253, (3, 8))}, atol=1e-4,
     rtol=1e-4)
_add("corrcoef", lambda v: np.corrcoef(v), {"x": F(254, (3, 8))},
     atol=1e-4, rtol=1e-4)
_add("dist", lambda a, b: np.linalg.norm((a - b).reshape(-1)),
     {"x": F(255), "y": F(256)}, atol=1e-4)

# eigen/factorization families: compare invariants (reconstruction /
# eigenvalues) rather than sign-ambiguous factors
_add("eigh", lambda a: np.linalg.eigvalsh(a), {"x": SPD(257)},
     call=lambda op, ts, at: op(*ts, **at)[0], atol=1e-3, rtol=1e-3)
_add("eigvalsh", lambda a: np.linalg.eigvalsh(a), {"x": SPD(258)},
     atol=1e-3, rtol=1e-3)
_add("qr", lambda a: np.abs(np.linalg.qr(a)[1]), {"x": F(259, (4, 3))},
     call=lambda op, ts, at: abs(op(*ts, **at)[1]), atol=1e-3, rtol=1e-3)
_add("svd", lambda a: np.linalg.svd(a, compute_uv=False),
     {"x": F(260, (4, 3))},
     call=lambda op, ts, at: op(*ts, **at)[1], atol=1e-3, rtol=1e-3)

# ============================================================= creation

_add("zeros", lambda: np.zeros((3, 4), np.float32), {},
     attrs={"shape": [3, 4]})
_add("ones", lambda: np.ones((3, 4), np.float32), {},
     attrs={"shape": [3, 4]})
_add("full", lambda: np.full((2, 3), 2.5, np.float32), {},
     attrs={"shape": [2, 3], "fill_value": 2.5})
_add("eye", lambda: np.eye(4, dtype=np.float32), {},
     attrs={"num_rows": 4})
_add("arange", lambda: np.arange(0, 10, 2, dtype=np.float32), {},
     attrs={"start": 0, "end": 10, "step": 2})
_add("linspace", lambda: np.linspace(0, 1, 5, dtype=np.float32), {},
     attrs={"start": 0, "stop": 1, "num": 5})
_add("zeros_like", np.zeros_like, {"x": F(261)})
_add("ones_like", np.ones_like, {"x": F(262)})
_add("full_like", lambda v: np.full_like(v, 3.0), {"x": F(263)},
     attrs={"fill_value": 3.0})
_add("tril_indices", lambda: np.stack(np.tril_indices(4)), {},
     attrs={"row": 4, "col": 4})
_add("triu_indices", lambda: np.stack(np.triu_indices(4)), {},
     attrs={"row": 4, "col": 4})
_add("clip", lambda v: np.clip(v, -0.5, 0.5), {"x": F(264)},
     attrs={"min": -0.5, "max": 0.5})
_add("cast", lambda v: v.astype(np.int32), {"x": FP(265)},
     attrs={"dtype": "int32"})
_add("numel", lambda v: np.int64(v.size), {"x": F(266)})
_add("scale", lambda v: v * 2.0 + 1.0, {"x": F(267)},
     attrs={"scale": 2.0, "bias": 1.0})

# ====================================================== combining ops

_add("concat", lambda a, b: np.concatenate([a, b], 0),
     {"x": A34(270), "y": A34(271)},
     call=lambda op, ts, at: op(list(ts), axis=0))
_add("concat", lambda a, b: np.concatenate([a, b], 1),
     {"x": A34(272), "y": A34(273)}, variant="ax1",
     call=lambda op, ts, at: op(list(ts), axis=1))
_add("stack", lambda a, b: np.stack([a, b], 0),
     {"x": A34(274), "y": A34(275)},
     call=lambda op, ts, at: op(list(ts), axis=0))
_add("stack", lambda a, b: np.stack([a, b], 1),
     {"x": A34(276), "y": A34(277)}, variant="ax1",
     call=lambda op, ts, at: op(list(ts), axis=1))
_add("hstack", lambda a, b: np.hstack([a, b]),
     {"x": A34(278), "y": A34(279)},
     call=lambda op, ts, at: op(list(ts)))
_add("vstack", lambda a, b: np.vstack([a, b]),
     {"x": A34(280), "y": A34(281)},
     call=lambda op, ts, at: op(list(ts)))
_add("split", lambda v: tuple(np.split(v, 2, 1)),
     {"x": F(282, (3, 4))},
     call=lambda op, ts, at: tuple(op(ts[0], 2, axis=1)))
_add("chunk", lambda v: tuple(np.array_split(v, 2, 0)),
     {"x": F(283, (4, 3))},
     call=lambda op, ts, at: tuple(op(ts[0], 2, axis=0)))
_add("unbind", lambda v: tuple(v[i] for i in range(3)),
     {"x": F(284, (3, 4))},
     call=lambda op, ts, at: tuple(op(ts[0], axis=0)))
_add("unstack", lambda v: tuple(v[:, i] for i in range(3)),
     {"x": F(285, (4, 3))},
     call=lambda op, ts, at: tuple(op(ts[0], axis=1)))
_add("meshgrid", lambda a, b: tuple(np.meshgrid(a, b, indexing="ij")),
     {"x": F(286, (3,)), "y": F(287, (4,))},
     call=lambda op, ts, at: tuple(op(*ts)))
_add("einsum", lambda a, b: np.einsum("ij,jk->ik", a, b),
     {"x": F(288, (3, 4)), "y": F(289, (4, 5))},
     call=lambda op, ts, at: op("ij,jk->ik", *ts), atol=1e-4)
_add("einsum", lambda a: np.einsum("ii->", a), {"x": SQ(290)},
     call=lambda op, ts, at: op("ii->", ts[0]), variant="trace",
     atol=1e-4)

# ======================================================= int arithmetic

for i, (nm, ref) in enumerate([("add", np.add), ("subtract", np.subtract),
                               ("multiply", np.multiply),
                               ("maximum", np.maximum),
                               ("minimum", np.minimum)]):
    _add(nm, ref, {"x": I(300 + i, dtype=np.int32),
                   "y": I(310 + i, dtype=np.int32)}, variant="int32")

# ================================================ nn.functional oracle

_add("softmax", (lambda v: np.exp(v - _sps.logsumexp(
    v, axis=-1, keepdims=True))) if _sps else None, {"x": F(320)},
     attrs={"axis": -1}, atol=1e-5)
_add("softmax", (lambda v: np.exp(v - _sps.logsumexp(
    v, axis=0, keepdims=True))) if _sps else None, {"x": F(321)},
     attrs={"axis": 0}, variant="ax0", atol=1e-5)
_add("normalize",
     lambda v: v / np.maximum(np.linalg.norm(v, axis=1, keepdims=True),
                              1e-12),
     {"x": F(322)}, attrs={"axis": 1}, atol=1e-5)
_add("cosine_similarity",
     lambda a, b: (a * b).sum(1) / np.maximum(
         np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1), 1e-8),
     {"x1": F(323), "x2": F(324)}, attrs={"axis": 1}, atol=1e-5)
_add("linear", lambda x, w, b: x @ w + b,
     {"x": F(325, (3, 4)), "weight": F(326, (4, 5)),
      "bias": F(327, (5,))}, atol=1e-4)
_add("mse_loss", lambda a, b: np.mean((a - b) ** 2),
     {"input": F(328), "label": F(329)}, atol=1e-5)
_add("l1_loss", lambda a, b: np.mean(np.abs(a - b)),
     {"input": F(330), "label": F(331)}, atol=1e-5)
_add("kl_div",
     (lambda lp, t: np.mean(t * (np.log(t) - lp))) if True else None,
     {"input": lambda: np.log(_rng(332).dirichlet(
         np.ones(6), 4).astype(np.float32)),
      "label": lambda: _rng(333).dirichlet(
          np.ones(6), 4).astype(np.float32)},
     attrs={"reduction": "mean"}, atol=1e-5)
_add("binary_cross_entropy",
     lambda p, t: np.mean(-(t * np.log(p) + (1 - t) * np.log(1 - p))),
     {"input": lambda: _rng(334).uniform(0.1, 0.9, (4, 6)).astype(
         np.float32),
      "label": lambda: (_rng(335).rand(4, 6) > 0.5).astype(np.float32)},
     atol=1e-5)
_add("one_hot", lambda i: np.eye(6, dtype=np.float32)[i],
     {"x": lambda: np.array([[1, 5], [0, 2]], np.int64)},
     attrs={"num_classes": 6}, variant="2d")
_add("embedding", lambda i, w: w[i],
     {"x": lambda: np.array([[0, 2], [1, 1]], np.int64),
      "weight": F(336, (4, 5))})
_add("label_smooth",
     lambda v: v * 0.9 + 0.1 / 6,
     {"label": lambda: np.eye(6, dtype=np.float32)[
         np.array([0, 2, 4, 1])]},
     attrs={"epsilon": 0.1}, atol=1e-5)

# ====================================================== complex/other

_add("real", np.real, {"x": lambda: (_rng(340).randn(3, 4)
                                     + 1j * _rng(341).randn(3, 4)).astype(
                                         np.complex64)})
_add("imag", np.imag, {"x": lambda: (_rng(342).randn(3, 4)
                                     + 1j * _rng(343).randn(3, 4)).astype(
                                         np.complex64)})
_add("conj", np.conj, {"x": lambda: (_rng(344).randn(3, 4)
                                     + 1j * _rng(345).randn(3, 4)).astype(
                                         np.complex64)})
_add("angle", np.angle, {"x": lambda: (_rng(346).randn(3, 4)
                                       + 1j * _rng(347).randn(3, 4)
                                       ).astype(np.complex64)},
     atol=1e-5)
_add("complex", lambda r, i: r + 1j * i, {"real": F(348), "imag": F(349)})
_add("as_complex", lambda v: v[..., 0] + 1j * v[..., 1],
     {"x": F(350, (3, 4, 2))})
_add("as_real", lambda v: np.stack([v.real, v.imag], -1),
     {"x": lambda: (_rng(351).randn(3, 4) + 1j * _rng(352).randn(3, 4)
                    ).astype(np.complex64)})
_add("clone", lambda v: v, {"x": F(353)})
_add("assign", lambda v: v, {"x": F(354)})
_add("equal_all", lambda a, b: np.array(np.array_equal(a, b)),
     {"x": I(355, hi=3), "y": I(355, hi=3)})
_add("allclose", lambda a, b: np.array(np.allclose(a, b)),
     {"x": F(356), "y": F(356)})
_add("expand_as", lambda v, o: np.broadcast_to(v, o.shape),
     {"x": lambda: _rng(357).randn(1, 4).astype(np.float32),
      "y": F(358, (3, 4))})
_add("gather_nd", lambda v, i: v[tuple(i.T)],
     {"x": F(359, (3, 4)),
      "index": lambda: np.array([[0, 1], [2, 3]], np.int64)})
_add("scatter_nd_add",
     lambda v, i, u: (lambda o: (np.add.at(o, tuple(i.T), u), o)[1])(
         v.copy()),
     {"x": lambda: np.zeros((4,), np.float32),
      "index": lambda: np.array([[1], [2], [1]], np.int64),
      "updates": lambda: np.array([1.0, 2.0, 3.0], np.float32)})
_add("put_along_axis",
     lambda v, i, u: np.put_along_axis(v.copy(), i, u, 1) or
     (lambda o: (np.put_along_axis(o, i, u, 1), o)[1])(v.copy()),
     {"arr": F(360, (3, 4)),
      "indices": lambda: np.zeros((3, 1), np.int64),
      "values": lambda: np.full((3, 1), 9.0, np.float32)},
     attrs={"axis": 1})

# =================================================== round-3 op-tail batch
# (reference python/paddle/tensor/{math,manipulation,linalg}.py tail)

_add("deg2rad", np.deg2rad, {"x": F(400)})
_add("rad2deg", np.rad2deg, {"x": F(401)})
_add("sgn", np.sign, {"x": F(402)})
_add("negative", np.negative, {"x": F(403)})
_add("positive", np.positive, {"x": F(404)})
_add("nextafter", np.nextafter, {"x": F(405), "y": F(406)})
_add("ldexp", lambda x, y: np.ldexp(x, y.astype(np.int32)),
     {"x": F(407), "y": I(408, lo=-3, hi=4, dtype=np.int32)})
_add("frexp", lambda x: np.frexp(x), {"x": F(409)})
_add("isposinf",
     lambda x: np.isposinf(x),
     {"x": lambda: np.array([1.0, np.inf, -np.inf, np.nan], np.float32)})
_add("isneginf",
     lambda x: np.isneginf(x),
     {"x": lambda: np.array([1.0, np.inf, -np.inf, np.nan], np.float32)})
_add("isin", lambda x, t: np.isin(x, t),
     {"x": I(410, hi=6), "test_x": lambda: np.array([1, 3], np.int64)})
_add("diff", lambda x: np.diff(x), {"x": F(411)})
_add("trapezoid", lambda y: np.trapz(y), {"y": F(412, (5,))})
_add("quantile", lambda x: np.quantile(x, 0.5),
     {"x": F(413)}, attrs={"q": 0.5})
_add("nanquantile", lambda x: np.nanquantile(x, 0.5),
     {"x": F(414)}, attrs={"q": 0.5})
_add("nanmedian", lambda x: np.nanmedian(x),
     {"x": lambda: np.array([[1.0, np.nan, 3.0],
                             [4.0, 5.0, np.nan]], np.float32)})
_add("xlogy", lambda x, y: np.where(x == 0, 0.0, x * np.log(y)),
     {"x": F(415, shape=(4, 6), lo=0.0, hi=2.0), "y": FP(416)}, atol=1e-4)
if _sps is not None:
    _add("gammaln", _sps.gammaln, {"x": FP(417)}, atol=1e-4)
    _add("gammainc", _sps.gammainc, {"x": FP(418), "y": FP(419)}, atol=1e-4)
    _add("gammaincc", _sps.gammaincc, {"x": FP(420), "y": FP(421)},
         atol=1e-4)
    _add("i0", _sps.i0, {"x": F(422)}, atol=1e-4)
    _add("i0e", _sps.i0e, {"x": F(423)}, atol=1e-5)
    _add("i1", _sps.i1, {"x": F(424)}, atol=1e-4)
    _add("i1e", _sps.i1e, {"x": F(425)}, atol=1e-5)
    _add("multigammaln", lambda x: _sps.multigammaln(x, 2),
         {"x": F(426, lo=1.2, hi=4.0)}, attrs={"p": 2}, atol=1e-4)
_add("unflatten", lambda x: x.reshape(4, 2, 3), {"x": F(427, (4, 6))},
     attrs={"axis": 1, "shape": (2, 3)})
_add("fliplr", np.fliplr, {"x": F(428)})
_add("flipud", np.flipud, {"x": F(429)})
_add("take", lambda x, i: np.take(x.reshape(-1), i),
     {"x": F(430), "index": lambda: np.array([0, 5, 11], np.int64)})
_add("index_fill",
     lambda x, i: (lambda o: (o.__setitem__((slice(None), i), 7.0), o)[1])(
         x.copy()),
     {"x": F(431), "index": lambda: np.array([0, 2], np.int64)},
     attrs={"axis": 1, "value": 7.0})
_add("tensor_split", lambda x: tuple(np.array_split(x, 3, 0)),
     {"x": F(432, (6, 4))}, attrs={"num_or_indices": 3})
_add("hsplit", lambda x: tuple(np.hsplit(x, 2)), {"x": F(433, (4, 6))},
     attrs={"num_or_indices": 2})
_add("vsplit", lambda x: tuple(np.vsplit(x, 2)), {"x": F(434, (4, 6))},
     attrs={"num_or_indices": 2})
_add("column_stack", lambda a, b: np.column_stack([a, b]),
     {"x": F(435, (4,)), "y": F(436, (4,))},
     call=lambda op, ts, at: op([ts[0], ts[1]]))
_add("hstack", lambda a, b: np.hstack([a, b]),
     {"x": F(437, (4,)), "y": F(438, (4,))},
     call=lambda op, ts, at: op([ts[0], ts[1]]))
_add("vstack", lambda a, b: np.vstack([a, b]),
     {"x": F(439, (4,)), "y": F(440, (4,))},
     call=lambda op, ts, at: op([ts[0], ts[1]]))
_add("dstack", lambda a, b: np.dstack([a, b]),
     {"x": F(441, (4,)), "y": F(442, (4,))},
     call=lambda op, ts, at: op([ts[0], ts[1]]))
_add("block_diag", lambda a, b: np.block(
    [[a, np.zeros((a.shape[0], b.shape[1]))],
     [np.zeros((b.shape[0], a.shape[1])), b]]).astype(np.float32),
     {"x": F(443, (2, 2)), "y": F(444, (3, 3))},
     call=lambda op, ts, at: op(ts[0], ts[1]))
_add("addmm", lambda i, x, y: i + x @ y,
     {"input": F(445, (4, 4)), "x": F(446, (4, 5)), "y": F(447, (5, 4))},
     atol=1e-4)
_add("baddbmm", lambda i, x, y: i + np.matmul(x, y),
     {"input": F(448, (2, 3, 3)), "x": F(449, (2, 3, 4)),
      "y": F(450, (2, 4, 3))}, atol=1e-4)
_add("vander", lambda x: np.vander(x), {"x": F(451, (5,))}, atol=1e-4)
_add("cdist",
     lambda a, b: np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                          + 1e-30),
     {"x": F(452, (4, 3)), "y": F(453, (5, 3))}, atol=1e-4)
_add("pdist",
     lambda a: np.sqrt(((a[:, None, :] - a[None, :, :]) ** 2).sum(-1)
                       + 1e-30)[np.triu_indices(a.shape[0], 1)],
     {"x": F(454, (5, 3))}, atol=1e-4)
_add("renorm",
     lambda x: x * np.minimum(
         1.0, 1.0 / (np.abs(x ** 2).sum(1) ** 0.5 + 1e-12))[:, None],
     {"x": F(455, (4, 6))}, attrs={"p": 2.0, "axis": 0, "max_norm": 1.0},
     atol=1e-4)
_add("cholesky_inverse",
     lambda L: np.linalg.inv(L @ L.T),
     {"x": lambda: np.linalg.cholesky(
         (lambda a: a @ a.T + 3 * np.eye(3))(
             _rng(456).randn(3, 3)).astype(np.float32))}, atol=1e-2,
     rtol=1e-3)
_add("masked_scatter",
     lambda x, m, v: (lambda o: (o.__setitem__(
         m, v.reshape(-1)[:int(m.sum())]), o)[1])(x.copy()),
     {"x": F(457), "mask": B(458), "value": F(459, (24,))})
_add("cumulative_trapezoid",
     lambda y: np.array([np.trapz(y[:i + 2]) for i in range(len(y) - 1)],
                        np.float32),
     {"y": F(460, (6,))}, atol=1e-4)

# filter any rows whose ref ended up None (missing scipy)
TABLE = [c for c in TABLE if c is not None and c.ref is not None]


def coverage_names():
    return sorted({c.name for c in TABLE})
