"""Linear algebra ops (paddle.matmul/linalg.* parity).

Reference: python/paddle/tensor/linalg.py; kernels paddle/phi/kernels/
matmul_kernel.h etc. On TPU every matmul here lands on the MXU — keep
inputs bf16-friendly and batched.
"""
import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


@register("matmul", method=True)
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@register("mm", method=True)
def mm(x, y):
    return jnp.matmul(x, y)


@register("bmm", method=True)
def bmm(x, y):
    return jnp.matmul(x, y)


@register("dot", method=True)
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register("inner", method=True)
def inner(x, y):
    return jnp.inner(x, y)


@register("outer", method=True)
def outer(x, y):
    return jnp.outer(x, y)


@register("cross", method=True)
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@register("kron")
def kron(x, y):
    return jnp.kron(x, y)


@register("mv", method=True)
def mv(x, vec):
    return jnp.matmul(x, vec)


@register("t", method=True)
def t(x):
    return x.T if x.ndim >= 2 else x


@register("trace", method=True)
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register("norm", method=True)
def norm(x, p=None, axis=None, keepdim=False):
    if p is None:
        p = "fro" if axis is None or not isinstance(axis, int) else 2
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


@register("dist")
def dist(x, y, p=2):
    return norm.__wrapped__(x - y, p=p)


@register("matrix_norm")
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@register("vector_norm")
def vector_norm(x, p=2, axis=None, keepdim=False):
    # axis=None means the VECTOR norm of the flattened input (paddle
    # semantics); jnp.linalg.norm would compute the matrix 2-norm for 2-D
    if axis is None:
        out = jnp.linalg.norm(x.reshape(-1), ord=p)
        return out.reshape((1,) * x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@register("cond")
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register("det", method=True)
def det(x):
    return jnp.linalg.det(x)


@register("slogdet")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


@register("inverse", method=True)
def inverse(x):
    return jnp.linalg.inv(x)


@register("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register("matrix_power", method=True)
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@register("matrix_rank")
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register("qr")
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@register("svd")
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@register("eig")
def eig(x):
    return jnp.linalg.eig(x)


@register("eigh")
def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@register("eigvals")
def eigvals(x):
    return jnp.linalg.eigvals(x)


@register("eigvalsh")
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register("cholesky", method=True)
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@register("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@register("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@register("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register("lstsq")
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register("lu")
def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv


def multi_dot(xs):
    """paddle.linalg.multi_dot(list_of_tensors)."""
    from ..core.tensor import dispatch as _dispatch
    return _dispatch(lambda *vs: jnp.linalg.multi_dot(vs), *xs,
                     name="multi_dot")


from .registry import register_direct as _register_direct  # noqa: E402
_register_direct("multi_dot", multi_dot)


@register("householder_product")
def householder_product(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)
    q = eye
    for i in range(n):
        v = jnp.concatenate([jnp.zeros((i,), x.dtype), jnp.ones((1,), x.dtype),
                             x[i + 1:, i]])
        h = eye - tau[i] * jnp.outer(v, v)
        q = q @ h
    return q[:, :n]


@register("corrcoef")
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@register("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@register("einsum_impl")
def _einsum_vals(*operands, equation=None):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    from ..core.tensor import dispatch
    return dispatch(lambda *vs: jnp.einsum(equation, *vs), *operands, name="einsum")


from .registry import register_direct  # noqa: E402

register_direct("einsum", einsum)


# ------------------------------------------------------- linalg tail


@register("addmm", method=True)
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@register("baddbmm", method=True)
def baddbmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@register("vander")
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


@register("cdist")
def cdist(x, y, p=2.0):
    d = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


@register("pdist")
def pdist(x, p=2.0):
    n = x.shape[0]
    iu, ju = jnp.triu_indices(n, k=1)
    d = x[iu] - x[ju]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


@register("renorm", method=True)
def renorm(x, p, axis, max_norm):
    xm = jnp.moveaxis(x, axis, 0)
    flat = xm.reshape(xm.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, -1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-12), 1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(xm.shape), 0, axis)


@register("cholesky_inverse")
def cholesky_inverse(x, upper=False):
    """inv(A) from A's Cholesky factor; batched, via triangular solves
    (cho_solve) rather than generic inv."""
    import jax.scipy.linalg as jsl
    eye = jnp.broadcast_to(jnp.eye(x.shape[-1], dtype=x.dtype),
                           x.shape)
    return jsl.cho_solve((x, not upper), eye)


@register("lu_unpack", nondiff_args=(1,))
def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """paddle.linalg.lu_unpack parity: supports arbitrary batch dims via
    vmap; honours the unpack flags (None placeholders when off)."""
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)

    def one(a, piv):
        L = jnp.tril(a[:, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[:k, :])
        # pivots (1-based sequential swaps) -> permutation matrix
        perm = jnp.arange(m)
        for i in range(piv.shape[-1]):
            j = piv[i]
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        P = jnp.eye(m, dtype=a.dtype)[perm].T
        return P, L, U

    fn = one
    for _ in lu_data.shape[:-2]:
        fn = jax.vmap(fn)
    P, L, U = fn(lu_data, lu_pivots.astype(jnp.int32) - 1)
    return (P if unpack_pivots else None,
            L if unpack_ludata else None,
            U if unpack_ludata else None)


@register("tensordot")
def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)) and len(axes) == 2 and \
            isinstance(axes[0], (list, tuple)):
        axes = (tuple(axes[0]), tuple(axes[1]))
    return jnp.tensordot(x, y, axes=axes)
