"""Elementwise math, reductions, comparison and logic ops.

Parity source: python/paddle/tensor/math.py + logic.py in the reference
(thin wrappers over generated _C_ops); here each op is the jnp expression
XLA fuses directly.
"""
import jax
import jax.numpy as jnp

from .registry import register

# ---------------------------------------------------------------- elementwise


@register("add", method=True)
def add(x, y):
    return jnp.add(x, y)


@register("subtract", method=True)
def subtract(x, y):
    return jnp.subtract(x, y)


@register("multiply", method=True)
def multiply(x, y):
    return jnp.multiply(x, y)


@register("divide", method=True)
def divide(x, y):
    return jnp.divide(x, y)


@register("floor_divide", method=True)
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@register("mod", method=True)
def mod(x, y):
    return jnp.mod(x, y)


@register("remainder", method=True)
def remainder(x, y):
    return jnp.remainder(x, y)


@register("pow", method=True)
def pow(x, y):  # noqa: A001
    return jnp.power(x, y)


@register("maximum", method=True)
def maximum(x, y):
    return jnp.maximum(x, y)


@register("minimum", method=True)
def minimum(x, y):
    return jnp.minimum(x, y)


@register("fmax")
def fmax(x, y):
    return jnp.fmax(x, y)


@register("fmin")
def fmin(x, y):
    return jnp.fmin(x, y)


@register("neg", method=True)
def neg(x):
    return jnp.negative(x)


@register("abs", method=True)
def abs(x):  # noqa: A001
    return jnp.abs(x)


@register("sign", method=True)
def sign(x):
    return jnp.sign(x)


@register("exp", method=True)
def exp(x):
    return jnp.exp(x)


@register("expm1", method=True)
def expm1(x):
    return jnp.expm1(x)


@register("log", method=True)
def log(x):
    return jnp.log(x)


@register("log2", method=True)
def log2(x):
    return jnp.log2(x)


@register("log10", method=True)
def log10(x):
    return jnp.log10(x)


@register("log1p", method=True)
def log1p(x):
    return jnp.log1p(x)


@register("sqrt", method=True)
def sqrt(x):
    return jnp.sqrt(x)


@register("rsqrt", method=True)
def rsqrt(x):
    return jax.lax.rsqrt(x)


@register("square", method=True)
def square(x):
    return jnp.square(x)


@register("reciprocal", method=True)
def reciprocal(x):
    return jnp.reciprocal(x)


@register("sin", method=True)
def sin(x):
    return jnp.sin(x)


@register("cos", method=True)
def cos(x):
    return jnp.cos(x)


@register("tan", method=True)
def tan(x):
    return jnp.tan(x)


@register("asin", method=True)
def asin(x):
    return jnp.arcsin(x)


@register("acos", method=True)
def acos(x):
    return jnp.arccos(x)


@register("atan", method=True)
def atan(x):
    return jnp.arctan(x)


@register("atan2")
def atan2(x, y):
    return jnp.arctan2(x, y)


@register("sinh", method=True)
def sinh(x):
    return jnp.sinh(x)


@register("cosh", method=True)
def cosh(x):
    return jnp.cosh(x)


@register("tanh", method=True)
def tanh(x):
    return jnp.tanh(x)


@register("asinh", method=True)
def asinh(x):
    return jnp.arcsinh(x)


@register("acosh", method=True)
def acosh(x):
    return jnp.arccosh(x)


@register("atanh", method=True)
def atanh(x):
    return jnp.arctanh(x)


@register("erf", method=True)
def erf(x):
    return jax.scipy.special.erf(x)


@register("erfinv", method=True)
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("logsigmoid")
def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


@register("floor", method=True)
def floor(x):
    return jnp.floor(x)


@register("ceil", method=True)
def ceil(x):
    return jnp.ceil(x)


@register("round", method=True)
def round(x):  # noqa: A001
    return jnp.round(x)


@register("trunc", method=True)
def trunc(x):
    return jnp.trunc(x)


@register("frac", method=True)
def frac(x):
    return x - jnp.trunc(x)


@register("clip", method=True)
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


@register("scale", method=True)
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@register("lerp", method=True)
def lerp(x, y, weight):
    return x + weight * (y - x)


@register("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@register("multiply_add")
def multiply_add(x, y, z):
    return x * y + z


@register("logit")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@register("logaddexp")
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@register("hypot")
def hypot(x, y):
    return jnp.hypot(x, y)


@register("nan_to_num", method=True)
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register("angle")
def angle(x):
    return jnp.angle(x)


@register("conj", method=True)
def conj(x):
    return jnp.conj(x)


@register("real", method=True)
def real(x):
    return jnp.real(x)


@register("imag", method=True)
def imag(x):
    return jnp.imag(x)


@register("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


@register("lgamma")
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@register("polygamma")
def polygamma(x, n=1):
    return jax.scipy.special.polygamma(n, x)


@register("heaviside")
def heaviside(x, y):
    return jnp.heaviside(x, y)


@register("copysign")
def copysign(x, y):
    return jnp.copysign(x, y)


@register("gcd")
def gcd(x, y):
    return jnp.gcd(x, y)


@register("lcm")
def lcm(x, y):
    return jnp.lcm(x, y)


# ---------------------------------------------------------------- reductions


@register("sum", method=True)
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


@register("mean", method=True)
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


@register("max", method=True)
def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=axis, keepdims=keepdim)


@register("min", method=True)
def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=axis, keepdims=keepdim)


@register("prod", method=True)
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


@register("std", method=True)
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@register("var", method=True)
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@register("median", method=True)
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


@register("nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


@register("nansum")
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=axis, dtype=dtype, keepdims=keepdim)


@register("logsumexp", method=True)
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


@register("all", method=True)
def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=axis, keepdims=keepdim)


@register("any", method=True)
def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=axis, keepdims=keepdim)


@register("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


@register("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


@register("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


# ---------------------------------------------------------------- cumulative


@register("cumsum", method=True)
def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


@register("cumprod", method=True)
def cumprod(x, dim=None):
    return jnp.cumprod(x, axis=dim)


def _cum_extreme(x, axis, is_max):
    """(running extreme values, index of first attaining element) —
    paddle.cummax/cummin return both (python/paddle/tensor/math.py).
    Associative scan over (value, index) pairs; strict comparison keeps
    the EARLIEST index on ties, and the pairwise combine is associative
    so the scan is correct for any tree order."""
    ax = axis % x.ndim
    n = x.shape[ax]
    pos = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32).reshape(
            [-1 if i == ax else 1 for i in range(x.ndim)]), x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        better = (bv > av) if is_max else (bv < av)
        return jnp.where(better, bv, av), jnp.where(better, bi, ai)

    vals, idx = jax.lax.associative_scan(combine, (x, pos), axis=ax)
    return vals, idx.astype(jnp.int64)


@register("cummax")
def cummax(x, axis=None):
    xs = x.reshape(-1) if axis is None else x
    return _cum_extreme(xs, 0 if axis is None else axis, True)


@register("cummin")
def cummin(x, axis=None):
    xs = x.reshape(-1) if axis is None else x
    return _cum_extreme(xs, 0 if axis is None else axis, False)


@register("logcumsumexp")
def logcumsumexp(x, axis=None):
    xs = x.reshape(-1) if axis is None else x
    ax = 0 if axis is None else axis
    return jax.lax.cumlogsumexp(xs, axis=ax)


# ---------------------------------------------------------------- comparison


@register("equal", method=True)
def equal(x, y):
    return jnp.equal(x, y)


@register("not_equal", method=True)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@register("greater_than", method=True)
def greater_than(x, y):
    return jnp.greater(x, y)


@register("greater_equal", method=True)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@register("less_than", method=True)
def less_than(x, y):
    return jnp.less(x, y)


@register("less_equal", method=True)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@register("equal_all")
def equal_all(x, y):
    return jnp.array_equal(x, y)


@register("allclose", method=True)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register("isclose", method=True)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register("isnan", method=True)
def isnan(x):
    return jnp.isnan(x)


@register("isinf", method=True)
def isinf(x):
    return jnp.isinf(x)


@register("isfinite", method=True)
def isfinite(x):
    return jnp.isfinite(x)


@register("logical_and", method=True)
def logical_and(x, y):
    return jnp.logical_and(x, y)


@register("logical_or", method=True)
def logical_or(x, y):
    return jnp.logical_or(x, y)


@register("logical_not", method=True)
def logical_not(x):
    return jnp.logical_not(x)


@register("logical_xor", method=True)
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@register("bitwise_and", method=True)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@register("bitwise_or", method=True)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@register("bitwise_xor", method=True)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@register("bitwise_not", method=True)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@register("bitwise_left_shift")
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@register("bitwise_right_shift")
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


# -------------------------------------------------- special/extra elementwise
# (reference python/paddle/tensor/math.py tail + ops.yaml special functions)


@register("deg2rad", method=True)
def deg2rad(x):
    return jnp.deg2rad(x)


@register("rad2deg", method=True)
def rad2deg(x):
    return jnp.rad2deg(x)


@register("xlogy")
def xlogy(x, y):
    from jax.scipy.special import xlogy as _x
    return _x(x, y)


@register("sgn", method=True)
def sgn(x):
    return jnp.sign(x)


@register("positive")
def positive(x):
    return jnp.positive(x)


@register("negative", method=True)
def negative(x):
    return jnp.negative(x)


@register("i0", method=True)
def i0(x):
    from jax.scipy.special import i0 as _i0
    return _i0(x)


@register("i0e", method=True)
def i0e(x):
    from jax.scipy.special import i0e as _i
    return _i(x)


@register("i1", method=True)
def i1(x):
    from jax.scipy.special import i1 as _i
    return _i(x)


@register("i1e", method=True)
def i1e(x):
    from jax.scipy.special import i1e as _i
    return _i(x)


@register("gammaln", method=True)
def gammaln(x):
    from jax.scipy.special import gammaln as _g
    return _g(x)


@register("gammainc", method=True)
def gammainc(x, y):
    from jax.scipy.special import gammainc as _g
    return _g(x, y)


@register("gammaincc", method=True)
def gammaincc(x, y):
    from jax.scipy.special import gammaincc as _g
    return _g(x, y)


@register("multigammaln")
def multigammaln(x, p):
    from jax.scipy.special import multigammaln as _g
    return _g(x, int(p))


@register("nextafter", method=True)
def nextafter(x, y):
    return jnp.nextafter(x, y)


@register("ldexp", method=True)
def ldexp(x, y):
    return jnp.ldexp(x, y.astype(jnp.int32) if hasattr(y, "astype") else y)


@register("frexp", method=True)
def frexp(x):
    return jnp.frexp(x)


@register("isposinf", method=True)
def isposinf(x):
    return jnp.isposinf(x)


@register("isneginf", method=True)
def isneginf(x):
    return jnp.isneginf(x)


@register("isreal", method=True)
def isreal(x):
    return jnp.isreal(x)


@register("isin", nondiff_args=(1,))
def isin(x, test_x, assume_unique=False, invert=False):
    return jnp.isin(x, test_x, invert=invert)


@register("diff", method=True)
def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@register("trapezoid")
def trapezoid(y, x=None, dx=1.0, axis=-1):
    return jax.scipy.integrate.trapezoid(y, x=x, dx=dx, axis=axis)


@register("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1):
    # no jax.scipy cumulative_trapezoid: composed from the trapezoid rule
    ya = jnp.moveaxis(y, axis, -1)
    avg = (ya[..., 1:] + ya[..., :-1]) / 2.0
    if x is not None:
        xa = jnp.moveaxis(x, axis, -1) if getattr(x, "ndim", 0) else x
        d = jnp.diff(xa, axis=-1)
        seg = avg * d
    else:
        seg = avg * dx
    return jnp.moveaxis(jnp.cumsum(seg, -1), -1, axis)


@register("quantile", method=True)
def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                        method=interpolation)


@register("nanquantile", method=True)
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                           method=interpolation)


@register("nanmedian", method=True)
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)
