"""Shape/layout manipulation + indexing + search ops.

Parity source: python/paddle/tensor/manipulation.py, search.py in the
reference. Static shapes everywhere — dynamic-shape ops (nonzero,
masked_select, unique) are eager-only by construction, mirroring how XLA
forbids them inside jit.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, dispatch, unwrap, wrap
from .registry import register, register_direct

# ----------------------------------------------------------------- reshaping


@register("reshape", method=True)
def reshape(x, shape):
    return jnp.reshape(x, shape)


@register("flatten", method=True)
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    start = start_axis % nd
    stop = stop_axis % nd
    shape = list(x.shape)
    new_shape = shape[:start] + [int(np.prod(shape[start:stop + 1]))] + shape[stop + 1:]
    return jnp.reshape(x, new_shape)


@register("squeeze", method=True)
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


@register("unsqueeze", method=True)
def unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


@register("transpose", method=True)
def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


@register("moveaxis", method=True)
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@register("swapaxes", method=True)
def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


@register("broadcast_to", method=True)
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


@register("expand", method=True)
def expand(x, shape):
    shape = [s if s != -1 else x.shape[i - (len(shape) - x.ndim)]
             for i, s in enumerate(shape)]
    return jnp.broadcast_to(x, shape)


@register("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register("tile", method=True)
def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


@register("repeat_interleave", method=True)
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("flip", method=True)
def flip(x, axis):
    return jnp.flip(x, axis=axis)


@register("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@register("roll", method=True)
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@register("as_complex")
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


# ------------------------------------------------------------- join / split


def concat(x, axis=0):
    """paddle.concat(list_of_tensors, axis)."""
    return dispatch(lambda *vs: jnp.concatenate(vs, axis=axis), *x, name="concat")


register_direct("concat", concat)


def stack(x, axis=0):
    return dispatch(lambda *vs: jnp.stack(vs, axis=axis), *x, name="stack")


register_direct("stack", stack)


def vstack(x):
    return dispatch(lambda *vs: jnp.vstack(vs), *x, name="vstack")


register_direct("vstack", vstack)


def hstack(x):
    return dispatch(lambda *vs: jnp.hstack(vs), *x, name="hstack")


register_direct("hstack", hstack)


@register("split", method=True)
def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sizes = list(num_or_sections)
    dim = x.shape[axis]
    if any(s == -1 for s in sizes):
        known = sum(s for s in sizes if s != -1)
        sizes = [dim - known if s == -1 else s for s in sizes]
    idx = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


@register("chunk", method=True)
def chunk(x, chunks, axis=0):
    return tuple(jnp.split(x, chunks, axis=axis))


@register("unbind", method=True)
def unbind(x, axis=0):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@register("unstack")
def unstack(x, axis=0, num=None):
    n = num or x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


# --------------------------------------------------------------- slicing


@register("slice", nondiff_args=())
def slice(x, axes, starts, ends):  # noqa: A001
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = jnp.s_[st:en]
    return x[tuple(idx)]


@register("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[st:en:sd]
    return x[tuple(idx)]


@register("crop")
def crop(x, shape, offsets=None):
    offsets = offsets or [0] * x.ndim
    idx = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return x[idx]


def _getitem(x, index):
    if isinstance(index, Tensor):
        return dispatch(lambda v, i: v[i], x, index, nondiff_args=(1,), name="getitem")
    if isinstance(index, tuple):
        has_tensor = any(isinstance(i, Tensor) for i in index)
        if has_tensor:
            tpos = [i for i, e in enumerate(index) if isinstance(e, Tensor)]
            tens = [index[i] for i in tpos]

            def fn(v, *idxs):
                full = list(index)
                for p, i in zip(tpos, idxs):
                    full[p] = i
                return v[tuple(full)]

            return dispatch(fn, x, *tens,
                            nondiff_args=tuple(range(1, len(tens) + 1)),
                            name="getitem")
    return dispatch(lambda v: v[index], x, name="getitem")


def _setitem(self, index, value):
    # Eager-only mutation (reference: __setitem__ via set_value op).
    idx = unwrap(index) if isinstance(index, Tensor) else index
    if isinstance(idx, tuple):
        idx = tuple(unwrap(i) if isinstance(i, Tensor) else i for i in idx)
    val = unwrap(value) if isinstance(value, Tensor) else value
    self._replace_value(self._value.at[idx].set(val))


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem


# --------------------------------------------------------------- gather etc


@register("gather", method=True, nondiff_args=(1,))
def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register("gather_nd", method=True, nondiff_args=(1,))
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register("take_along_axis", nondiff_args=(1,))
def take_along_axis(arr, indices, axis, broadcast=True):
    return jnp.take_along_axis(arr, indices, axis=axis)


@register("put_along_axis", nondiff_args=(1,))
def put_along_axis(arr, indices, values, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, values, axis=axis, inplace=False)
    if reduce == "add":
        idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(arr.ndim)])
               for d, s in enumerate(indices.shape)]
        idx[axis] = indices
        return arr.at[tuple(idx)].add(values)
    raise NotImplementedError(reduce)


@register("scatter", nondiff_args=(1,))
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@register("scatter_nd_add", nondiff_args=(1,))
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register("index_select", method=True, nondiff_args=(1,))
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register("index_add", nondiff_args=(1,))
def index_add(x, index, axis, value):
    idx = [jnp.s_[:]] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@register("index_put", nondiff_args=(1,))
def index_put(x, indices, value, accumulate=False):
    if accumulate:
        return x.at[tuple(indices)].add(value)
    return x.at[tuple(indices)].set(value)


@register("where")
def where(condition, x=None, y=None):
    return jnp.where(condition, x, y)


@register("select_scatter")
def select_scatter(x, values, axis, index):
    idx = [jnp.s_[:]] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@register("masked_fill", method=True)
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@register("diagonal", method=True)
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@register("diag")
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + builtins_abs(offset)
        base = jnp.full((n, n), padding_value, dtype=x.dtype)
        return base + jnp.diag(x - padding_value, k=offset) \
            if False else jnp.where(jnp.eye(n, k=offset, dtype=bool), jnp.diag(x, k=offset), base)
    return jnp.diag(x, k=offset)


builtins_abs = abs


@register("diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    out = jax.vmap(jnp.diag, in_axes=0)(x.reshape(-1, x.shape[-1])) if x.ndim > 1 \
        else jnp.diag(x, k=offset)
    if x.ndim > 1:
        out = out.reshape(x.shape[:-1] + out.shape[-2:])
    return out


@register("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@register("tril", method=True)
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register("triu", method=True)
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle convention: pad applies to last len(pad)//2 dims, reversed order
        n = len(pad) // 2
        width = [(0, 0)] * (x.ndim - n) + [
            (pad[2 * i], pad[2 * i + 1]) for i in range(n)
        ]
    if mode == "constant":
        return jnp.pad(x, width, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, width, mode=jmode)


# --------------------------------------------------------------- search/sort


@register("argmax", method=True)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(dtype) if dtype else out


@register("argmin", method=True)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(dtype) if dtype else out


@register("argsort", method=True)
def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out


@register("sort", method=True)
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


@register("topk", method=True)
def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
        v, i = jax.lax.top_k(xm if largest else -xm, k)
        if not largest:
            v = -v
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)
    v, i = jax.lax.top_k(x if largest else -x, k)
    if not largest:
        v = -v
    return v, i


@register("kthvalue", method=True)
def kthvalue(x, k, axis=-1, keepdim=False):
    v = jnp.sort(x, axis=axis)
    i = jnp.argsort(x, axis=axis)
    vk = jnp.take(v, k - 1, axis=axis)
    ik = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        vk, ik = jnp.expand_dims(vk, axis), jnp.expand_dims(ik, axis)
    return vk, ik


@register("mode", method=True)
def mode(x, axis=-1, keepdim=False):
    srt = jnp.sort(x, axis=axis)
    # most frequent value via run-length on sorted values
    eq = jnp.concatenate(
        [jnp.ones_like(jnp.take(srt, jnp.array([0]), axis=axis), dtype=jnp.int32),
         (jnp.diff(srt, axis=axis) != 0).astype(jnp.int32)], axis=axis)
    run_id = jnp.cumsum(eq, axis=axis)
    # count occurrences of each run id positionally
    counts = jax.vmap(lambda r: jnp.sum(r[:, None] == r[None, :], axis=1),
                      in_axes=0)(run_id.reshape(-1, run_id.shape[-1]))
    counts = counts.reshape(run_id.shape)
    best = jnp.argmax(counts, axis=axis, keepdims=True)
    vals = jnp.take_along_axis(srt, best, axis=axis)
    if not keepdim:
        vals = jnp.squeeze(vals, axis=axis)
    idx = jnp.argmax((x == (vals if keepdim else jnp.expand_dims(vals, axis))),
                     axis=axis, keepdims=keepdim)
    return vals, idx


@register("searchsorted")
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim > 1:
        # paddle supports batched innermost-dim search; jnp.searchsorted
        # is 1-D only, so vmap over the leading dims
        fn = lambda s, v: jnp.searchsorted(s, v, side=side)
        for _ in range(sorted_sequence.ndim - 1):
            fn = jax.vmap(fn)
        out = fn(sorted_sequence,
                 values.reshape(sorted_sequence.shape[:-1] + (-1,)))
        out = out.reshape(values.shape)
    else:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    return out.astype(jnp.int32) if out_int32 else out.astype(jnp.int64)


@register("bucketize")
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32) if out_int32 else out.astype(jnp.int64)


@register("bincount")
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


@register("histogram")
def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(x, bins=bins, range=rng)
    return h


# ------------------------------------------------- dynamic-shape (eager only)


def nonzero(x, as_tuple=False):
    xv = unwrap(x) if isinstance(x, Tensor) else x
    idx = np.nonzero(np.asarray(xv))
    if as_tuple:
        return tuple(wrap(jnp.asarray(i)) for i in idx)
    return wrap(jnp.asarray(np.stack(idx, axis=-1)))


register_direct("nonzero", nonzero, method=True)


def masked_select(x, mask):
    xv = np.asarray(unwrap(x))
    mv = np.asarray(unwrap(mask) if isinstance(mask, Tensor) else mask)
    return wrap(jnp.asarray(xv[mv]))


register_direct("masked_select", masked_select, method=True)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    xv = np.asarray(unwrap(x))
    res = np.unique(xv, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(wrap(jnp.asarray(r)) for r in res)
    return wrap(jnp.asarray(res))


register_direct("unique", unique, method=True)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    xv = np.asarray(unwrap(x))
    vals = []
    prev = object()
    for v in xv.reshape(-1) if axis is None else xv:
        if not np.array_equal(v, prev):
            vals.append(v)
        prev = v
    return wrap(jnp.asarray(np.array(vals)))


register_direct("unique_consecutive", unique_consecutive)


# --------------------------------------------------------------- dtype/cast


@register("cast", method=True)
def cast(x, dtype):
    from ..core.dtype import convert_dtype
    return x.astype(convert_dtype(dtype))


def astype(x, dtype):
    return cast(x, dtype)


register_direct("astype", astype, method=True)


@register("numel", method=True)
def numel(x):
    return jnp.asarray(x.size, dtype=jnp.int64)


@register("one_hot")
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


@register("meshgrid")
def meshgrid(*args):
    return tuple(jnp.meshgrid(*args, indexing="ij"))


@register("atleast_1d")
def atleast_1d(x):
    return jnp.atleast_1d(x)


@register("atleast_2d")
def atleast_2d(x):
    return jnp.atleast_2d(x)


@register("atleast_3d")
def atleast_3d(x):
    return jnp.atleast_3d(x)


# ------------------------------------------ reshaping/stacking tail
# (reference python/paddle/tensor/manipulation.py tail)


@register("unflatten", method=True)
def unflatten(x, axis, shape):
    ax = axis % x.ndim
    new = list(x.shape[:ax]) + list(shape) + list(x.shape[ax + 1:])
    return x.reshape(new)


@register("view", method=True)
def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(list(shape_or_dtype))
    return x.view(shape_or_dtype) if hasattr(x, "view") else \
        x.astype(shape_or_dtype)


@register("as_strided", method=True)
def as_strided(x, shape, stride, offset=0):
    flat = x.reshape(-1)
    idx = offset + sum(
        jnp.arange(s).reshape([-1 if i == d else 1
                               for i in range(len(shape))]) * st
        for d, (s, st) in enumerate(zip(shape, stride)))
    return flat[idx.reshape(-1)].reshape(list(shape))


@register("tensor_split", nondiff_args=(1,))
def tensor_split(x, num_or_indices, axis=0):
    if isinstance(num_or_indices, int):
        return jnp.array_split(x, num_or_indices, axis=axis)
    return jnp.split(x, num_or_indices, axis=axis)


@register("hsplit", nondiff_args=(1,))
def hsplit(x, num_or_indices):
    return jnp.hsplit(x, num_or_indices)


@register("vsplit", nondiff_args=(1,))
def vsplit(x, num_or_indices):
    return jnp.vsplit(x, num_or_indices)


@register("dsplit", nondiff_args=(1,))
def dsplit(x, num_or_indices):
    return jnp.dsplit(x, num_or_indices)


def _stack_list(fn):
    def op(x, name=None):
        from ..core.tensor import Tensor, dispatch
        vals = list(x)
        return dispatch(lambda *vs: fn(vs), *vals, name=name)
    return op


from .registry import register_direct as _rd  # noqa: E402

_rd("column_stack", _stack_list(jnp.column_stack))
_rd("row_stack", _stack_list(jnp.vstack))
_rd("dstack", _stack_list(jnp.dstack))
_rd("hstack", _stack_list(jnp.hstack))
_rd("vstack", _stack_list(jnp.vstack))


@register("fliplr", method=True)
def fliplr(x):
    return jnp.fliplr(x)


@register("flipud", method=True)
def flipud(x):
    return jnp.flipud(x)


@register("block_diag")
def block_diag(*inputs):
    return jax.scipy.linalg.block_diag(*inputs)


@register("take", method=True, nondiff_args=(1,))
def take(x, index, mode="raise"):
    """Flat-index gather. mode='raise' checks bounds eagerly (concrete
    indices only — under jit, data-dependent raising is impossible and
    out-of-range indices clamp, diverging from the reference's error)."""
    if mode == "raise" and not isinstance(index, jax.core.Tracer):
        n = x.size
        if bool(jnp.any((index < -n) | (index >= n))):
            raise IndexError(f"take index out of range for {n} elements")
    m = {"raise": "clip", "wrap": "wrap", "clip": "clip"}[mode]
    return jnp.take(x.reshape(-1), index, mode=m)


@register("index_fill", method=True, nondiff_args=(1,))
def index_fill(x, index, axis, value):
    import builtins
    idx = [builtins.slice(None)] * x.ndim   # `slice` = the paddle op here
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


@register("masked_scatter", method=True, nondiff_args=(1,))
def masked_scatter(x, mask, value):
    # paddle semantics: fill masked slots with value's leading elements in
    # row-major order; too-few source elements is an error (checked
    # eagerly — under jit the count is data-dependent and clamps instead)
    flat_m = mask.reshape(-1)
    if not isinstance(flat_m, jax.core.Tracer):
        needed = int(jnp.sum(flat_m))
        if value.size < needed:
            raise ValueError(
                f"masked_scatter: value has {value.size} elements, mask "
                f"needs {needed}")
    pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
    src = value.reshape(-1)[jnp.clip(pos, 0, value.size - 1)]
    return jnp.where(flat_m, src, x.reshape(-1)).reshape(x.shape)


# ------------------------------------------------ top-level parity tail


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference
    python/paddle/tensor/math.py multiplex): inputs list of [B, ...],
    index [B, 1] -> out[b] = inputs[index[b]][b]."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    def fn(idx, *cands):
        stacked = jnp.stack(cands)
        i = idx.reshape(-1).astype(jnp.int32)
        return stacked[i, jnp.arange(stacked.shape[1])]

    return dispatch(fn, index, *inputs, nondiff_args=(0,),
                    name="multiplex")


register_direct("multiplex", multiplex)


@register("index_sample", nondiff_args=(1,))
def index_sample(x, index):
    """Per-row gather (reference tensor/search.py index_sample):
    x [B, N], index [B, M] -> out[b, m] = x[b, index[b, m]]."""
    return jnp.take_along_axis(x, index.astype(jnp.int32), -1)


@register("increment")
def increment(x, value=1.0):
    return x + jnp.asarray(value, x.dtype)


@register("shard_index", nondiff_args=())
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """Re-map global ids to shard-local ids (reference
    tensor/manipulation.py shard_index)."""
    size = (index_num + nshards - 1) // nshards
    lo = shard_id * size
    inside = (input >= lo) & (input < lo + size)
    return jnp.where(inside, input - lo, ignore_value)


@register("scatter_nd", nondiff_args=(0,))
def scatter_nd(index, updates, shape):
    out = jnp.zeros(list(shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@register("reverse", method=True)
def reverse(x, axis):
    axes = [axis] if isinstance(axis, int) else list(axis)
    return jnp.flip(x, axes)


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference tensor/math.py add_n)."""
    from ..core.tensor import dispatch
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return dispatch(lambda *vs: sum(vs[1:], vs[0]), *inputs, name="add_n")


_rd("add_n", add_n)


@register("is_empty")
def is_empty(x):
    return jnp.asarray(x.size == 0)


@register("shape", nondiff_args=(0,))
def shape(x):
    return jnp.asarray(x.shape, jnp.int32)


@register("broadcast_shape", nondiff_args=(0, 1))
def _broadcast_shape_op(x_shape, y_shape):
    return jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape))
