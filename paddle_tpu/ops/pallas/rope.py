"""Rotary position embedding.

Reference: composed in Python in the snapshot (SURVEY §2.4 — the dedicated
`fused_rotary_position_embedding` CUDA kernel landed later upstream). On TPU
the rotate+mul fuses into neighbouring matmuls under XLA, so the jnp
composition below *is* the fused kernel; a Pallas version only pays off fused
into flash-attention's Q/K load, which is an M4+ item.
"""
import jax.numpy as jnp


def available() -> bool:
    return True


def precompute_freqs(head_dim, max_seq_len, theta=10000.0, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [S, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin, position_ids=None):
    """x: [B, S, H, D]; cos/sin: [S_max, D/2] (neox / llama interleave-half).

    PT_ROPE_PALLAS=1 routes through the Pallas kernel on TPU (opt-in
    pending an on-chip A/B; the XLA-fused jnp path is the measured
    default)."""
    import os
    if (position_ids is None and os.environ.get("PT_ROPE_PALLAS") == "1"
            and x.ndim == 4):
        from .flash_attention import on_tpu
        if on_tpu():
            return apply_rotary_pallas(x, cos, sin)
    return _apply_rotary_jnp(x, cos, sin, position_ids)


def _apply_rotary_jnp(x, cos, sin, position_ids=None):
    seq = x.shape[1]
    if position_ids is not None:
        c = jnp.take(cos, position_ids, axis=0)     # [B, S, D/2]
        s = jnp.take(sin, position_ids, axis=0)
        c = c[:, :, None, :]
        s = s[:, :, None, :]
    else:
        c = cos[None, :seq, None, :]
        s = sin[None, :seq, None, :]
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity."""
    outs = [apply_rotary(q, cos, sin, position_ids),
            apply_rotary(k, cos, sin, position_ids)]
    outs.append(v if v is None else v)
    return tuple(outs)


# ------------------------------------------------- Pallas kernel variant
# (SURVEY §2.4 "rotary embedding -> Pallas rope"). The jnp composition
# above stays the default path — XLA fuses it into the surrounding
# matmuls, and the measured bench numbers are against it; the kernel is
# opted in via PT_ROPE_PALLAS=1 (or apply_rotary_pallas directly) pending
# an on-chip A/B.


def apply_rotary_pallas(x, cos, sin, block_s=512, interpret=False):
    """Pallas rope: x [B, S, H, D] processed as [(B*H), S, D] row blocks,
    cos/sin staged per sequence block in VMEM."""
    b, seq, h, d = x.shape
    d2 = d // 2
    bs = min(block_s, seq)
    if seq % bs or seq > cos.shape[0]:
        # ragged length, or seq beyond the precomputed table (the jnp
        # path fails loudly on the latter; Pallas would silently clamp)
        return _apply_rotary_jnp(x, cos, sin)
    xt = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, seq, d)
    grid = (b * h, seq // bs)
    out = _rope_call(xt, cos[:seq], sin[:seq], bs, d, d2, grid, interpret)
    return jnp.transpose(out.reshape(b, h, seq, d), (0, 2, 1, 3))


def _rope_call(xt, c, s, bs, d, d2, grid, interpret):
    import jax
    from jax.experimental import pallas as pl

    def kernel(x_ref, cos_ref, sin_ref, o_ref):
        x = x_ref[0]
        cc = cos_ref[...]
        ss = sin_ref[...]
        x1 = x[:, :d2]
        x2 = x[:, d2:]
        o_ref[0, :, :d2] = (x1 * cc - x2 * ss).astype(o_ref.dtype)
        o_ref[0, :, d2:] = (x2 * cc + x1 * ss).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bs, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((bs, d2), lambda i, j: (j, 0)),
            pl.BlockSpec((bs, d2), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
        interpret=interpret,
    )(xt, c, s)
