"""Rotary position embedding.

Reference: composed in Python in the snapshot (SURVEY §2.4 — the dedicated
`fused_rotary_position_embedding` CUDA kernel landed later upstream). On TPU
the rotate+mul fuses into neighbouring matmuls under XLA, so the jnp
composition below *is* the fused kernel; a Pallas version only pays off fused
into flash-attention's Q/K load, which is an M4+ item.
"""
import jax.numpy as jnp


def available() -> bool:
    return True


def precompute_freqs(head_dim, max_seq_len, theta=10000.0, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [S, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin, position_ids=None):
    """x: [B, S, H, D]; cos/sin: [S_max, D/2] (neox / llama interleave-half)."""
    seq = x.shape[1]
    if position_ids is not None:
        c = jnp.take(cos, position_ids, axis=0)     # [B, S, D/2]
        s = jnp.take(sin, position_ids, axis=0)
        c = c[:, :, None, :]
        s = s[:, :, None, :]
    else:
        c = cos[None, :seq, None, :]
        s = sin[None, :seq, None, :]
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity."""
    outs = [apply_rotary(q, cos, sin, position_ids),
            apply_rotary(k, cos, sin, position_ids)]
    outs.append(v if v is None else v)
    return tuple(outs)
