"""Pallas fused GEMM + bias + activation epilogue (TPU).

Reference capability: cublasLt epilogue fusion —
paddle/fluid/operators/fused/fused_gemm_epilogue_op.cu (+ cublaslt.h,
attn_gemm.h), exposed as fused_linear/fused_linear_activation
(python/paddle/incubate/nn/functional/fused_matmul_bias.py).

TPU-native design: a blocked matmul on the MXU whose epilogue (bias add +
gelu/relu) runs in VMEM right after the K-loop accumulation — the bias/
activation never round-trips through HBM. The backward is expressed as
two more fused GEMMs (dx = dz' @ W^T, dW = x^T @ dz') plus a bias-grad
row reduction, where dz' = dz * act'(pre) recomputed from the saved
pre-activation-free inputs (custom_vjp, remat style).

XLA usually fuses simple epilogues by itself; this kernel exists for the
cases it does not (relu_grad/gelu_grad recompute chains) and for API
parity. `fused_gemm_epilogue(..., use_pallas=False)` falls back to the
jnp composition, which XLA fuses on any backend.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params

DEFAULT_BM = 256
DEFAULT_BN = 256
DEFAULT_BK = 512

__all__ = ["fused_gemm_epilogue"]


def _act(z, activation):
    if activation == "relu":
        return jnp.maximum(z, 0.0)
    if activation == "gelu":
        return jax.nn.gelu(z, approximate=True)
    return z


def _fit(b, n):
    while b > 128 and n % b != 0:
        b //= 2
    return min(b, n)


def _mm_kernel(x_ref, w_ref, b_ref, o_ref, acc_scr, *, nk, activation,
               has_bias):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        z = acc_scr[:]
        if has_bias:
            z = z + b_ref[...].astype(jnp.float32)   # [1, bn] broadcasts
        o_ref[...] = _act(z, activation).astype(o_ref.dtype)


def _gemm_epilogue_pallas(x, w, bias, activation, interpret=False):
    """x: [M, K], w: [K, N], bias: [N] or None -> act(x@w + bias)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    n = w.shape[1]
    bm, bn, bk = _fit(DEFAULT_BM, m), _fit(DEFAULT_BN, n), _fit(
        DEFAULT_BK, k)
    grid = (m // bm, n // bn, k // bk)
    # uniform kernel arity: a missing bias becomes a zeros row (one [1,N]
    # VMEM read per output tile — negligible against the K loop)
    b_row = (bias if bias is not None
             else jnp.zeros((n,), x.dtype)).reshape(1, n)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
        pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
    ]
    args = [x, w, b_row]
    kernel = functools.partial(_mm_kernel, nk=grid[2],
                               activation=activation, has_bias=True)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


def _ref(x, w, bias, activation):
    z = x @ w
    if bias is not None:
        z = z + bias
    return _act(z.astype(jnp.float32), activation).astype(x.dtype)


def _pallas_ok(x, w):
    m, k = x.shape
    n = w.shape[1]
    return (on_tpu() and m % _fit(DEFAULT_BM, m) == 0
            and n % _fit(DEFAULT_BN, n) == 0
            and k % _fit(DEFAULT_BK, k) == 0
            and min(m, n, k) >= 128)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_gemm_epilogue(x, w, bias, activation="none"):
    """act(x @ w + bias); x [.., K] flattened to 2-D internally."""
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if _pallas_ok(x2, w):
        out = _gemm_epilogue_pallas(x2, w, bias, activation)
    else:
        out = _ref(x2, w, bias, activation)
    return out.reshape(lead + (w.shape[1],))


def _fge_fwd(x, w, bias, activation):
    return fused_gemm_epilogue(x, w, bias, activation), (x, w, bias)


def _fge_bwd(activation, res, g):
    x, w, bias = res
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    g2 = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
    if activation != "none":
        # recompute pre-activation once; scale the cotangent by act'(z)
        z = x2 @ w.astype(jnp.float32)
        if bias is not None:
            z = z + bias.astype(jnp.float32)
        _, dact = jax.vjp(lambda t: _act(t, activation), z)
        (g2,) = dact(g2)
    dx = (g2 @ w.astype(jnp.float32).T).astype(x.dtype).reshape(x.shape)
    dw = (x2.T @ g2).astype(w.dtype)
    db = g2.sum(0).astype(bias.dtype) if bias is not None else None
    return dx, dw, db


fused_gemm_epilogue.defvjp(_fge_fwd, _fge_bwd)
