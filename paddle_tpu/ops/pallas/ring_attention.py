"""Ring attention over the "sp" (sequence/context parallel) mesh axis.

Capability the reference LACKS (SURVEY §5.7: no sequence/context
parallelism in the snapshot) but the north star requires for long-context.
TPU-native design: sequence is sharded over "sp"; each step every rank
attends its local Q block against the K/V block it currently holds, merges
with running online-softmax stats, then `ppermute`s K/V around the ring so
compute overlaps the neighbour-to-neighbour ICI transfer. Expressed as a
`lax.scan` so reverse-mode AD yields the reverse ring for the backward pass
automatically.

Used inside shard_map (parallel/sp.py wires it into models); single-rank
call degrades to ordinary causal attention.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attn(q, k, v, sm_scale, mask=None):
    """One blockwise attention contribution with stats.

    q: [B,H,Sq,D], k/v: [B,H,Sk,D] -> (numer [B,H,Sq,D], m, l).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # [B,H,Sq]
    # avoid -inf - -inf
    m_safe = jnp.maximum(m, NEG_INF)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    numer = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return numer, m_safe, l


def ring_attention(q, k, v, axis_name="sp", causal=True, sm_scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D] inside shard_map over
    `axis_name`. Returns local attention output [B, H, S_local, D]."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]  # kv travels to next rank

    def seq_mask(src_rank):
        """Causal mask for local q rows vs kv from src_rank."""
        if not causal:
            return None
        q_pos = my * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        k_pos = src_rank * sq + jax.lax.broadcasted_iota(jnp.int32,
                                                         (sq, sq), 1)
        return (q_pos >= k_pos)[None, None]

    def step(carry, i):
        kv, acc, m_run, l_run = carry
        k_i, v_i = kv
        # kv currently held originated at rank (my - i) mod n
        src = (my - i) % n
        numer, m_blk, l_blk = _block_attn(q, k_i, v_i, sm_scale,
                                          seq_mask(src))
        m_new = jnp.maximum(m_run, m_blk)
        c_run = jnp.exp(m_run - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        acc = acc * c_run[..., None] + numer * c_blk[..., None]
        l_new = l_run * c_run + l_blk * c_blk
        k_n = jax.lax.ppermute(k_i, axis_name, perm)
        v_n = jax.lax.ppermute(v_i, axis_name, perm)
        return ((k_n, v_n), acc, m_new, l_new), None

    b, h, _, d = q.shape
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (kv_f, acc, m_f, l_f), _ = jax.lax.scan(
        step, ((k, v), acc0, m0, l0), jnp.arange(n))
    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
    return (acc / l_safe[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", causal=True, sm_scale=None,
                      attn_fn=None):
    """DeepSpeed-Ulysses alternative: all_to_all heads<->sequence so each
    rank holds ALL tokens for H/n heads, runs full (flash) attention
    locally, then all_to_alls back. Needs heads % axis_size == 0."""
    n = jax.lax.axis_size(axis_name)
    # [B, H, S_loc, D] -> gather seq, split heads
    q_ = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    k_ = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    v_ = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    if attn_fn is None:
        from .flash_attention import _ref_attention
        if sm_scale is None:
            sm_scale = 1.0 / math.sqrt(q.shape[-1])
        out = _ref_attention(q_, k_, v_, sm_scale, causal)
    else:
        out = attn_fn(q_, k_, v_)
    # back: split seq, gather heads
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
