"""Ring attention over the "sp" (sequence/context parallel) mesh axis.

Capability the reference LACKS (SURVEY §5.7: no sequence/context
parallelism in the snapshot) but the north star requires for long-context.
TPU-native design: sequence is sharded over "sp"; each step every rank
attends its local Q block against the K/V block it currently holds, merges
with running online-softmax stats, then `ppermute`s K/V around the ring so
compute overlaps the neighbour-to-neighbour ICI transfer. Expressed as a
`lax.scan` so reverse-mode AD yields the reverse ring for the backward pass
automatically.

Used inside shard_map (parallel/sp.py wires it into models); single-rank
call degrades to ordinary causal attention.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from ..._compat import axis_size as _axis_size

NEG_INF = -1e30


def _block_attn(q, k, v, sm_scale, mask=None):
    """One blockwise attention contribution with stats.

    q: [B,H,Sq,D], k/v: [B,H,Sk,D] -> (numer [B,H,Sq,D], m, l).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # [B,H,Sq]
    # avoid -inf - -inf
    m_safe = jnp.maximum(m, NEG_INF)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    numer = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return numer, m_safe, l


def ring_attention(q, k, v, axis_name="sp", causal=True, sm_scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D] inside shard_map over
    `axis_name`. Returns local attention output [B, H, S_local, D].

    Each ring step runs the Pallas flash kernel (XLA reference off-TPU)
    on the KV block currently held and merges (o, lse) pairs with
    logaddexp weights — the flash backward consumes the lse cotangent
    exactly (flash_attention.py _fwl_bwd), so the whole ring
    differentiates through the fused kernel. Causal steps dispatch per
    block origin: diagonal → causal kernel, below → full kernel, above →
    skipped entirely (no FLOPs for fully-masked tiles)."""
    from .flash_attention import flash_attention_with_lse

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    # GQA: permute the RAW kv shards (ICI bytes stay at the kv-head
    # size); repeat to the query head count only inside each step
    rep = h // k.shape[1]
    assert h % k.shape[1] == 0, (h, k.shape[1])
    perm = [(i, (i + 1) % n) for i in range(n)]  # kv travels to next rank

    def step(carry, i):
        (k_i, v_i), o_run, lse_run = carry
        src = (my - i) % n  # rank where the held kv block originated
        k_r = jnp.repeat(k_i, rep, axis=1) if rep > 1 else k_i
        v_r = jnp.repeat(v_i, rep, axis=1) if rep > 1 else v_i

        def full(_):
            return flash_attention_with_lse(q, k_r, v_r, sm_scale, False)

        def diag(_):
            return flash_attention_with_lse(q, k_r, v_r, sm_scale, True)

        def masked(_):
            return (jnp.zeros((b, h, sq, d), q.dtype),
                    jnp.full((b, h, sq), NEG_INF, jnp.float32))

        if causal:
            # 0: src < my (full), 1: src == my (diagonal), 2: src > my
            case = jnp.where(src == my, 1, jnp.where(src > my, 2, 0))
            o_blk, lse_blk = jax.lax.switch(case, [full, diag, masked],
                                            None)
        else:
            o_blk, lse_blk = full(None)

        lse_new = jnp.logaddexp(lse_run, lse_blk)
        w_run = jnp.exp(lse_run - lse_new)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new)[..., None]
        o_new = o_run * w_run + o_blk.astype(jnp.float32) * w_blk
        k_n = jax.lax.ppermute(k_i, axis_name, perm)
        v_n = jax.lax.ppermute(v_i, axis_name, perm)
        return ((k_n, v_n), o_new, lse_new), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    (_, o_f, lse_f), _ = jax.lax.scan(step, ((k, v), o0, lse0),
                                      jnp.arange(n))
    return o_f.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", causal=True, sm_scale=None,
                      attn_fn=None):
    """DeepSpeed-Ulysses alternative: all_to_all heads<->sequence so each
    rank holds ALL tokens for H/n heads, runs full (flash) attention
    locally, then all_to_alls back. Needs heads % axis_size == 0."""
    n = _axis_size(axis_name)
    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses_attention: local heads {q.shape[1]} not divisible "
            f"by {axis_name!r} size {n} — the heads<->sequence "
            f"all_to_all needs heads % sp == 0 (use ring attention or "
            f"reduce the sp degree)")
    # [B, H, S_loc, D] -> gather seq, split heads
    q_ = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    k_ = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    v_ = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    if attn_fn is None:
        # default to the Pallas flash kernel (auto-falls back to the
        # reference composition off-TPU / on non-block-aligned shapes)
        from .flash_attention import _flash
        if sm_scale is None:
            sm_scale = 1.0 / math.sqrt(q.shape[-1])
        out = _flash(q_, k_, v_, sm_scale, causal)
    else:
        out = attn_fn(q_, k_, v_)
    # back: split seq, gather heads
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
