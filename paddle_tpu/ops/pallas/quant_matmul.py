"""Pallas int8×int8→int32 blocked matmul with fused dequantize.

Reference capability: the cutlass-backed int8 kernels behind the PTQ
`convert` inference path (python/paddle/quantization/, cmake/external/
cutlass.cmake). TPU-native: the MXU multiplies int8 at 2× bf16
throughput; this kernel keeps A/B tiles int8 in VMEM, accumulates int32
on the MXU, and applies the per-tensor (x) / per-channel (w) scales in
the epilogue — one pass, no int32 matrix in HBM.

`quantized_matmul(x_i8, w_i8, sx, sw)` ≈ (x_i8 * sx) @ (w_i8 * sw).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .flash_attention import on_tpu
from . import tpu_compiler_params

DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 256


def available() -> bool:
    return on_tpu()


def _qmm_kernel(x_ref, w_ref, sx_ref, sw_ref, o_ref, acc_scr, *, nk):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(ki == nk - 1)
    def _done():
        # fused dequant epilogue: per-tensor x scale, per-channel w scale
        o_ref[...] = (acc_scr[...].astype(jnp.float32)
                      * sx_ref[0] * sw_ref[...][None, :]).astype(o_ref.dtype)


def quantized_matmul(x, w, scale_x, scale_w, block_m=DEFAULT_BLOCK_M,
                     block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
                     interpret=False, out_dtype=jnp.float32):
    """x: int8 [M, K]; w: int8 [K, N]; scale_x scalar; scale_w scalar or
    [N]. Returns dequantized [M, N] in ``out_dtype``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    n = w.shape[1]
    bm = min(block_m, m)
    bn = min(block_n, n)
    bk = min(block_k, k)
    sw = jnp.broadcast_to(jnp.asarray(scale_w, jnp.float32), (n,))
    sx = jnp.asarray(scale_x, jnp.float32).reshape(1)
    if m % bm or n % bn or k % bk:
        # ragged shapes: plain XLA path (still int32 MXU accumulate)
        acc = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return (acc.astype(jnp.float32) * sx * sw[None, :]).astype(out_dtype)

    grid = (m // bm, n // bn, k // bk)
    return pl.pallas_call(
        functools.partial(_qmm_kernel, nk=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1,), lambda i, j, kk: (0,)),
            pl.BlockSpec((bn,), lambda i, j, kk: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, w, sx, sw)


def quantize_tensor(x, per_channel_axis=None):
    """Symmetric int8 quantization helper: returns (q_int8, scale)."""
    if per_channel_axis is None:
        amax = jnp.max(jnp.abs(x))
        scale = amax / 127.0 + 1e-12
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, scale
    axes = tuple(i for i in range(x.ndim) if i != per_channel_axis)
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    scale = amax / 127.0 + 1e-12
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.reshape(-1)
