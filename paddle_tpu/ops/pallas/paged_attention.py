"""Pallas ragged paged-attention decode kernel (TPU).

Serving-side analogue of "Ragged Paged Attention" (PAPERS.md): instead of
one dense per-slot KV buffer ``[slots, max_cache_len, heads, dim]`` —
whose HBM footprint and decode read bandwidth scale with the CONFIGURED
cache length — K/V live in a global page pool
``[num_pages, page_size, kv_heads, head_dim]`` and each decode slot owns
an ordered list of page ids (its block table). Decode attention gathers
pages through the block table, masks by the slot's ACTUAL length, and
early-exits pages wholly beyond it, so both memory and bandwidth scale
with real tokens.

Kernel shape: one query token per slot (decode step). Grid is
``(slots, pages_per_slot)`` with the page axis innermost ("arbitrary"),
accumulating an online softmax in VMEM scratch exactly like
``flash_attention._fwd_kernel``; the block table and per-slot lengths
ride ``PrefetchScalarGridSpec`` scalar prefetch so the page DMA for grid
step ``(s, p)`` is issued from ``block_tables[s, p]`` before the body
runs. GQA is handled in-kernel (query-head groups attend to their kv
head) so the pool stores kv heads unrepeated.

The XLA fallback (`_ref_paged_attention`) gathers pages into the
contiguous ``[slot, pages*page_size, ...]`` frame and then mirrors
``models/generation._cached_attend`` operation-for-operation, which makes
the paged decode path BIT-IDENTICAL to the dense one whenever
``pages_per_slot * page_size == max_cache_len`` (positions beyond a
slot's length hit -1e30 in both, contributing exactly 0.0f to softmax
and output). CPU tests run the Pallas kernel via ``interpret=True``.
"""
import functools
import math

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params

NEG_INF = -1e30

__all__ = ["paged_attention", "available"]


def available() -> bool:
    return on_tpu()


# ----------------------------------------------------------------- kernel


def _paged_attn_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, page_size, pages_per_slot,
                       kv_heads, rep, sm_scale):
    """Grid (slots, pages_per_slot); one query row per slot.

    q_ref  [1, nh, hd]       this slot's query token
    k_ref  [1, page_size, kvh, hd]   the page block_tables[s, p] points at
    len_ref[s]               valid KV tokens for slot s (ragged lengths)
    Scratch m/l/acc carry the online softmax across the page axis.
    """
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[s]

    # early-exit: a page whose first position is past the slot's length
    # holds no valid tokens — skip all compute for it
    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [nh, hd]
        k = k_ref[0].astype(jnp.float32)            # [pg, kvh, hd]
        v = v_ref[0].astype(jnp.float32)
        nh = q.shape[0]
        m_prev = m_scr[:]                           # [nh, 128]
        l_prev = l_scr[:]

        # ragged masking: position p*pg + j is valid iff < length
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (nh, page_size), 1)
        valid = col < length

        # per-kv-head-group contractions keep the MXU ops unbatched
        logits = []
        for g in range(kv_heads):
            qg = q[g * rep:(g + 1) * rep]           # [rep, hd]
            kg = k[:, g]                            # [pg, hd]
            logits.append(jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
        s_log = jnp.concatenate(logits, axis=0) * sm_scale   # [nh, pg]
        s_log = jnp.where(valid, s_log, NEG_INF)

        m_cur = jnp.max(s_log, axis=-1, keepdims=True)       # [nh, 1]
        m_new = jnp.maximum(m_prev[:, :1], m_cur)
        corr = jnp.exp(m_prev[:, :1] - m_new)                # [nh, 1]
        pexp = jnp.exp(s_log - m_new)                        # [nh, pg]
        pexp = jnp.where(valid, pexp, 0.0)
        l_scr[:] = jnp.broadcast_to(
            corr * l_prev[:, :1] + jnp.sum(pexp, -1, keepdims=True),
            l_scr.shape)
        pv = []
        for g in range(kv_heads):
            pv.append(jax.lax.dot_general(
                pexp[g * rep:(g + 1) * rep], v[:, g],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))         # [rep, hd]
        acc_scr[:] = acc_scr[:] * corr + jnp.concatenate(pv, axis=0)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)              # empty slot guard
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, block_tables, lengths,
                            sm_scale, interpret=False):
    """q [S, nh, hd]; pages [P, pg, kvh, hd]; block_tables [S, maxp] int32
    (unused tail entries must hold any VALID page id, e.g. 0); lengths
    [S] int32. Returns [S, nh, hd]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    maxp = block_tables.shape[1]
    rep = nh // kvh
    if nh % kvh:
        raise ValueError(f"query heads ({nh}) must be a multiple of kv "
                         f"heads ({kvh})")

    flat_bt = block_tables.reshape(-1).astype(jnp.int32)
    kernel = functools.partial(
        _paged_attn_kernel, page_size=pg, pages_per_slot=maxp,
        kv_heads=kvh, rep=rep, sm_scale=sm_scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, maxp),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda s, p, bt, ln: (s, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda s, p, bt, ln: (bt[s * maxp + p], 0, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda s, p, bt, ln: (bt[s * maxp + p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd), lambda s, p, bt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(flat_bt, lengths.astype(jnp.int32), q, k_pages, v_pages)


# ------------------------------------------------- mesh-sharded kernel path


def kv_head_shards(mesh, num_kv_heads, num_heads=None, axis="mp"):
    """Ways an attention launch splits over ``mesh``'s ``axis`` on the
    kv-head dimension: the axis size when it divides the kv heads (and
    the query heads, which follows for any integral GQA ratio), else 1.
    1 means "launch replicated" — the caller's divisibility fallback,
    matching the pool placement rule in ``models/generation``."""
    if mesh is None:
        return 1
    size = int(dict(mesh.shape).get(axis, 1))
    if size <= 1 or num_kv_heads % size:
        return 1
    if num_heads is not None and num_heads % size:
        return 1
    return size


def _paged_attention_sharded(q, k_pages, v_pages, block_tables, lengths,
                             sm_scale, mesh, axis, interpret):
    """Per-shard Pallas launches over the mesh's ``axis``: the page
    pools arrive sharded on their kv-head dim, q splits into the
    matching query-head groups (a GQA group never straddles a shard —
    consecutive head blocks keep each kv head with its own rep query
    heads), the block table and lengths ride replicated, and the
    out_spec's head-axis concatenation IS the attention all-gather
    GSPMD would insert on the fallback path. XLA cannot partition a
    custom call, so the kernel path must shard_map itself; returns None
    when the head counts don't divide the axis — the caller then runs
    one replicated launch."""
    from jax.sharding import PartitionSpec as P

    from ..._compat import shard_map
    if kv_head_shards(mesh, k_pages.shape[2], q.shape[1], axis) <= 1:
        return None
    fn = functools.partial(_paged_attention_pallas, sm_scale=sm_scale,
                           interpret=interpret)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, axis, None), P(None, None, axis, None),
                  P(None, None, axis, None), P(None, None), P(None)),
        out_specs=P(None, axis, None), check_vma=False,
    )(q, k_pages, v_pages, block_tables, lengths)


# ------------------------------------------------------ XLA reference path


def _ref_paged_attention(q, k_pages, v_pages, block_tables, lengths,
                         sm_scale):
    """Gather-through-block-table reference. Mirrors the dense decode
    attention (`generation._cached_attend` at s=1) op-for-op so the paged
    server emits bit-identical tokens to the dense backend on every
    platform: valid positions carry the exact cached values, positions at
    or beyond ``lengths`` are masked to -1e30 before the same f32 softmax
    (contributing exactly 0.0), and the einsum specs match."""
    S, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    maxp = block_tables.shape[1]
    T = maxp * pg
    k = k_pages[block_tables].reshape(S, T, kvh, hd)
    v = v_pages[block_tables].reshape(S, T, kvh, hd)
    rep = nh // kvh
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qb = q[:, None]                                        # [S, 1, nh, hd]
    logits = jnp.einsum("bsnd,btnd->bnst", qb, k) * sm_scale
    pos = jnp.arange(T)
    ok = pos[None, None] < lengths[:, None, None]          # [S, 1, T]
    logits = jnp.where(ok[:, None], logits.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, v)[:, 0]


# --------------------------------------------------------------- public


def paged_attention(q, k_pages, v_pages, block_tables, lengths,
                    sm_scale=None, interpret=False, mesh=None):
    """Ragged paged-attention decode step.

    q            [slots, num_heads, head_dim]   one query token per slot
    k_pages      [num_pages, page_size, kv_heads, head_dim]  global pool
    v_pages      same shape as ``k_pages``
    block_tables [slots, pages_per_slot] int32  page ids, in position
                 order; entries past a slot's allocation must hold a
                 valid id (the manager fills them with 0)
    lengths      [slots] int32  valid KV tokens per slot (ragged)
    mesh         optional ``jax.sharding.Mesh`` whose ``mp`` axis the
                 page pools are sharded over on their kv-head dim
                 (sharded paged serving): the Pallas path then runs one
                 launch PER SHARD via shard_map — each shard reads only
                 its resident pool slice, block tables replicated —
                 and the head-axis restitch is the attention
                 all-gather. Ignored on the XLA fallback, where GSPMD
                 partitions the gather/einsum composition from the
                 pool's input sharding directly.

    Returns [slots, num_heads, head_dim]. Runs the Pallas kernel on TPU
    (or under ``interpret=True`` anywhere); elsewhere the gather-based
    XLA composition, which is bit-identical to the dense decode path.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if available() or interpret:
        if mesh is not None:
            out = _paged_attention_sharded(
                q, k_pages, v_pages, block_tables, lengths, sm_scale,
                mesh, "mp", interpret)
            if out is not None:
                return out
        return _paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                       lengths, sm_scale,
                                       interpret=interpret)
    return _ref_paged_attention(q, k_pages, v_pages, block_tables,
                                lengths, sm_scale)
