"""Pallas RMSNorm kernel (+ custom VJP).

Reference equivalent: rms_norm CUDA kernel named in the north star; in the
reference snapshot RMSNorm is Python-composed (SURVEY §2.4). Here: one fused
VMEM pass per row-block — x is read once, normalized on the VPU, scaled by
the (broadcast) weight; backward recomputes the rstd instead of storing
activations (bandwidth-bound op, recompute is free).
"""
import functools

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params


def available() -> bool:
    return on_tpu()


def _ref_fwd(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return (xf * rstd * w.astype(jnp.float32)).astype(x.dtype)


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pick_block_rows(rows, block_rows):
    br = min(block_rows, rows)
    while rows % br:
        br //= 2
    return max(br, 1)


def _pallas_fwd(x, w, eps, block_rows=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    br = _pick_block_rows(rows, block_rows)
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(x2, w)
    return out.reshape(orig_shape)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, dw_scr, *, eps,
                nblocks):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)          # [1, d]
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = x * rstd
    gw = g * w
    dx = rstd * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _finalize():
        dw_ref[:] = dw_scr[:]


def _pallas_bwd(x, w, g, eps, block_rows=256, interpret=False):
    """Single fused pass: reads x/g once per row block, emits dx and the
    accumulated dw (reference capability: dedicated rms_norm grad kernel;
    XLA's fusion is close for this bandwidth-bound op — kept because the
    fused dw accumulation avoids a second x read)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    g2 = g.reshape(rows, d)
    br = _pick_block_rows(rows, block_rows)
    nblocks = rows // br
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, nblocks=nblocks),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, w.reshape(1, d), g2)
    return dx.reshape(orig_shape), dw.reshape(d).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, w, eps=1e-6):
    if available():
        return _pallas_fwd(x, w, eps)
    return _ref_fwd(x, w, eps)


def _fwd(x, w, eps):
    return rms_norm(x, w, eps), (x, w)


def _ref_bwd(x, w, g, eps):
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xf * rstd
    gw = gf * wf
    d = x.shape[-1]
    dx = rstd * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum((gf * xhat).reshape(-1, d), axis=0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


def _bwd(eps, res, g):
    x, w = res
    if available():
        return _pallas_bwd(x, w, g, eps)
    return _ref_bwd(x, w, g, eps)


rms_norm.defvjp(_fwd, _bwd)
