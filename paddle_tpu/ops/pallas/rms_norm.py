"""Pallas RMSNorm kernel (+ custom VJP).

Reference equivalent: rms_norm CUDA kernel named in the north star; in the
reference snapshot RMSNorm is Python-composed (SURVEY §2.4). Here: one fused
VMEM pass per row-block — x is read once, normalized on the VPU, scaled by
the (broadcast) weight; backward recomputes the rstd instead of storing
activations (bandwidth-bound op, recompute is free).
"""
import functools

import jax
import jax.numpy as jnp

from . import on_tpu


def available() -> bool:
    return on_tpu()


def _ref_fwd(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return (xf * rstd * w.astype(jnp.float32)).astype(x.dtype)


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pallas_fwd(x, w, eps, block_rows=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    br = min(block_rows, rows)
    while rows % br:
        br //= 2
    br = max(br, 1)
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(x2, w)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, w, eps=1e-6):
    if available():
        return _pallas_fwd(x, w, eps)
    return _ref_fwd(x, w, eps)


def _fwd(x, w, eps):
    return rms_norm(x, w, eps), (x, w)


def _bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xf * rstd
    gw = gf * wf
    d = x.shape[-1]
    dx = rstd * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum((gf * xhat).reshape(-1, d), axis=0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_fwd, _bwd)
