"""Pallas TPU kernel pack.

TPU-native replacement for the reference's fused CUDA kernels
(paddle/phi/kernels/gpu/flash_attn_kernel.cu, fused_*_op.cu — see SURVEY §2.4).
Each module exposes `available()` (True when running on a TPU backend) and
falls back to an equivalent XLA composition elsewhere, so the same model code
runs in CPU tests and on hardware.
"""
import functools

import jax


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def tpu_compiler_params(**kwargs):
    """JAX-version compat shim for the Mosaic compiler-params struct:
    newer JAX exposes ``pltpu.CompilerParams``, 0.4.x calls it
    ``pltpu.TPUCompilerParams``. Every Pallas kernel in this package
    builds its ``compiler_params`` through here."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
