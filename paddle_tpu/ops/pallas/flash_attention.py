"""Pallas flash attention (TPU).

Reference equivalent: paddle/phi/kernels/gpu/flash_attn_kernel.cu (dynloaded
libflashattn; python surface python/paddle/nn/functional/flash_attention.py:20).
TPU-native design: blockwise online-softmax forward entirely in VMEM with a
(B·H, Q-blocks, KV-blocks) grid — the KV axis is the innermost ("arbitrary")
grid dimension accumulating into VMEM scratch, so each Q block streams K/V
tiles through VMEM exactly once. Layout is paddle's [batch, seq, heads, dim];
internally [B,H,S,D].

Backward is a dedicated two-kernel Pallas pass (dq; dk+dv) from the saved
output + logsumexp, FlashAttention-2 style: delta = rowsum(do*o) is
precomputed, each kernel recomputes p = exp(s - lse) blockwise and
accumulates into VMEM scratch. Both kernels work in the transposed
[block_k, block_q] frame so lse/delta stay (1, block_q) row vectors
(no in-kernel transposes; contractions go through dot_general on the MXU)
and causal block skip prunes fully-masked tiles. Reference capability:
paddle/phi/kernels/gpu/flash_attn_grad_kernel.cu.
"""
import functools
import math

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params

# v5e-swept defaults (benchmarks/flash_block_sweep.py): 1024/1024 is
# 3.7x faster fwd and 4.5x fwd+bwd than 128/128; >1024 fails to compile
# (VMEM). Kernels clamp to the sequence length when shorter.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def available() -> bool:
    return on_tpu()


# --------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q, block_k,
                num_kv_blocks):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [block_q, d]
        k = k_ref[0].astype(jnp.float32)          # [block_k, d]
        v = v_ref[0].astype(jnp.float32)          # [block_k, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                           # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:]                          # [block_q, 128]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev[:, :1] - m_new[:, :1])   # [block_q,1]
        p = jnp.exp(s - m_new[:, :1])              # [block_q, block_k]
        l_new = corr * l_prev[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip fully-masked KV blocks above the diagonal
        pl.when(ki * block_k <= (qi + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        m_fin = m_scr[:]
        l_fin = l_scr[:]
        l = jnp.where(l_fin[:, :1] == 0.0, 1.0, l_fin[:, :1])
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_fin + jnp.log(jnp.maximum(l_fin, 1e-30))
                      ).astype(lse_ref.dtype)


def _flash_fwd_pallas(q, k, v, sm_scale, causal,
                      block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                      interpret=False):
    """q,k,v: [BH, S, D] (batch*heads flattened). Returns (o, lse[BH,S,128])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    nq = sq // block_q
    nk = sk // block_k
    grid = (bh, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kv_blocks=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


# -------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k,
                   num_kv_blocks):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [block_q, d]
        k = k_ref[0].astype(jnp.float32)            # [block_k, d]
        v = v_ref[0].astype(jnp.float32)            # [block_k, d]
        do = do_ref[0].astype(jnp.float32)          # [block_q, d]
        lse = lse_ref[0]                            # [1, block_q]
        delta = delta_ref[0]                        # [1, block_q]
        # transposed frame: st[kk, qq] = k·q * scale
        st = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        st = st * sm_scale                          # [block_k, block_q]
        pt = jnp.exp(st - lse)                      # exp(s - lse)^T
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            pt = jnp.where(q_pos >= k_pos, pt, 0.0)
        dpt = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dst = pt * (dpt - delta)                    # [block_k, block_q]
        # dq[qq, d] += ds[qq, kk] @ k[kk, d]  == dst^T @ k via dim-0 contract
        dq_scr[:] = dq_scr[:] + sm_scale * jax.lax.dot_general(
            dst, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(ki * block_k <= (qi + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k, num_q_blocks):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [block_q, d]
        k = k_ref[0].astype(jnp.float32)            # [block_k, d]
        v = v_ref[0].astype(jnp.float32)            # [block_k, d]
        do = do_ref[0].astype(jnp.float32)          # [block_q, d]
        lse = lse_ref[0]                            # [1, block_q]
        delta = delta_ref[0]                        # [1, block_q]
        st = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        st = st * sm_scale
        pt = jnp.exp(st - lse)                      # [block_k, block_q]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            pt = jnp.where(q_pos >= k_pos, pt, 0.0)
        # dv[kk, d] += p^T[kk, qq] @ do[qq, d]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pt, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dpt = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dst = pt * (dpt - delta)                    # [block_k, block_q]
        # dk[kk, d] += ds^T[kk, qq] @ q[qq, d]
        dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
            dst, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qi + 1) * block_q - 1 >= ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, sm_scale, causal,
                      block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                      interpret=False, dlse=None):
    """q,k,v,o,do: [BH, S, D]; lse: [BH, S]. Returns (dq, dk, dv).

    ``dlse``: optional cotangent of lse (ring-attention merge path). It
    folds into the row term: ds = p*(dp - delta + dlse), so we just pass
    delta' = delta - dlse to the kernels."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    nq = sq // block_q
    nk = sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # rows as [BH*nq, 1, block_q]: block == array dims on the last two
    # axes, which satisfies Mosaic's (8, 128) block-tiling constraint
    lse = lse.astype(jnp.float32).reshape(bh * nq, 1, block_q)
    delta = delta.reshape(bh * nq, 1, block_q)

    qkv_spec_q = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec_q = pl.BlockSpec((1, 1, block_q),
                              lambda b, i, j: (b * nq + i, 0, 0))
    kv_spec_q = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_kv_blocks=nk),
        grid=(bh, nq, nk),
        in_specs=[qkv_spec_q, kv_spec_q, kv_spec_q, qkv_spec_q,
                  row_spec_q, row_spec_q],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    qkv_spec_k = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    row_spec_k = pl.BlockSpec((1, 1, block_q),
                              lambda b, j, i: (b * nq + i, 0, 0))
    kv_spec_k = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq),
        grid=(bh, nk, nq),
        in_specs=[qkv_spec_k, kv_spec_k, kv_spec_k, qkv_spec_k,
                  row_spec_k, row_spec_k],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------- XLA reference path


def _ref_attention(q, k, v, sm_scale, causal):
    """[B,H,S,D] reference; used for CPU tests and as backward recompute."""
    return _ref_with_lse(q, k, v, sm_scale, causal)[0]


# --------------------------------------------------------------- public api


def _fit_block(pref, seq):
    """Largest power-of-two block <= pref that divides seq (>=128)."""
    b = min(pref, seq)
    while b > 128 and seq % b != 0:
        b //= 2
    return b


def _pallas_ok(q, k):
    """Pallas path requires whole blocks: seq lengths must be divisible
    by SOME supported block size (>=128) — the kernels then pick the
    largest fitting one, so e.g. seq 2560 runs with 512-blocks instead of
    falling back to the O(S^2)-memory XLA composition."""
    sq, sk = q.shape[2], k.shape[2]
    return (available() and sq % _fit_block(DEFAULT_BLOCK_Q, sq) == 0
            and sk % _fit_block(DEFAULT_BLOCK_K, sk) == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, sm_scale, causal):
    # q,k,v: [B,H,S,D]
    if _pallas_ok(q, k):
        b, h, s, d = q.shape
        o, _ = _flash_fwd_pallas(q.reshape(b * h, s, d),
                                 k.reshape(b * h, k.shape[2], d),
                                 v.reshape(b * h, v.shape[2], d),
                                 sm_scale, causal)
        return o.reshape(b, h, s, d)
    return _ref_attention(q, k, v, sm_scale, causal)


def _flash_fwd(q, k, v, sm_scale, causal):
    if _pallas_ok(q, k):
        b, h, s, d = q.shape
        o, lse = _flash_fwd_pallas(q.reshape(b * h, s, d),
                                   k.reshape(b * h, k.shape[2], d),
                                   v.reshape(b * h, v.shape[2], d),
                                   sm_scale, causal)
        return o.reshape(b, h, s, d), (q, k, v, o, lse)
    return _ref_attention(q, k, v, sm_scale, causal), (q, k, v, None, None)


def _flash_bwd(sm_scale, causal, res, g):
    q, k, v, o, lse = res
    if o is not None:
        b, h, s, d = q.shape
        sk = k.shape[2]
        dq, dk, dv = _flash_bwd_pallas(
            q.reshape(b * h, s, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), o, lse,
            g.reshape(b * h, s, d), sm_scale, causal)
        return (dq.reshape(b, h, s, d), dk.reshape(b, h, sk, d),
                dv.reshape(b, h, sk, d))
    _, vjp = jax.vjp(lambda q_, k_, v_: _ref_attention(q_, k_, v_, sm_scale,
                                                       causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------- (o, lse) variant for ring

def _ref_with_lse(q, k, v, sm_scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(q, k, v, sm_scale, causal):
    """[B,H,S,D] attention returning (o, lse[B,H,S]). The lse output is
    differentiable, which is what lets ring attention merge per-ring-step
    partial results (weights depend on lse) with exact gradients."""
    if _pallas_ok(q, k):
        b, h, s, d = q.shape
        sk = k.shape[2]
        o, lse = _flash_fwd_pallas(q.reshape(b * h, s, d),
                                   k.reshape(b * h, sk, d),
                                   v.reshape(b * h, sk, d),
                                   sm_scale, causal)
        return o.reshape(b, h, s, d), lse.reshape(b, h, s)
    return _ref_with_lse(q, k, v, sm_scale, causal)


def _fwl_fwd(q, k, v, sm_scale, causal):
    if _pallas_ok(q, k):
        b, h, s, d = q.shape
        sk = k.shape[2]
        o, lse = _flash_fwd_pallas(q.reshape(b * h, s, d),
                                   k.reshape(b * h, sk, d),
                                   v.reshape(b * h, sk, d),
                                   sm_scale, causal)
        return ((o.reshape(b, h, s, d), lse.reshape(b, h, s)),
                (q, k, v, o, lse))
    out = _ref_with_lse(q, k, v, sm_scale, causal)
    return out, (q, k, v, None, None)


def _fwl_bwd(sm_scale, causal, res, ct):
    q, k, v, o, lse = res
    do, dlse = ct
    if o is not None:
        b, h, s, d = q.shape
        sk = k.shape[2]
        dq, dk, dv = _flash_bwd_pallas(
            q.reshape(b * h, s, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), o, lse,
            do.reshape(b * h, s, d), sm_scale, causal,
            dlse=dlse.reshape(b * h, s))
        return (dq.reshape(b, h, s, d), dk.reshape(b, h, sk, d),
                dv.reshape(b, h, sk, d))
    _, vjp = jax.vjp(lambda a, b_, c: _ref_with_lse(a, b_, c, sm_scale,
                                                    causal), q, k, v)
    return vjp((do, dlse))


flash_attention_with_lse.defvjp(_fwl_fwd, _fwl_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """q,k,v: paddle layout [batch, seq, num_heads, head_dim]."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash(qt, kt, vt, sm_scale, causal)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None):
    """Same kernel, [batch, heads, seq, dim] layout (no transposes)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, sm_scale, causal)
