"""Pallas flash attention (TPU).

Reference equivalent: paddle/phi/kernels/gpu/flash_attn_kernel.cu (dynloaded
libflashattn; python surface python/paddle/nn/functional/flash_attention.py:20).
TPU-native design: blockwise online-softmax forward entirely in VMEM with a
(B·H, Q-blocks, KV-blocks) grid — the KV axis is the innermost ("arbitrary")
grid dimension accumulating into VMEM scratch, so each Q block streams K/V
tiles through VMEM exactly once. Layout is paddle's [batch, seq, heads, dim];
internally [B,H,S,D].

Backward currently differentiates a blockwise XLA recompute (O(S·block)
memory via lax.scan) — the dedicated Pallas backward kernel is the M4 perf
item. Forward returns the logsumexp needed for that backward.
"""
import functools
import math

import jax
import jax.numpy as jnp

from . import on_tpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def available() -> bool:
    return on_tpu()


# --------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q, block_k,
                num_kv_blocks):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [block_q, d]
        k = k_ref[0].astype(jnp.float32)          # [block_k, d]
        v = v_ref[0].astype(jnp.float32)          # [block_k, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                           # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:]                          # [block_q, 128]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev[:, :1] - m_new[:, :1])   # [block_q,1]
        p = jnp.exp(s - m_new[:, :1])              # [block_q, block_k]
        l_new = corr * l_prev[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip fully-masked KV blocks above the diagonal
        pl.when(ki * block_k <= (qi + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        m_fin = m_scr[:]
        l_fin = l_scr[:]
        l = jnp.where(l_fin[:, :1] == 0.0, 1.0, l_fin[:, :1])
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_fin + jnp.log(jnp.maximum(l_fin, 1e-30))
                      ).astype(lse_ref.dtype)


def _flash_fwd_pallas(q, k, v, sm_scale, causal,
                      block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                      interpret=False):
    """q,k,v: [BH, S, D] (batch*heads flattened). Returns (o, lse[BH,S,128])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    nq = sq // block_q
    nk = sk // block_k
    grid = (bh, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kv_blocks=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


# ----------------------------------------------------- XLA reference path


def _ref_attention(q, k, v, sm_scale, causal):
    """[B,H,S,D] reference; used for CPU tests and as backward recompute."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# --------------------------------------------------------------- public api


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, sm_scale, causal):
    # q,k,v: [B,H,S,D]
    if available():
        b, h, s, d = q.shape
        o, _ = _flash_fwd_pallas(q.reshape(b * h, s, d),
                                 k.reshape(b * h, k.shape[2], d),
                                 v.reshape(b * h, v.shape[2], d),
                                 sm_scale, causal)
        return o.reshape(b, h, s, d)
    return _ref_attention(q, k, v, sm_scale, causal)


def _flash_fwd(q, k, v, sm_scale, causal):
    return _flash(q, k, v, sm_scale, causal), (q, k, v)


def _flash_bwd(sm_scale, causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _ref_attention(q_, k_, v_, sm_scale,
                                                       causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """q,k,v: paddle layout [batch, seq, num_heads, head_dim]."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash(qt, kt, vt, sm_scale, causal)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None):
    """Same kernel, [batch, heads, seq, dim] layout (no transposes)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, sm_scale, causal)
