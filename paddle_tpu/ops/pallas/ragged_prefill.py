"""Pallas ragged prefill attention over the paged KV pool (TPU).

Prefill-side counterpart of ``paged_attention.py`` (PAPERS.md "Ragged
Paged Attention"): several variable-length prompt CHUNKS — one per
serving slot — are packed into a single ``[slots, chunk]`` launch and
attend causally over the global page pool through their slots' block
tables, each at its own prefix offset ``t0`` (an auto-prefix-cache hit
resumes at the first uncached token and attends over the already-cached
pages exactly like a decode step does). This is what lets the serving
scheduler run the prefill work of SEVERAL admissions as one device
dispatch, interleaved with decode ticks, with K/V written straight into
pool pages — no dense batch-1 cache detour.

Kernel shape: grid ``(slots, pages_per_slot)`` with the page axis
innermost ("arbitrary"), ``chunk`` query rows per slot, accumulating an
online softmax in VMEM scratch over the page axis like the decode
kernel — the scratch simply carries ``chunk * num_heads`` rows instead
of ``num_heads``. The block table and the per-slot ``t0``/last-valid
position ride ``PrefetchScalarGridSpec`` scalar prefetch, so a slot
whose chunk is empty this launch (``last < 0``, the scheduler's idle
sentinel) skips every page's compute, and trailing pages beyond a
slot's frontier early-exit.

The XLA fallback (``_ref_ragged_prefill``) gathers the pool through the
block table into the contiguous per-slot frame and then mirrors
``models/generation._cached_attend`` operation-for-operation (same
einsum specs, same -1e30 mask, same f32 softmax), which keeps ragged
prefill BIT-IDENTICAL to the dense batch-1 prefill path: a masked
position contributes exactly 0.0f in both, and XLA's row-wise matmul
results are stable across the batch/sequence shapes involved (asserted
by the parity suite, tests/test_ragged_prefill.py). CPU tests run the
Pallas kernel via ``interpret=True``.
"""
import functools
import math

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params
from .paged_attention import NEG_INF

__all__ = ["ragged_prefill_attention", "available"]

# query rows per kernel launch: scratch is (rows * num_heads)-tall in
# VMEM, so the public entry tiles wider chunks down to this
_QUERY_TILE = 8


def available() -> bool:
    return on_tpu()


# ----------------------------------------------------------------- kernel


def _ragged_prefill_kernel(bt_ref, t0_ref, last_ref, q_ref, k_ref, v_ref,
                           o_ref, m_scr, l_scr, acc_scr, *, page_size,
                           pages_per_slot, chunk, kv_heads, rep, sm_scale):
    """Grid (slots, pages_per_slot); ``chunk`` query rows per slot.

    q_ref  [1, chunk, nh, hd]       this slot's packed prompt chunk
    k_ref  [1, page_size, kvh, hd]  the page block_tables[s, p] points at
    t0_ref[s]   absolute position of the chunk's first row (prefix offset)
    last_ref[s] last position the chunk writes (t0 + take - 1); -1 for a
                slot with no prefill work this launch (all compute skipped)
    Scratch m/l/acc carry the online softmax across the page axis, one
    row per (chunk row, query head) pair.
    """
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    t0 = t0_ref[s]
    last = last_ref[s]
    nh = kv_heads * rep

    # early-exit: a page wholly past the chunk's frontier (or an idle
    # slot, last == -1) holds nothing any row may attend to
    @pl.when(p * page_size <= last)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [chunk, nh, hd]
        k = k_ref[0].astype(jnp.float32)            # [pg, kvh, hd]
        v = v_ref[0].astype(jnp.float32)
        m_prev = m_scr[:]                           # [chunk*nh, 128]
        l_prev = l_scr[:]

        # per-kv-head-group contractions keep the MXU ops unbatched
        logits = []
        for g in range(kv_heads):
            qg = q[:, g * rep:(g + 1) * rep].reshape(chunk * rep, -1)
            kg = k[:, g]                            # [pg, hd]
            logits.append(jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
                .reshape(chunk, rep, page_size))
        s_log = jnp.concatenate(logits, axis=1)     # [chunk, nh, pg]
        s_log = s_log.reshape(chunk * nh, page_size) * sm_scale

        # causal ragged masking: key position p*pg + j is visible to
        # chunk row c iff it is <= t0 + c (the row's absolute position)
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (chunk * nh, page_size), 1)
        row = jax.lax.broadcasted_iota(
            jnp.int32, (chunk * nh, page_size), 0) // nh
        valid = col <= t0 + row
        s_log = jnp.where(valid, s_log, NEG_INF)

        m_cur = jnp.max(s_log, axis=-1, keepdims=True)   # [chunk*nh, 1]
        m_new = jnp.maximum(m_prev[:, :1], m_cur)
        corr = jnp.exp(m_prev[:, :1] - m_new)
        pexp = jnp.exp(s_log - m_new)
        pexp = jnp.where(valid, pexp, 0.0)
        l_scr[:] = jnp.broadcast_to(
            corr * l_prev[:, :1] + jnp.sum(pexp, -1, keepdims=True),
            l_scr.shape)
        pe = pexp.reshape(chunk, nh, page_size)
        pv = []
        for g in range(kv_heads):
            pv.append(jax.lax.dot_general(
                pe[:, g * rep:(g + 1) * rep].reshape(chunk * rep, -1),
                v[:, g], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
                .reshape(chunk, rep, -1))
        pv = jnp.concatenate(pv, axis=1).reshape(chunk * nh, -1)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)              # idle-slot guard
        o_ref[0] = (acc_scr[:] / l).reshape(
            chunk, kv_heads * rep, -1).astype(o_ref.dtype)


def _ragged_prefill_pallas(q, k_pages, v_pages, block_tables, t0, last,
                           sm_scale, interpret=False):
    """q [S, C, nh, hd]; pages [P, pg, kvh, hd]; block_tables [S, maxp]
    int32 (unused tail entries must hold any VALID page id, e.g. 0);
    t0/last [S] int32 (last = t0 + take - 1, or -1 to skip the slot).
    Returns [S, C, nh, hd]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, C, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    maxp = block_tables.shape[1]
    rep = nh // kvh
    if nh % kvh:
        raise ValueError(f"query heads ({nh}) must be a multiple of kv "
                         f"heads ({kvh})")

    flat_bt = block_tables.reshape(-1).astype(jnp.int32)
    kernel = functools.partial(
        _ragged_prefill_kernel, page_size=pg, pages_per_slot=maxp,
        chunk=C, kv_heads=kvh, rep=rep, sm_scale=sm_scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, maxp),
        in_specs=[
            pl.BlockSpec((1, C, nh, hd),
                         lambda s, p, bt, t0_, ls: (s, 0, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda s, p, bt, t0_, ls:
                         (bt[s * maxp + p], 0, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda s, p, bt, t0_, ls:
                         (bt[s * maxp + p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, nh, hd),
                               lambda s, p, bt, t0_, ls: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C * nh, 128), jnp.float32),
            pltpu.VMEM((C * nh, 128), jnp.float32),
            pltpu.VMEM((C * nh, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, nh, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(flat_bt, t0.astype(jnp.int32), last.astype(jnp.int32),
      q, k_pages, v_pages)


# ------------------------------------------------- mesh-sharded kernel path


def _ragged_prefill_sharded(q, k_pages, v_pages, block_tables, t0, last,
                            sm_scale, mesh, axis, interpret):
    """Per-shard Pallas launches over the mesh's ``axis`` (sharded
    paged serving): pools sharded on kv heads, q split into the
    matching query-head groups (head axis 2 of [S, C, nh, hd]), block
    table / t0 / last replicated, output restitched on the head axis —
    the same split ``paged_attention._paged_attention_sharded`` makes
    for decode. Returns None when the head counts don't divide the
    axis; the caller then runs one replicated launch."""
    from jax.sharding import PartitionSpec as P

    from ..._compat import shard_map
    from .paged_attention import kv_head_shards
    if kv_head_shards(mesh, k_pages.shape[2], q.shape[2], axis) <= 1:
        return None
    fn = functools.partial(_ragged_prefill_pallas, sm_scale=sm_scale,
                           interpret=interpret)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, None, axis, None), P(None, None, axis, None),
                  P(None, None, axis, None), P(None, None), P(None),
                  P(None)),
        out_specs=P(None, None, axis, None), check_vma=False,
    )(q, k_pages, v_pages, block_tables, t0, last)


# ------------------------------------------------------ XLA reference path


def _ref_ragged_prefill(q, k_pages, v_pages, block_tables, t0, sm_scale):
    """Gather-through-block-table reference. Mirrors the dense prefill
    attention (``generation._cached_attend``) op-for-op so the ragged
    prefill path emits BIT-IDENTICAL cache rows and logits to the dense
    batch-1 prefill on every platform: valid positions carry the exact
    cached values, positions beyond a row's causal frontier are masked
    to -1e30 before the same f32 softmax (contributing exactly 0.0),
    and the einsum specs match."""
    S, C, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    maxp = block_tables.shape[1]
    T = maxp * pg
    k = k_pages[block_tables].reshape(S, T, kvh, hd)
    v = v_pages[block_tables].reshape(S, T, kvh, hd)
    rep = nh // kvh
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bsnd,btnd->bnst", q, k) * sm_scale
    pos = jnp.arange(T)
    row = t0[:, None] + jnp.arange(C, dtype=jnp.int32)[None]   # [S, C]
    ok = pos[None, None] <= row[:, :, None]                    # [S, C, T]
    logits = jnp.where(ok[:, None], logits.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, v)


# --------------------------------------------------------------- public


def ragged_prefill_attention(q, k_pages, v_pages, block_tables, t0,
                             last=None, sm_scale=None, interpret=False,
                             mesh=None):
    """Ragged packed-prefill attention over paged KV.

    q            [slots, chunk, num_heads, head_dim]  packed prompt
                 chunks, one variable-length segment per slot (shorter
                 segments are padded on the right; their garbage rows
                 are causally self-contained and discarded by the
                 caller)
    k_pages      [num_pages, page_size, kv_heads, head_dim]  global pool
    v_pages      same shape as ``k_pages``
    block_tables [slots, pages_per_slot] int32  page ids in position
                 order; entries past a slot's allocation must hold a
                 valid id (the manager fills them with 0)
    t0           [slots] int32  absolute position of each slot's first
                 chunk row — the prefix offset (cached pages before it
                 are attended through the block table)
    last         [slots] int32  last position each slot's chunk writes
                 (t0 + take - 1); -1 skips the slot entirely. Defaults
                 to ``t0 + chunk - 1`` (every row live).

    Row c of slot s attends to key positions <= t0[s] + c. Returns
    [slots, chunk, num_heads, head_dim]. Runs the Pallas kernel on TPU
    (or under ``interpret=True`` anywhere); elsewhere the gather-based
    XLA composition, which is bit-identical to the dense prefill path.
    ``mesh`` (sharded paged serving) splits the kernel launch per
    kv-head shard exactly like ``paged_attention`` — ignored on the
    XLA fallback, where GSPMD partitions from the pool's sharding.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if last is None:
        last = t0 + q.shape[1] - 1

    def _launch(qt, t0t, lastt):
        if mesh is not None:
            out = _ragged_prefill_sharded(qt, k_pages, v_pages,
                                          block_tables, t0t, lastt,
                                          sm_scale, mesh, "mp", interpret)
            if out is not None:
                return out
        return _ragged_prefill_pallas(qt, k_pages, v_pages, block_tables,
                                      t0t, lastt, sm_scale,
                                      interpret=interpret)

    if available() or interpret:
        # the kernel's VMEM scratch is (rows * nh)-tall: tile the query
        # rows so scratch stays bounded whatever chunk width the
        # scheduler packs (prefill_tokens_per_tick defaults to
        # max_cache_len — untiled, a long first chunk would blow VMEM
        # at serve time). Row r of tile starting at r0 sits at absolute
        # position t0 + r0 + r, so each tile is just a ragged launch
        # with a shifted prefix offset; the idle sentinel (last = -1)
        # survives the min().
        C = q.shape[1]
        if C <= _QUERY_TILE:
            return _launch(q, t0, last)
        outs = []
        for r0 in range(0, C, _QUERY_TILE):
            qt = q[:, r0:r0 + _QUERY_TILE]
            lastt = jnp.minimum(last, t0 + r0 + qt.shape[1] - 1)
            outs.append(_launch(qt, t0 + r0, lastt))
        return jnp.concatenate(outs, axis=1)
    out = _ref_ragged_prefill(q, k_pages, v_pages, block_tables, t0,
                              sm_scale)
    # platform-consistent skip semantics: the kernel's idle slots
    # (last < 0) finalize to zeros through the empty-accumulator guard;
    # zero the same rows here so fallback output matches bit-for-bit
    return jnp.where((last < 0)[:, None, None, None],
                     jnp.zeros_like(out), out)
