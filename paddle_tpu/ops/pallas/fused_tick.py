"""Pallas fused mixed prefill/decode tick attention (TPU) — ISSUE 14.

One serving tick used to be several device programs: a ragged-prefill
launch for the admission wave, the s=1 decode program for live slots,
plus the state pushes between them — and both paged kernels issued page
DMAs across the FULL block-table width per slot, masking (but paying
for) every page beyond a slot's live length (the PR-6 cut the goodput
ledger priced at a 0.001 paged goodput ratio). This module is the
attention core of the fused tick (FlashFuser / "Tile-Level Activation
Overlap", PAPERS.md): every slot's work this tick — a prefill CHUNK at
its own prefix offset, a single s=1 DECODE row, or nothing — runs as
one kernel whose DMA schedule covers ONLY live pages.

Two ideas over ``ragged_prefill.py``:

- **Unified per-row phase.** A decode step at position ``t`` is exactly
  a one-row prefill chunk with ``t0 = t``: write K/V at ``t``, attend
  causally to positions ``<= t``. So one kernel covers both phases —
  each query row ``r`` of slot ``s`` attends to positions
  ``<= t0[s] + r``, with its own online softmax lane. (The XLA
  fallback still routes decode rows through an s=1-shaped einsum —
  XLA CPU's single-row matmul takes a fused-reduce path ~1 ulp off the
  multi-row one, the PR-6 measurement — so fused serving stays
  BIT-IDENTICAL to the unfused decode program on every platform.)
- **True page skipping.** The grid is not ``(slots, table_width)`` but
  a flat DMA SCHEDULE: scalar-prefetched ``(sched_slot, sched_page)``
  pairs listing, slot-major, exactly the live pages
  (``ceil((last+1)/page_size)`` per live slot). A page wholly beyond a
  slot's frontier is never DMAed — HBM traffic scales with live
  tokens, not the configured cache length. The schedule is padded up a
  quarter-octave ladder (pad entries carry ``slot == n_slots`` and are
  fully skipped) so compiles stay O(log total_pages) with pad bounded
  at ~25% of live entries, and the caller passes
  block tables SLICED to the live width for the same reason on the
  gather fallback: the compiled program's cost-analysis bytes are flat
  in the configured block-table width (test-asserted in
  tests/test_costs.py).

The XLA fallback (``_ref_fused_tick``) gathers the live-width table
slice and mirrors ``models/generation._cached_attend`` op-for-op —
prefill rows through the same s=C einsum as ``_ref_ragged_prefill``,
decode rows through the same s=1 einsum as ``_ref_paged_attention`` —
which keeps fused tokens bit-identical to both unfused paths (the
masked-softmax output is bitwise invariant to the gathered frame's
extent on this XLA version; pinned by tests/test_fused_tick.py).
CPU tests run the Pallas kernel via ``interpret=True``.
"""
import functools
import math

import numpy as np

import jax
import jax.numpy as jnp

from . import on_tpu, tpu_compiler_params
from .paged_attention import NEG_INF
from .ragged_prefill import _QUERY_TILE

__all__ = ["fused_tick_attention", "build_schedule", "available"]


def available() -> bool:
    return on_tpu()


# ------------------------------------------------------------- schedule


def _ladder(n, min_entries):
    """Quarter-octave schedule-length ladder: round ``n`` up to the
    next multiple of ``2**floor(log2 n) / 4``. Pad stays <= ~25% of
    the live entries (a plain pow2 ladder wastes up to ~100% right
    past each power — the dominant fused-goodput waste at long
    contexts) while the number of distinct compile signatures stays
    O(4 log total_pages)."""
    n = max(int(n), int(min_entries))
    step = max(1, (1 << (n.bit_length() - 1)) // 4)
    return -(-n // step) * step


def build_schedule(last, page_size, n_slots=None, min_entries=8):
    """Host-side DMA schedule for one fused launch.

    ``last`` ([S] ints): each slot's last written position this launch
    (prefill: ``t0 + take - 1``; decode: ``t``; idle: ``-1``). A live
    slot contributes entries ``(s, 0) .. (s, last // page_size)`` —
    exactly the pages any of its live rows may attend to — in slot-
    major page order (the kernel's online softmax accumulates one
    slot's run contiguously). The schedule is padded up a
    quarter-octave ladder (floor ``min_entries``; see ``_ladder``)
    with ``(n_slots, 0)`` sentinels the kernel skips, so the launch
    signature stays on an O(log) compile ladder while live page
    counts drift tick to tick, and the pad — the fused path's ONLY
    remaining masked DMA — stays <= ~25% of the live entries.

    Returns ``(sched_slot, sched_page, n_live)`` — two int32 arrays of
    equal ladder length and the number of real (unpadded) entries;
    ``(len - n_live) * page_size`` is the ledger's masked-DMA model
    for the launch.
    """
    last = np.asarray(last, np.int64)
    if n_slots is None:
        n_slots = last.shape[0]
    # vectorized: this runs on the host EVERY tick — no per-page
    # Python loop on the packing hot path
    npages = np.where(last >= 0, last // int(page_size) + 1, 0)
    n_live = int(npages.sum())
    total = _ladder(n_live, min_entries)
    ss = np.full(total, int(n_slots), np.int32)
    sp = np.zeros(total, np.int32)
    ss[:n_live] = np.repeat(np.arange(last.shape[0]), npages)
    sp[:n_live] = np.arange(n_live) - np.repeat(
        np.cumsum(npages) - npages, npages)
    return ss, sp, n_live


# ----------------------------------------------------------------- kernel


def _fused_tick_kernel(bt_ref, t0_ref, ss_ref, sp_ref, q_ref, k_ref,
                       v_ref, o_ref, m_scr, l_scr, acc_scr, *, page_size,
                       n_slots, table_width, chunk, kv_heads, rep,
                       sm_scale, n_steps):
    """Grid ``(n_steps,)`` — one scheduled (slot, page) per step.

    q_ref  [1, chunk, nh, hd]       the scheduled slot's packed rows
    k_ref  [1, page_size, kvh, hd]  the page bt[slot, sched_page[g]]
                                    points at
    t0_ref[s]  absolute position of slot s's first row (decode rows
               are one-row chunks at their write position)
    ss/sp      the DMA schedule (slot-major; pad entries carry
               ``slot == n_slots`` and skip everything)
    Scratch m/l/acc carry one slot's online softmax across its
    contiguous schedule run; the run finalizes when the next entry
    belongs to a different slot.
    """
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    s = ss_ref[g]
    live = s < n_slots
    s_idx = jnp.minimum(s, n_slots - 1)           # clamp sentinel reads
    prev_s = ss_ref[jnp.maximum(g - 1, 0)]
    next_s = ss_ref[jnp.minimum(g + 1, n_steps - 1)]
    first = jnp.logical_or(g == 0, prev_s != s)
    closes = jnp.logical_or(g == n_steps - 1, next_s != s)
    nh = kv_heads * rep

    @pl.when(jnp.logical_and(live, first))
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(live)
    def _compute():
        p = sp_ref[g]
        t0 = t0_ref[s_idx]
        q = q_ref[0].astype(jnp.float32)            # [chunk, nh, hd]
        k = k_ref[0].astype(jnp.float32)            # [pg, kvh, hd]
        v = v_ref[0].astype(jnp.float32)
        m_prev = m_scr[:]                           # [chunk*nh, 128]
        l_prev = l_scr[:]

        # per-kv-head-group contractions keep the MXU ops unbatched
        logits = []
        for grp in range(kv_heads):
            qg = q[:, grp * rep:(grp + 1) * rep].reshape(chunk * rep, -1)
            kg = k[:, grp]                          # [pg, hd]
            logits.append(jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
                .reshape(chunk, rep, page_size))
        s_log = jnp.concatenate(logits, axis=1)     # [chunk, nh, pg]
        s_log = s_log.reshape(chunk * nh, page_size) * sm_scale

        # causal ragged masking: key position p*pg + j is visible to
        # row c iff it is <= t0 + c (decode rows: c = 0, t0 = t)
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (chunk * nh, page_size), 1)
        row = jax.lax.broadcasted_iota(
            jnp.int32, (chunk * nh, page_size), 0) // nh
        valid = col <= t0 + row
        s_log = jnp.where(valid, s_log, NEG_INF)

        m_cur = jnp.max(s_log, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev[:, :1], m_cur)
        corr = jnp.exp(m_prev[:, :1] - m_new)
        pexp = jnp.exp(s_log - m_new)
        pexp = jnp.where(valid, pexp, 0.0)
        l_scr[:] = jnp.broadcast_to(
            corr * l_prev[:, :1] + jnp.sum(pexp, -1, keepdims=True),
            l_scr.shape)
        pe = pexp.reshape(chunk, nh, page_size)
        pv = []
        for grp in range(kv_heads):
            pv.append(jax.lax.dot_general(
                pe[:, grp * rep:(grp + 1) * rep].reshape(chunk * rep, -1),
                v[:, grp], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
                .reshape(chunk, rep, -1))
        pv = jnp.concatenate(pv, axis=1).reshape(chunk * nh, -1)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jnp.logical_and(live, closes))
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)              # safety guard
        o_ref[0] = (acc_scr[:] / l).reshape(
            chunk, kv_heads * rep, -1).astype(o_ref.dtype)


def _fused_tick_pallas(q, k_pages, v_pages, block_tables, t0, sched_slot,
                       sched_page, sm_scale, interpret=False):
    """q [S, C, nh, hd]; pages [P, pg, kvh, hd]; block_tables [S, W]
    int32 sliced to the live width (unused tail entries must hold any
    VALID page id, e.g. 0); t0 [S] int32; sched_* [G] int32 (pad
    entries carry slot == S). Returns [S, C, nh, hd]; rows of slots
    absent from the schedule are left unwritten (the caller zeroes
    idle slots)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, C, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    W = block_tables.shape[1]
    G = sched_slot.shape[0]
    rep = nh // kvh
    if nh % kvh:
        raise ValueError(f"query heads ({nh}) must be a multiple of kv "
                         f"heads ({kvh})")

    flat_bt = block_tables.reshape(-1).astype(jnp.int32)
    kernel = functools.partial(
        _fused_tick_kernel, page_size=pg, n_slots=S, table_width=W,
        chunk=C, kv_heads=kvh, rep=rep, sm_scale=sm_scale, n_steps=G)

    def _slot(g, bt, t0_, ss, sp):
        return jnp.minimum(ss[g], S - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(G,),
        in_specs=[
            pl.BlockSpec((1, C, nh, hd),
                         lambda g, bt, t0_, ss, sp:
                         (_slot(g, bt, t0_, ss, sp), 0, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda g, bt, t0_, ss, sp:
                         (bt[_slot(g, bt, t0_, ss, sp) * W + sp[g]],
                          0, 0, 0)),
            pl.BlockSpec((1, pg, kvh, hd),
                         lambda g, bt, t0_, ss, sp:
                         (bt[_slot(g, bt, t0_, ss, sp) * W + sp[g]],
                          0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, nh, hd),
                               lambda g, bt, t0_, ss, sp:
                               (_slot(g, bt, t0_, ss, sp), 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C * nh, 128), jnp.float32),
            pltpu.VMEM((C * nh, 128), jnp.float32),
            pltpu.VMEM((C * nh, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, nh, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(flat_bt, t0.astype(jnp.int32), sched_slot.astype(jnp.int32),
      sched_page.astype(jnp.int32), q, k_pages, v_pages)


# ------------------------------------------------------ XLA reference path


def _ref_fused_tick(q, k_pages, v_pages, block_tables, t0, dec,
                    sm_scale):
    """Gather-through-the-live-slice reference. Prefill rows mirror
    ``_ref_ragged_prefill`` (s=C causal einsum), decode rows mirror
    ``_ref_paged_attention`` (s=1 einsum at lengths ``t0 + 1``) — the
    split keeps fused tokens BIT-IDENTICAL to both unfused programs on
    every platform (XLA CPU's single-row matmul differs ~1 ulp from
    the multi-row path, the PR-6 measurement). The gather spans only
    ``block_tables``' width — the caller slices it to the live page
    frontier, so compiled bytes are flat in the CONFIGURED table
    width (the skipped-page-DMA story, priced by the cost catalog)."""
    S, C, nh, hd = q.shape
    P, pg, kvh, _ = k_pages.shape
    W = block_tables.shape[1]
    T = W * pg
    k = k_pages[block_tables].reshape(S, T, kvh, hd)
    v = v_pages[block_tables].reshape(S, T, kvh, hd)
    rep = nh // kvh
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    pos = jnp.arange(T)
    # prefill-shaped causal attention over all C rows
    logits = jnp.einsum("bsnd,btnd->bnst", q, k) * sm_scale
    row = t0[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
    ok = pos[None, None] <= row[:, :, None]                # [S, C, T]
    p_pre = jax.nn.softmax(
        jnp.where(ok[:, None], logits.astype(jnp.float32), -1e30),
        axis=-1).astype(q.dtype)
    pre = jnp.einsum("bnst,btnd->bsnd", p_pre, v)
    # decode-shaped s=1 attention on row 0 at lengths t0 + 1
    qd = q[:, :1]
    logits_d = jnp.einsum("bsnd,btnd->bnst", qd, k) * sm_scale
    ok_d = pos[None, None] < (t0 + 1)[:, None, None]       # [S, 1, T]
    p_dec = jax.nn.softmax(
        jnp.where(ok_d[:, None], logits_d.astype(jnp.float32), -1e30),
        axis=-1).astype(q.dtype)
    dec_row = jnp.einsum("bnst,btnd->bsnd", p_dec, v)      # [S, 1, ...]
    dec_full = jnp.concatenate(
        [dec_row, jnp.zeros_like(q[:, 1:])], axis=1)
    return jnp.where((dec > 0)[:, None, None, None], dec_full, pre)


# --------------------------------------------------------------- public


def fused_tick_attention(q, k_pages, v_pages, block_tables, t0, last,
                         dec, sched_slot, sched_page, sm_scale=None,
                         interpret=False):
    """Fused mixed prefill/decode tick attention over paged KV.

    q            [slots, chunk, num_heads, head_dim]  one packed row
                 group per slot: a prompt chunk (right-padded), a
                 single decode row in row 0, or garbage for idle slots
    k_pages      [num_pages, page_size, kv_heads, head_dim]  global pool
    v_pages      same shape as ``k_pages``
    block_tables [slots, live_width] int32  the LIVE slice of the block
                 tables (width >= every slot's live page count; tail
                 entries hold a valid id, the manager fills 0)
    t0           [slots] int32  absolute position of each slot's first
                 row (decode: the write position ``t``)
    last         [slots] int32  last position each slot's rows write
                 (``t0 + take - 1``; decode: ``t0``); ``-1`` marks an
                 idle slot — skipped by the kernel, zeroed on output
    dec          [slots] int32  1 for decode slots (fallback routes
                 them through the s=1 einsum for bit-parity with the
                 unfused decode program; the kernel is phase-agnostic)
    sched_slot / sched_page
                 [entries] int32 DMA schedule from ``build_schedule``:
                 slot-major live pages, ladder-padded with
                 ``slot == slots`` sentinels

    Row c of slot s attends to key positions <= t0[s] + c. Returns
    [slots, chunk, num_heads, head_dim]; idle slots' rows are zeros,
    live slots' rows past their take are garbage the caller discards.
    Runs the Pallas kernel on TPU (or under ``interpret=True``
    anywhere); elsewhere the gather-based XLA composition, bit-exact
    with the unfused ragged-prefill and s=1 decode programs.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if available() or interpret:
        # tile wide chunks down to the ragged kernel's VMEM-bounded
        # row count; each tile is a shifted-offset launch against the
        # SAME schedule (live rows of tile r0 still attend <= last,
        # all covered pages scheduled) — still one host dispatch, the
        # tiles live inside one jitted program
        C = q.shape[1]
        if C <= _QUERY_TILE:
            out = _fused_tick_pallas(q, k_pages, v_pages, block_tables,
                                     t0, sched_slot, sched_page,
                                     sm_scale, interpret=interpret)
        else:
            outs = []
            for r0 in range(0, C, _QUERY_TILE):
                qt = q[:, r0:r0 + _QUERY_TILE]
                outs.append(_fused_tick_pallas(
                    qt, k_pages, v_pages, block_tables, t0 + r0,
                    sched_slot, sched_page, sm_scale,
                    interpret=interpret))
            out = jnp.concatenate(outs, axis=1)
    else:
        out = _ref_fused_tick(q, k_pages, v_pages, block_tables, t0,
                              dec, sm_scale)
    # platform-consistent idle semantics: slots with no work this
    # launch (absent from the schedule / garbage on the fallback)
    # read as zeros everywhere
    return jnp.where((last < 0)[:, None, None, None],
                     jnp.zeros_like(out), out)
