"""Random sampling ops over the global/scoped RNG (paddle.rand/randn/... parity).

Reference: python/paddle/tensor/random.py. Keys come from
core.random.next_key() so the same call sites work eagerly (global seed) and
inside a jitted step (explicit rng_scope) — see core/random.py.
"""
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, unwrap, wrap
from .registry import register_direct


def rand(shape, dtype="float32"):
    return wrap(jax.random.uniform(rnd.next_key(), shape,
                                   dtype=convert_dtype(dtype)))


def randn(shape, dtype="float32"):
    return wrap(jax.random.normal(rnd.next_key(), shape,
                                  dtype=convert_dtype(dtype)))


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):  # noqa: A002
    key = jax.random.PRNGKey(seed) if seed else rnd.next_key()
    return wrap(jax.random.uniform(key, shape, dtype=convert_dtype(dtype),
                                   minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = unwrap(mean) if isinstance(mean, Tensor) else mean
        s = unwrap(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return wrap(m + s * jax.random.normal(rnd.next_key(), shp))
    return wrap(mean + std * jax.random.normal(rnd.next_key(), shape or ()))


def gaussian(shape, mean=0.0, std=1.0, dtype="float32"):
    return wrap(mean + std * jax.random.normal(
        rnd.next_key(), shape, dtype=convert_dtype(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return wrap(jax.random.randint(rnd.next_key(), shape, low, high,
                                   dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    v = unwrap(x)
    if high is None:
        low, high = 0, low
    return wrap(jax.random.randint(rnd.next_key(), v.shape, low, high,
                                   dtype=convert_dtype(dtype) or v.dtype))


def randperm(n, dtype="int64"):
    return wrap(jax.random.permutation(rnd.next_key(), n).astype(
        convert_dtype(dtype)))


def shuffle(x, axis=0):
    v = unwrap(x) if isinstance(x, Tensor) else x
    return wrap(jax.random.permutation(rnd.next_key(), v, axis=axis))


def multinomial(x, num_samples=1, replacement=False):
    v = unwrap(x) if isinstance(x, Tensor) else x
    logits = jnp.log(v + 1e-30)
    if replacement:
        out = jax.random.categorical(rnd.next_key(), logits,
                                     shape=v.shape[:-1] + (num_samples,))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(rnd.next_key(), v.shape)
        out = jnp.argsort(logits + g, axis=-1, descending=True)[..., :num_samples]
    return wrap(out.astype(jnp.int64))


def bernoulli(x):
    v = unwrap(x) if isinstance(x, Tensor) else x
    return wrap(jax.random.bernoulli(rnd.next_key(), v).astype(v.dtype))


def poisson(x):
    v = unwrap(x) if isinstance(x, Tensor) else x
    return wrap(jax.random.poisson(rnd.next_key(), v).astype(v.dtype))


def exponential_(x, lam=1.0):
    v = unwrap(x)
    x._replace_value(jax.random.exponential(rnd.next_key(), v.shape,
                                            dtype=v.dtype) / lam)
    return x


def standard_normal(shape, dtype="float32"):
    return wrap(jax.random.normal(rnd.next_key(), shape,
                                  dtype=convert_dtype(dtype)))


def rand_like(x, dtype=None):
    v = unwrap(x)
    return wrap(jax.random.uniform(rnd.next_key(), v.shape,
                                   dtype=convert_dtype(dtype) or v.dtype))


def randn_like(x, dtype=None):
    v = unwrap(x)
    return wrap(jax.random.normal(rnd.next_key(), v.shape,
                                  dtype=convert_dtype(dtype) or v.dtype))


for _n in ["rand", "randn", "uniform", "normal", "gaussian", "randint",
           "randint_like", "randperm", "shuffle", "multinomial", "bernoulli",
           "poisson", "standard_normal", "rand_like", "randn_like"]:
    register_direct(_n, globals()[_n])
