"""Native runtime: ctypes bindings over libpaddle_tpu_rt.so.

The C++ pieces the reference keeps native stay native here (SURVEY §7 M1):
TCPStore rendezvous (tcp_store.cc) and the FLAGS_ registry (flags.cc).
Built on first use via CMake+ninja (falls back to direct g++), mirroring the
reference's JIT cpp_extension toolchain
(python/paddle/utils/cpp_extension/).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "build", "libpaddle_tpu_rt.so")
_lock = threading.Lock()
_lib = None


def build_library(force=False):
    """CMake+ninja build of the runtime library (g++ direct fallback).
    Rebuilds when any csrc source is newer than the built .so (a stale
    library missing newly added symbols would break EVERY runtime user
    at ctypes bind time)."""
    if os.path.exists(_LIB_PATH) and not force:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        srcdir = os.path.join(_HERE, "csrc")
        fresh = all(os.path.getmtime(os.path.join(srcdir, f)) <= lib_mtime
                    for f in os.listdir(srcdir) if f.endswith(".cc"))
        if fresh:
            return _LIB_PATH
        force = True
    build_dir = os.path.join(_HERE, "build")
    os.makedirs(build_dir, exist_ok=True)
    try:
        subprocess.run(["cmake", "-G", "Ninja", "-S", _HERE, "-B", build_dir],
                       check=True, capture_output=True)
        subprocess.run(["cmake", "--build", build_dir], check=True,
                       capture_output=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        srcs = [os.path.join(_HERE, "csrc", f)
                for f in ("tcp_store.cc", "flags.cc", "shm_ring.cc")]
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-o", _LIB_PATH,
             *srcs, "-lpthread", "-lrt"], check=True)
    return _LIB_PATH


def lib():
    global _lib
    with _lock:
        if _lib is None:
            path = build_library()
            L = ctypes.CDLL(path)
            L.pt_store_server_start.restype = ctypes.c_void_p
            L.pt_store_server_start.argtypes = [ctypes.c_int]
            L.pt_store_server_port.restype = ctypes.c_int
            L.pt_store_server_port.argtypes = [ctypes.c_void_p]
            L.pt_store_server_stop.argtypes = [ctypes.c_void_p]
            L.pt_store_client_connect.restype = ctypes.c_void_p
            L.pt_store_client_connect.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_int, ctypes.c_int]
            L.pt_store_set.restype = ctypes.c_int
            L.pt_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_int]
            L.pt_store_get.restype = ctypes.c_long
            L.pt_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_long]
            L.pt_store_add.restype = ctypes.c_longlong
            L.pt_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_longlong]
            L.pt_store_check.restype = ctypes.c_int
            L.pt_store_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            L.pt_store_del.restype = ctypes.c_int
            L.pt_store_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            L.pt_store_client_close.argtypes = [ctypes.c_void_p]
            L.pt_flags_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            L.pt_flags_get.restype = ctypes.c_char_p
            L.pt_flags_get.argtypes = [ctypes.c_char_p]
            L.pt_flags_has.restype = ctypes.c_int
            L.pt_flags_has.argtypes = [ctypes.c_char_p]
            L.pt_flags_list.restype = ctypes.c_char_p
            L.shm_ring_create.restype = ctypes.c_void_p
            L.shm_ring_create.argtypes = [ctypes.c_char_p,
                                          ctypes.c_uint64,
                                          ctypes.c_uint32]
            L.shm_ring_attach.restype = ctypes.c_void_p
            L.shm_ring_attach.argtypes = [ctypes.c_char_p]
            L.shm_ring_push.restype = ctypes.c_int
            L.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_int]
            L.shm_ring_pop.restype = ctypes.c_int64
            L.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64, ctypes.c_int]
            L.shm_ring_size.restype = ctypes.c_uint64
            L.shm_ring_size.argtypes = [ctypes.c_void_p]
            L.shm_ring_slot_size.restype = ctypes.c_uint64
            L.shm_ring_slot_size.argtypes = [ctypes.c_void_p]
            L.shm_ring_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
            _lib = L
    return _lib


class ShmRing:
    """Lock-free SPSC shared-memory ring (native csrc/shm_ring.cc) —
    the DataLoader's worker->main batch transport (reference C++
    buffered_reader over shared memory). ``create=True`` owns the
    segment (and unlinks it on close); workers ``attach``."""

    def __init__(self, name, slot_size=1 << 23, n_slots=8, create=True):
        self.name = name
        self._own = create
        self.slot_size = slot_size
        self._buf = None                 # lazy persistent pop buffer
        if create:
            self._h = lib().shm_ring_create(name.encode(), slot_size,
                                            n_slots)
        else:
            self._h = lib().shm_ring_attach(name.encode())
        if not self._h:
            raise RuntimeError(f"shm_ring {'create' if create else 'attach'}"
                               f" failed for {name!r}")
        if not create:
            # the creator owns the true slot size; read it back
            self.slot_size = int(lib().shm_ring_slot_size(self._h))

    def push(self, data, timeout_ms=-1):
        rc = lib().shm_ring_push(self._h, bytes(data), len(data),
                                 timeout_ms)
        if rc == -2:
            raise ValueError(f"payload {len(data)} bytes exceeds the "
                             "ring slot size")
        return rc == 0

    def pop(self, max_len=None, timeout_ms=-1):
        if timeout_ms == 0 and len(self) == 0:
            return None                  # cheap empty probe: no buffer
        cap = max_len or self.slot_size
        if self._buf is None or len(self._buf) < cap:
            self._buf = ctypes.create_string_buffer(cap)
        n = lib().shm_ring_pop(self._h, self._buf, cap, timeout_ms)
        if n == -1:
            return None
        if n == -2:
            raise ValueError("ring payload larger than max_len")
        return self._buf.raw[:n]

    def __len__(self):
        return int(lib().shm_ring_size(self._h))

    def close(self):
        if self._h:
            lib().shm_ring_close(self._h, 1 if self._own else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TCPStoreServer:
    """Rank-0 side of the rendezvous (reference tcp_store.cc MasterDaemon)."""

    def __init__(self, port=0):
        self._h = lib().pt_store_server_start(port)
        if not self._h:
            raise RuntimeError(f"failed to bind TCPStore on port {port}")

    @property
    def port(self):
        return lib().pt_store_server_port(self._h)

    def stop(self):
        if self._h:
            lib().pt_store_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client (reference phi TCPStore API: set/get/add/wait).

    Thread-safe: one request/reply cycle at a time per connection — the
    elastic manager heartbeats from a daemon thread while the main
    thread polls membership, and interleaved writes on the shared
    socket would corrupt the length-prefixed protocol (observed as a
    blocked check() waiting on a reply the other thread consumed).
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30):
        self._server = None
        self._io_lock = threading.Lock()
        if is_master:
            self._server = TCPStoreServer(port)
            port = self._server.port
        self.host = host
        self.port = port
        self._h = lib().pt_store_client_connect(host.encode(), port,
                                                int(timeout * 1000))
        if not self._h:
            raise TimeoutError(f"cannot reach TCPStore at {host}:{port}")

    def set(self, key, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        with self._io_lock:
            rc = lib().pt_store_set(self._h, key.encode(), data,
                                    len(data))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key, max_len=1 << 20):
        buf = ctypes.create_string_buffer(max_len)
        with self._io_lock:
            n = lib().pt_store_get(self._h, key.encode(), buf, max_len)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    wait = get

    def add(self, key, delta=1):
        with self._io_lock:
            out = lib().pt_store_add(self._h, key.encode(), delta)
        if out == -1:
            raise RuntimeError("TCPStore.add failed")
        return int(out)

    def check(self, key):
        with self._io_lock:
            return bool(lib().pt_store_check(self._h, key.encode()))

    def delete_key(self, key):
        with self._io_lock:
            return lib().pt_store_del(self._h, key.encode()) == 0

    def barrier(self, name, world_size, timeout=60):
        """Counter barrier over the store (launcher sync primitive)."""
        import time
        n = self.add(f"__barrier__{name}", 1)
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self.add(f"__barrier__{name}", 0)
            if cur >= world_size:
                return True
            time.sleep(0.02)
        raise TimeoutError(f"barrier {name} timed out at {n}/{world_size}")

    def close(self):
        if self._h:
            lib().pt_store_client_close(self._h)
            self._h = None
        if self._server:
            self._server.stop()


# ------------------------------------------------------------------- flags


def set_flags(flags: dict):
    """paddle.set_flags parity (framework.py:7736)."""
    for k, v in flags.items():
        name = k[6:] if k.startswith("FLAGS_") else k
        lib().pt_flags_set(name.encode(), str(v).encode())
    # live hooks: flags that change framework behavior immediately
    if any(k.endswith("check_nan_inf") or k.endswith("check_nan_inf_level")
           for k in flags):
        from ..core.tensor import set_nan_inf_check
        cur = get_flags(["FLAGS_check_nan_inf", "FLAGS_check_nan_inf_level"])
        set_nan_inf_check(cur["FLAGS_check_nan_inf"] or 0,
                          cur["FLAGS_check_nan_inf_level"] or 0)


def get_flags(names):
    """paddle.get_flags parity."""
    single = isinstance(names, str)
    names_list = [names] if single else list(names)
    out = {}
    for k in names_list:
        name = k[6:] if k.startswith("FLAGS_") else k
        v = lib().pt_flags_get(name.encode())
        out[k] = v.decode() if v is not None else None
    return out


def list_flags():
    raw = lib().pt_flags_list().decode()
    return dict(line.split("=", 1) for line in raw.splitlines() if "=" in line)
