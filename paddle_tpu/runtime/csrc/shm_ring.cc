// Shared-memory SPSC ring buffer: the DataLoader worker->main batch
// transport, native.
//
// Reference analogue: the C++ reader core the reference feeds trainers
// with (paddle/fluid/operators/reader/buffered_reader.cc over
// paddle/fluid/memory shared-memory allocations; the Python DataLoader's
// _shared_memory path serializes into the same kind of segment). Here the
// ring IS the queue: fixed-size slots in one POSIX shm segment, a
// lock-free single-producer/single-consumer head/tail pair with acquire/
// release atomics, and a spin-then-sleep wait so an idle reader costs no
// CPU. One worker process owns the producer side; the main process pops.
//
// Layout: [Header | slot 0 | slot 1 | ... | slot n-1]
//   slot: u64 payload_len | payload bytes (slot_size - 8 capacity)
// C ABI (ctypes-bound in runtime/__init__.py):
//   shm_ring_create(name, slot_size, n_slots) -> handle | NULL
//   shm_ring_attach(name)                     -> handle | NULL
//   shm_ring_push(h, buf, len, timeout_ms)    -> 0 | -1 timeout | -2 big
//   shm_ring_pop(h, out, cap, timeout_ms)     -> len | -1 timeout | -2 cap
//   shm_ring_size(h)                          -> slots currently filled
//   shm_ring_close(h, unlink)

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <string>

namespace {

constexpr uint64_t kMagic = 0x70745F72696E6731ULL;  // "pt_ring1"

struct Header {
  uint64_t magic;
  uint64_t slot_size;   // bytes per slot incl. the u64 length prefix
  uint64_t n_slots;
  std::atomic<uint64_t> head;   // next slot to pop
  std::atomic<uint64_t> tail;   // next slot to push
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  std::string name;
};

uint8_t* slot_ptr(Ring* r, uint64_t idx) {
  return r->data + (idx % r->hdr->n_slots) * r->hdr->slot_size;
}

void sleep_ns(long ns) {
  timespec ts{0, ns};
  nanosleep(&ts, nullptr);
}

// spin briefly, then sleep in escalating steps; returns false on timeout
template <typename Cond>
bool wait_until(Cond cond, int timeout_ms) {
  for (int i = 0; i < 256; ++i) {
    if (cond()) return true;
  }
  long waited_ns = 0;
  long step = 50 * 1000;                       // 50 us
  const long limit = int64_t(timeout_ms) * 1000 * 1000;
  while (timeout_ms < 0 || waited_ns < limit) {
    if (cond()) return true;
    sleep_ns(step);
    waited_ns += step;
    if (step < 2 * 1000 * 1000) step *= 2;     // cap at 2 ms
  }
  return cond();
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, uint64_t slot_size,
                      uint32_t n_slots) {
  if (slot_size < 16 || n_slots == 0) return nullptr;
  shm_unlink(name);                            // stale segment from a crash
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t len = sizeof(Header) + size_t(slot_size) * n_slots;
  if (ftruncate(fd, off_t(len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) Header();
  hdr->slot_size = slot_size;
  hdr->n_slots = n_slots;
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->magic = kMagic;
  auto* r = new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(Header), len,
                     name};
  return r;
}

void* shm_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || size_t(st.st_size) < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, size_t(st.st_size), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, size_t(st.st_size));
    return nullptr;
  }
  auto* r = new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(Header),
                     size_t(st.st_size), name};
  return r;
}

int shm_ring_push(void* handle, const void* buf, uint64_t len,
                  int timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  if (len + 8 > r->hdr->slot_size) return -2;
  auto full = [r] {
    return r->hdr->tail.load(std::memory_order_relaxed) -
               r->hdr->head.load(std::memory_order_acquire) <
           r->hdr->n_slots;
  };
  if (!wait_until(full, timeout_ms)) return -1;
  uint64_t t = r->hdr->tail.load(std::memory_order_relaxed);
  uint8_t* slot = slot_ptr(r, t);
  std::memcpy(slot, &len, 8);
  std::memcpy(slot + 8, buf, len);
  r->hdr->tail.store(t + 1, std::memory_order_release);
  return 0;
}

int64_t shm_ring_pop(void* handle, void* out, uint64_t cap,
                     int timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  auto nonempty = [r] {
    return r->hdr->head.load(std::memory_order_relaxed) <
           r->hdr->tail.load(std::memory_order_acquire);
  };
  if (!wait_until(nonempty, timeout_ms)) return -1;
  uint64_t h = r->hdr->head.load(std::memory_order_relaxed);
  uint8_t* slot = slot_ptr(r, h);
  uint64_t len;
  std::memcpy(&len, slot, 8);
  if (len > cap) return -2;
  std::memcpy(out, slot + 8, len);
  r->hdr->head.store(h + 1, std::memory_order_release);
  return int64_t(len);
}

uint64_t shm_ring_slot_size(void* handle) {
  return static_cast<Ring*>(handle)->hdr->slot_size;
}

uint64_t shm_ring_size(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  return r->hdr->tail.load(std::memory_order_acquire) -
         r->hdr->head.load(std::memory_order_acquire);
}

void shm_ring_close(void* handle, int unlink) {
  auto* r = static_cast<Ring*>(handle);
  std::string name = r->name;
  munmap(r->hdr, r->map_len);
  if (unlink) shm_unlink(name.c_str());
  delete r;
}

}  // extern "C"
