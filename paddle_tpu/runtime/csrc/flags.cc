// Runtime flags registry.
//
// Native equivalent of the reference's exported-flags system
// (/root/reference/paddle/phi/core/flags.cc:34 PADDLE_DEFINE_EXPORTED_*,
// python surface paddle.set_flags/get_flags, framework.py:7736): a
// process-wide string map seeded from FLAGS_* environment variables, with
// typed readback. Host-side config (allocator strategy, log levels,
// nan-inf checks) reads through this, matching the FLAGS_ env protocol.

#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

extern "C" char** environ;

namespace {
std::map<std::string, std::string>& flag_map() {
  static std::map<std::string, std::string>* m = [] {
    auto* mm = new std::map<std::string, std::string>();
    for (char** e = environ; *e; ++e) {
      const char* s = *e;
      if (strncmp(s, "FLAGS_", 6) == 0) {
        const char* eq = strchr(s, '=');
        if (eq) {
          (*mm)[std::string(s + 6, eq - s - 6)] = std::string(eq + 1);
        }
      }
    }
    return mm;
  }();
  return *m;
}
std::mutex mu;
std::string last_result;
}  // namespace

extern "C" {

void pt_flags_set(const char* name, const char* value) {
  std::lock_guard<std::mutex> g(mu);
  flag_map()[name] = value;
}

// returns nullptr when unset
const char* pt_flags_get(const char* name) {
  std::lock_guard<std::mutex> g(mu);
  auto it = flag_map().find(name);
  if (it == flag_map().end()) return nullptr;
  last_result = it->second;
  return last_result.c_str();
}

int pt_flags_has(const char* name) {
  std::lock_guard<std::mutex> g(mu);
  return flag_map().count(name) ? 1 : 0;
}

// newline-joined "name=value" list
const char* pt_flags_list() {
  std::lock_guard<std::mutex> g(mu);
  last_result.clear();
  for (auto& kv : flag_map()) {
    last_result += kv.first;
    last_result += '=';
    last_result += kv.second;
    last_result += '\n';
  }
  return last_result.c_str();
}

}  // extern "C"
