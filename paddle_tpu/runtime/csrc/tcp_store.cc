// TCPStore: key-value rendezvous for multi-host bring-up.
//
// Native C++ equivalent of the reference's phi TCPStore
// (/root/reference/paddle/phi/core/distributed/store/tcp_store.h:120,
// tcp_utils.cc): rank-0 hosts the store; clients SET/GET/ADD/WAIT keys to
// exchange bootstrap info (the reference broadcasts ncclUniqueId this way;
// here the launcher exchanges coordinator addresses and barrier counters
// before jax.distributed.initialize takes over).
//
// Protocol (length-prefixed, host byte order on one machine / launcher use):
//   u8 op | u32 klen | key | u32 vlen | value
//   ops: 0=SET 1=GET(blocking) 2=ADD(i64 delta -> i64 reply) 3=CHECK
//        4=DEL 5=LIST_KEYS
// Replies: u32 len | payload  (GET/ADD/CHECK/LIST)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::mutex conn_mu;
  std::vector<int> conn_fds;
  Store store;
  ~Server() { stop(); }
  void stop() {
    if (running.exchange(false)) {
      shutdown(listen_fd, SHUT_RDWR);
      close(listen_fd);
      if (accept_thread.joinable()) accept_thread.join();
      {
        // wake serve_conn threads blocked in recv on live clients —
        // joining them without this deadlocks process exit whenever a
        // client (e.g. this process's own rendezvous connection) is
        // still connected
        std::lock_guard<std::mutex> g(conn_mu);
        for (int fd : conn_fds) shutdown(fd, SHUT_RDWR);
      }
      for (auto& w : workers)
        if (w.joinable()) w.join();
    }
  }
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_reply(int fd, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!write_full(fd, &len, 4)) return false;
  return payload.empty() || write_full(fd, payload.data(), payload.size());
}

void serve_conn(Server* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (srv->running) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_full(fd, key.data(), klen)) break;
    if (!read_full(fd, &vlen, 4)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_full(fd, val.data(), vlen)) break;

    Store& st = srv->store;
    if (op == 0) {  // SET
      {
        std::lock_guard<std::mutex> g(st.mu);
        st.data[key] = val;
      }
      st.cv.notify_all();
    } else if (op == 1) {  // blocking GET
      std::unique_lock<std::mutex> g(st.mu);
      st.cv.wait(g, [&] { return st.data.count(key) || !srv->running; });
      if (!srv->running) break;
      if (!send_reply(fd, st.data[key])) break;
    } else if (op == 2) {  // ADD
      int64_t delta = 0;
      memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
      int64_t now;
      {
        std::lock_guard<std::mutex> g(st.mu);
        int64_t cur = 0;
        auto it = st.data.find(key);
        if (it != st.data.end())
          memcpy(&cur, it->second.data(), std::min<size_t>(8, it->second.size()));
        now = cur + delta;
        st.data[key] = std::string(reinterpret_cast<char*>(&now), 8);
      }
      st.cv.notify_all();
      std::string reply(reinterpret_cast<char*>(&now), 8);
      if (!send_reply(fd, reply)) break;
    } else if (op == 3) {  // CHECK
      bool has;
      {
        std::lock_guard<std::mutex> g(st.mu);
        has = st.data.count(key) > 0;
      }
      std::string reply(1, has ? 1 : 0);
      if (!send_reply(fd, reply)) break;
    } else if (op == 4) {  // DEL
      {
        std::lock_guard<std::mutex> g(st.mu);
        st.data.erase(key);
      }
      st.cv.notify_all();
    } else if (op == 5) {  // LIST
      std::string keys;
      {
        std::lock_guard<std::mutex> g(st.mu);
        for (auto& kv : st.data) {
          keys += kv.first;
          keys += '\n';
        }
      }
      if (!send_reply(fd, keys)) break;
    } else {
      break;
    }
  }
  close(fd);
}

}  // namespace

extern "C" {

void* pt_store_server_start(int port) {
  auto* srv = new Server();
  srv->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(srv->listen_fd, 128) != 0) {
    delete srv;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  srv->port = ntohs(addr.sin_port);
  srv->running = true;
  srv->accept_thread = std::thread([srv] {
    while (srv->running) {
      int fd = accept(srv->listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (!srv->running) break;
        continue;
      }
      {
        std::lock_guard<std::mutex> g(srv->conn_mu);
        srv->conn_fds.push_back(fd);
      }
      srv->workers.emplace_back(serve_conn, srv, fd);
    }
  });
  return srv;
}

int pt_store_server_port(void* h) { return static_cast<Server*>(h)->port; }

void pt_store_server_stop(void* h) {
  auto* srv = static_cast<Server*>(h);
  srv->stop();
  delete srv;
}

struct Client {
  int fd = -1;
};

void* pt_store_client_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    inet_pton(AF_INET, host, &addr.sin_addr);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client();
      c->fd = fd;
      return c;
    }
    close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  return nullptr;
}

static bool send_cmd(Client* c, uint8_t op, const char* key, uint32_t klen,
                     const char* val, uint32_t vlen) {
  if (!write_full(c->fd, &op, 1)) return false;
  if (!write_full(c->fd, &klen, 4)) return false;
  if (klen && !write_full(c->fd, key, klen)) return false;
  if (!write_full(c->fd, &vlen, 4)) return false;
  if (vlen && !write_full(c->fd, val, vlen)) return false;
  return true;
}

int pt_store_set(void* h, const char* key, const char* val, int vlen) {
  auto* c = static_cast<Client*>(h);
  return send_cmd(c, 0, key, static_cast<uint32_t>(strlen(key)), val,
                  static_cast<uint32_t>(vlen))
             ? 0
             : -1;
}

// blocking get; returns bytes written or -1; caller provides buffer
long pt_store_get(void* h, const char* key, char* out, long cap) {
  auto* c = static_cast<Client*>(h);
  if (!send_cmd(c, 1, key, static_cast<uint32_t>(strlen(key)), nullptr, 0))
    return -1;
  uint32_t len;
  if (!read_full(c->fd, &len, 4)) return -1;
  std::string tmp(len, '\0');
  if (len && !read_full(c->fd, tmp.data(), len)) return -1;
  long n = std::min<long>(cap, static_cast<long>(len));
  memcpy(out, tmp.data(), static_cast<size_t>(n));
  return static_cast<long>(len);
}

long long pt_store_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<Client*>(h);
  if (!send_cmd(c, 2, key, static_cast<uint32_t>(strlen(key)),
                reinterpret_cast<char*>(&delta), 8))
    return -1;
  uint32_t len;
  if (!read_full(c->fd, &len, 4) || len != 8) return -1;
  long long out;
  if (!read_full(c->fd, &out, 8)) return -1;
  return out;
}

int pt_store_check(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  if (!send_cmd(c, 3, key, static_cast<uint32_t>(strlen(key)), nullptr, 0))
    return -1;
  uint32_t len;
  if (!read_full(c->fd, &len, 4) || len != 1) return -1;
  char has;
  if (!read_full(c->fd, &has, 1)) return -1;
  return has;
}

int pt_store_del(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  return send_cmd(c, 4, key, static_cast<uint32_t>(strlen(key)), nullptr, 0)
             ? 0
             : -1;
}

void pt_store_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  close(c->fd);
  delete c;
}

}  // extern "C"
