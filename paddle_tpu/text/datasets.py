"""paddle.text.datasets parity (Conll05st/Imdb/Imikolov/Movielens/
UCIHousing/WMT14/WMT16).

Reference: python/paddle/text/datasets/*.py — each downloads a corpus and
yields numpy examples via paddle.io.Dataset. This build runs with zero
egress, so every dataset takes `data_file` pointing at a local copy and
raises a clear error otherwise (same constructor surface otherwise).
Parsing matches the reference formats where feasible.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io.dataloader import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


class _LocalDataset(Dataset):
    _name = "dataset"

    def _require(self, data_file):
        if not data_file or not os.path.exists(data_file):
            raise RuntimeError(
                f"{self._name}: no network access in this environment; pass "
                f"data_file= pointing at a local copy of the corpus "
                f"(got {data_file!r})")
        return data_file

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, idx):
        return self.examples[idx]


class UCIHousing(_LocalDataset):
    """506x14 whitespace-separated numeric table (reference
    python/paddle/text/datasets/uci_housing.py; 13 features + price)."""
    _name = "UCIHousing"

    def __init__(self, data_file=None, mode="train", download=False):
        self._require(data_file)
        raw = np.loadtxt(data_file, dtype=np.float32)
        raw = raw.reshape(-1, 14)
        # reference normalizes features by train-split max/min/avg
        split = int(raw.shape[0] * 0.8)
        feats, prices = raw[:, :13], raw[:, 13:]
        mx, mn, avg = (feats[:split].max(0), feats[:split].min(0),
                       feats[:split].mean(0))
        rng = np.where(mx - mn == 0, 1, mx - mn)
        feats = (feats - avg) / rng
        data = np.concatenate([feats, prices], 1)
        part = data[:split] if mode == "train" else data[split:]
        self.examples = [(row[:13].astype(np.float32),
                          row[13:].astype(np.float32)) for row in part]


class Imikolov(_LocalDataset):
    """PTB-style n-gram dataset (reference imikolov.py): tokenized lines →
    (n-1 context ids, next-word id)."""
    _name = "Imikolov"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        self._require(data_file)
        with open(data_file) as f:
            lines = [ln.strip().lower().split() for ln in f]
        freq = {}
        for ln in lines:
            for w in ln:
                freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
                 if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        self.word_idx["<s>"] = len(self.word_idx)
        self.word_idx["<e>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.examples = []
        for ln in lines:
            ids = ([self.word_idx["<s>"]]
                   + [self.word_idx.get(w, unk) for w in ln]
                   + [self.word_idx["<e>"]])
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    win = ids[i:i + window_size]
                    self.examples.append(tuple(
                        np.array([t], np.int64) for t in win))
            else:  # SEQ: one (input, shifted-target) pair per line
                self.examples.append((np.asarray(ids[:-1], np.int64),
                                      np.asarray(ids[1:], np.int64)))


class Imdb(_LocalDataset):
    """IMDB sentiment tarball (aclImdb format: {train,test}/{pos,neg}/*.txt
    inside a .tar.gz), reference imdb.py."""
    _name = "Imdb"

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        self._require(data_file)
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                text = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower()
                toks = re.findall(r"[a-z]+", text)
                docs.append(toks)
                labels.append(0 if g.group(1) == "pos" else 1)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        # reference imdb.py build_dict: keep words with freq > cutoff
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.examples = [
            (np.asarray([self.word_idx.get(t, unk) for t in toks], np.int64),
             np.asarray(lab, np.int64))
            for toks, lab in zip(docs, labels)]


class Movielens(_LocalDataset):
    """ml-1m ratings (reference movielens.py): user::movie::rating rows."""
    _name = "Movielens"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        self._require(data_file)
        rows = []
        opener = gzip.open if data_file.endswith(".gz") else open
        with opener(data_file, "rt") as f:
            for ln in f:
                parts = ln.strip().split("::")
                if len(parts) >= 3:
                    rows.append((int(parts[0]), int(parts[1]),
                                 float(parts[2])))
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(rows)) < test_ratio
        sel = [r for r, m in zip(rows, mask) if m == (mode == "test")]
        self.examples = [
            (np.asarray(u, np.int64), np.asarray(m, np.int64),
             np.asarray(r, np.float32)) for u, m, r in sel]


class _ParallelCorpus(_LocalDataset):
    """src ||| tgt tab/'\t'-separated parallel lines with on-the-fly dicts
    (stands in for the reference's preprocessed WMT pickles)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        self._require(data_file)
        pairs = []
        with open(data_file) as f:
            for ln in f:
                if "\t" not in ln:
                    continue
                src, tgt = ln.rstrip("\n").split("\t", 1)
                pairs.append((src.split(), tgt.split()))
        freq_src, freq_tgt = {}, {}
        for s, t in pairs:
            for w in s:
                freq_src[w] = freq_src.get(w, 0) + 1
            for w in t:
                freq_tgt[w] = freq_tgt.get(w, 0) + 1

        def build(freq):
            vocab = ["<s>", "<e>", "<unk>"] + [
                w for w, _ in sorted(freq.items(), key=lambda kv: -kv[1])]
            if dict_size > 0:
                vocab = vocab[:dict_size]
            return {w: i for i, w in enumerate(vocab)}

        self.src_ids = build(freq_src)
        self.trg_ids = build(freq_tgt)
        unk_s, unk_t = self.src_ids["<unk>"], self.trg_ids["<unk>"]
        self.examples = []
        for s, t in pairs:
            sid = [self.src_ids.get(w, unk_s) for w in s]
            tid = ([self.trg_ids["<s>"]]
                   + [self.trg_ids.get(w, unk_t) for w in t])
            lbl = tid[1:] + [self.trg_ids["<e>"]]
            self.examples.append((np.asarray(sid, np.int64),
                                  np.asarray(tid, np.int64),
                                  np.asarray(lbl, np.int64)))


class WMT14(_ParallelCorpus):
    _name = "WMT14"


class WMT16(_ParallelCorpus):
    _name = "WMT16"


class Conll05st(_LocalDataset):
    """SRL dataset (reference conll05.py). Local format: one token per line
    `word predicate label`, blank line between sentences."""
    _name = "Conll05st"

    def __init__(self, data_file=None, mode="train", download=False):
        self._require(data_file)
        sents, cur = [], []
        with open(data_file) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    if cur:
                        sents.append(cur)
                        cur = []
                    continue
                cur.append(ln.split())
        if cur:
            sents.append(cur)
        words = sorted({t[0] for s in sents for t in s})
        preds = sorted({t[1] for s in sents for t in s})
        labels = sorted({t[-1] for s in sents for t in s})
        self.word_dict = {w: i for i, w in enumerate(words)}
        self.predicate_dict = {p: i for i, p in enumerate(preds)}
        self.label_dict = {l: i for i, l in enumerate(labels)}
        self.examples = []
        for s in sents:
            wid = np.asarray([self.word_dict[t[0]] for t in s], np.int64)
            pid = np.asarray([self.predicate_dict[t[1]] for t in s],
                             np.int64)
            lid = np.asarray([self.label_dict[t[-1]] for t in s], np.int64)
            self.examples.append((wid, pid, lid))
