"""paddle.text parity: viterbi_decode/ViterbiDecoder + NLP datasets.

Reference: python/paddle/text/__init__.py (__all__: Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16, ViterbiDecoder,
viterbi_decode), viterbi kernel paddle/phi/kernels/cpu/viterbi_decode_kernel.cc:156.

TPU-native design: the CRF decode is two `lax.scan`s (forward max-product +
reverse backtrace) over static-length sequences with length masking — the
reference's per-step mask/gather loop maps 1:1 onto scan carries.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, unwrap, wrap
from ..nn.layer import Layer
from .datasets import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,
                       WMT14, WMT16)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


def _arr(x):
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)


def _viterbi_scan(pot, trans, lengths, include_bos_eos_tag):
    B, L, T = pot.shape
    left = lengths.astype(jnp.int32)[:, None]  # [B,1]
    alpha = pot[:, 0]
    if include_bos_eos_tag:
        start, stop = trans[-1], trans[-2]
        alpha = alpha + start[None]
        alpha = alpha + jnp.where(left == 1, stop[None], 0.0)
    else:
        stop = None
    left = left - 1

    def fwd(carry, logit):
        alpha, left = carry
        trn_sum = alpha[:, :, None] + trans[None]      # [B, prev, curr]
        idx = jnp.argmax(trn_sum, axis=1)              # backpointers [B,T]
        nxt = jnp.max(trn_sum, axis=1) + logit
        alpha2 = jnp.where(left > 0, nxt, alpha)
        if stop is not None:
            alpha2 = alpha2 + jnp.where(left == 1, stop[None], 0.0)
        return (alpha2, left - 1), idx

    (alpha, left), hist = lax.scan(
        fwd, (alpha, left), jnp.swapaxes(pot[:, 1:], 0, 1))
    scores = jnp.max(alpha, axis=-1)
    last_ids = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]
    leftb = left[:, 0]                                  # lengths - L

    path_last = last_ids * (leftb >= 0)

    def bwd(carry, h):
        last_ids, leftb = carry
        leftb2 = leftb + 1
        upd = jnp.take_along_axis(h, last_ids[:, None], 1)[:, 0]
        upd = upd * (leftb2 > 0)
        upd = jnp.where(leftb2 == 0, last_ids, upd)
        new_last = jnp.where(leftb2 < 0, last_ids, upd)
        return (new_last, leftb2), upd

    _, rev = lax.scan(bwd, (last_ids, leftb), hist.astype(jnp.int32),
                      reverse=True)
    path = jnp.concatenate([jnp.swapaxes(rev, 0, 1), path_last[:, None]], 1)
    return scores, path


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag path. potentials [B, L, T], transitions [T, T],
    lengths [B] → (scores [B], paths [B, max(lengths)])."""
    pot = _arr(potentials).astype(jnp.float32)
    trans = _arr(transition_params).astype(jnp.float32)
    lens = _arr(lengths)
    scores, path = _viterbi_scan(pot, trans, lens, include_bos_eos_tag)
    max_len = int(np.asarray(lens).max())
    return (wrap(scores, stop_gradient=False),
            wrap(path[:, :max_len]))


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
