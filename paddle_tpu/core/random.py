"""Global RNG state for eager mode + scoped keys for jitted functions.

Reference parity: paddle.seed / get_rng_state (python/paddle/fluid/framework.py)
and the per-op CUDA philox streams. TPU-native design: a single root
``jax.random.PRNGKey`` plus a monotonically increasing fold-in counter gives
each eager random op a fresh, reproducible subkey. Inside a jitted step the
key must be explicit (functional purity), so layers pull keys from an active
:func:`rng_scope` instead — same call sites, both modes.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_state = threading.local()


def _tls():
    if not hasattr(_state, "key"):
        # ensure_compile_time_eval: first touch may happen inside a trace
        # (e.g. the static recorder's eval_shape); the global key must be
        # a concrete array, never a tracer that would leak out of scope
        with jax.ensure_compile_time_eval():
            _state.key = jax.random.PRNGKey(0)
        _state.count = 0
        _state.scopes = []
    return _state


def seed(s: int):
    tls = _tls()
    with jax.ensure_compile_time_eval():
        tls.key = jax.random.PRNGKey(int(s))
    tls.count = 0
    return tls.key


def get_rng_state():
    tls = _tls()
    return (tls.key, tls.count)


def set_rng_state(state):
    tls = _tls()
    tls.key, tls.count = state


class _Scope:
    __slots__ = ("key", "count")

    def __init__(self, key):
        self.key = key
        self.count = 0


@contextlib.contextmanager
def rng_scope(key):
    """Route random ops to subkeys of ``key`` (for use under jax.jit tracing)."""
    tls = _tls()
    tls.scopes.append(_Scope(key))
    try:
        yield
    finally:
        tls.scopes.pop()


def next_key():
    """Fresh subkey: from the innermost scope if active, else the global state."""
    tls = _tls()
    if tls.scopes:
        sc = tls.scopes[-1]
        k = jax.random.fold_in(sc.key, sc.count)
        sc.count += 1
        return k
    k = jax.random.fold_in(tls.key, tls.count)
    tls.count += 1
    return k


def in_rng_scope() -> bool:
    return bool(_tls().scopes)
