from . import dtype, random, tape, tensor  # noqa: F401
from .tape import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
