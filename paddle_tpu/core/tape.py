"""Define-by-run autograd tape.

TPU-native rethink of the reference's eager autograd engine
(/root/reference/paddle/fluid/eager/backward.cc:104 RunBackward,
grad_node_info.h:168 GradNodeBase): instead of per-op hand-written C++
GradNodes, every differentiable eager op is executed through ``jax.vjp`` and
the returned vjp closure *is* the grad node. Backward is a reverse traversal
over the recorded nodes in creation order — the same in-degree/ready-queue
semantics as the reference, collapsed onto JAX's functional AD.

The tape only serves the eager (dygraph-feeling) API. The performance path —
a jitted training step via ``paddle_tpu.jit`` — never records a tape; there
``jax.grad`` differentiates the whole step functionally.
"""
from __future__ import annotations

import contextlib
import threading

_state = threading.local()


def _tls():
    if not hasattr(_state, "enabled"):
        _state.enabled = True
        _state.counter = 0
    return _state


def tape_enabled() -> bool:
    return _tls().enabled


@contextlib.contextmanager
def no_grad():
    """Disable gradient recording (paddle.no_grad parity)."""
    tls = _tls()
    prev, tls.enabled = tls.enabled, False
    try:
        yield
    finally:
        tls.enabled = prev


@contextlib.contextmanager
def enable_grad():
    tls = _tls()
    prev, tls.enabled = tls.enabled, True
    try:
        yield
    finally:
        tls.enabled = prev


def set_grad_enabled(mode: bool):
    _tls().enabled = bool(mode)


class Node:
    """One recorded differentiable op: holds the vjp closure and the graph edges.

    Equivalent of the reference's GradNodeBase: ``parents`` are the
    differentiable input tensors (leaf params or intermediates), ``vjp`` maps
    output cotangents -> input cotangents.
    """

    __slots__ = ("id", "parents", "n_outputs", "out_ct", "name",
                 "_treedef", "_raw_vjp", "_out_avals", "out_hooks")

    def __init__(self, parents, n_outputs, name=""):
        tls = _tls()
        tls.counter += 1
        self.id = tls.counter
        self.parents = parents      # list[Tensor] (the differentiable inputs)
        self.n_outputs = n_outputs
        self.out_ct = [None] * n_outputs  # cotangent accumulators
        self.name = name
        self._treedef = None
        self._raw_vjp = None
        self._out_avals = None      # [(shape, dtype)] for zero-cotangent fill
        self.out_hooks = None       # out_index -> [(hook_id, fn)] (register_hook)

    def release(self):
        self._raw_vjp = None
        self.out_ct = [None] * self.n_outputs
