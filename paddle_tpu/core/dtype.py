"""Dtype aliases and conversion helpers.

Mirrors the reference's dtype surface (paddle/phi/common/data_type.h) with
jax.numpy dtypes as the single source of truth.
"""
import jax.numpy as jnp
import numpy as np

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "float16": float16, "fp16": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}


def convert_dtype(dtype):
    """Normalize a dtype-like (str / np dtype / jnp dtype) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"unknown dtype string: {dtype!r}")
        return _STR2DTYPE[dtype]
    return jnp.dtype(dtype)


def dtype_name(dtype):
    return np.dtype(dtype).name if dtype != bfloat16 else "bfloat16"


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
