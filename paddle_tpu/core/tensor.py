"""Tensor facade over jax.Array with eager autograd.

Design (vs reference /root/reference/paddle/phi/core/dense_tensor.h +
paddle/fluid/eager/autograd_meta.h): a Tensor is a thin Python wrapper holding
a ``jax.Array`` (or a JAX tracer, when used inside a jitted function via
``paddle_tpu.jit.functional_call``), a ``stop_gradient`` flag (paddle
semantics: True means "do not differentiate w.r.t. this"), an accumulated
``grad``, and an optional tape ``Node`` linking it into the autograd graph.

Every eager op goes through :func:`dispatch` — the single Python-level
boundary replacing the reference's per-op pybind/python-C crossing
(paddle/fluid/pybind/eager_method.cc). Under a jit trace the tape is off and
dispatch degenerates to a plain function call on tracers, so the same layer
code serves both eager and compiled execution.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .tape import Node, no_grad, tape_enabled

__all__ = [
    "Tensor", "Parameter", "to_tensor", "dispatch", "unwrap", "wrap",
    "param_substitution", "no_grad",
]

_subst = threading.local()
_amp = None  # lazy paddle_tpu.amp module ref (avoids circular import)


def _subst_map():
    m = getattr(_subst, "map", None)
    return m if m is not None else None


@contextlib.contextmanager
def param_substitution(mapping):
    """Temporarily substitute tensor values by ``id(tensor)`` (jit tracing).

    Used by ``paddle_tpu.jit.functional_call`` to run an eagerly-built Layer
    with traced parameter values, giving a pure function over a params pytree.
    """
    prev = getattr(_subst, "map", None)
    _subst.map = dict(mapping) if prev is None else {**prev, **mapping}
    try:
        yield
    finally:
        _subst.map = prev


def unwrap(x):
    """Tensor -> underlying value (honoring any active substitution)."""
    if isinstance(x, Tensor):
        m = _subst_map()
        if m is not None:
            v = m.get(id(x))
            if v is not None:
                return v
        return x._value
    return x


def wrap(value, stop_gradient=True):
    t = Tensor.__new__(Tensor)
    t._value = value
    t.stop_gradient = stop_gradient
    t.grad = None
    t._node = None
    t._out_index = 0
    t.name = None
    t._hooks = None
    return t


def _is_diff(a):
    return isinstance(a, Tensor) and not a.stop_gradient


_hook_counter = [0]


def _next_hook_id():
    _hook_counter[0] += 1
    return _hook_counter[0]


class _HookHandle:
    """Removable handle returned by Tensor.register_hook (reference:
    paddle.fluid.dygraph.tensor_patch_methods TensorHookRemoveHelper)."""

    __slots__ = ("_hooks", "_hid")

    def __init__(self, hooks, hid):
        self._hooks = hooks
        self._hid = hid

    def remove(self):
        if self._hooks is None:
            return False
        for i, (hid, _fn) in enumerate(self._hooks):
            if hid == self._hid:
                del self._hooks[i]
                self._hooks = None
                return True
        self._hooks = None
        return False


# Static-graph recorder hook (installed by paddle_tpu.static.graph). When a
# program_guard is active and any arg is a symbolic Variable, the op is
# recorded into the Program instead of executed (reference: OpDesc appended to
# BlockDesc by the static API, paddle/fluid/framework/framework.proto).
_static_recorder = None


def set_static_recorder(recorder):
    global _static_recorder
    _static_recorder = recorder


_check_nan_inf = False      # FLAGS_check_nan_inf (phi/core/flags.cc:62)
_check_nan_inf_level = 0    # 0 = raise, >=1 = warn


def _flag_truthy(v):
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    return s not in ("", "0", "false", "no", "off", "none")


def set_nan_inf_check(enabled, level=0):
    """Numerical sanitizer toggle (reference FLAGS_check_nan_inf: per-op
    device-side scans, framework/details/nan_inf_utils_detail.cu; eager
    hook eager/nan_inf_utils.cc). Wired from runtime.set_flags; accepts the
    env-protocol strings ('1'/'true'/'false'/...) and bools."""
    global _check_nan_inf, _check_nan_inf_level
    _check_nan_inf = _flag_truthy(enabled)
    try:
        _check_nan_inf_level = int(str(level))
    except (TypeError, ValueError):
        _check_nan_inf_level = 1 if _flag_truthy(level) else 0


def _nan_inf_scan(name, out):
    import jax
    import numpy as np
    flat, _ = jax.tree_util.tree_flatten(out)
    for v in flat:
        if isinstance(v, jax.core.Tracer):
            continue  # traced graphs: use jax_debug_nans instead
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(v))):
                arr = np.asarray(v)
                msg = (f"Operator {name or '<anonymous>'} output contains "
                       f"Inf/Nan: {int(np.isnan(arr).sum())} nan, "
                       f"{int(np.isinf(arr).sum())} inf "
                       f"(shape {arr.shape})")
                if _check_nan_inf_level >= 1:
                    import warnings
                    warnings.warn(msg)
                else:
                    raise FloatingPointError(msg)


def dispatch(fn, *args, name=None, nondiff_args=(), static_out_aval=None,
             **kwargs):
    """Execute ``fn(*values, **kwargs)``; record a vjp node if needed.

    ``fn`` must be a JAX-traceable function of positional array args.
    Positions listed in ``nondiff_args`` are never differentiated (e.g.
    integer index inputs). Returns Tensor(s) when any input was a Tensor,
    raw value(s) otherwise (so the same code path serves jit tracing).
    """
    global _amp
    if _static_recorder is not None and _static_recorder.active(args):
        return _static_recorder.record(fn, args, kwargs, name=name,
                                       static_out_aval=static_out_aval)
    any_tensor = any(isinstance(a, Tensor) for a in args)
    vals = [unwrap(a) for a in args]
    # AMP O1: cast inputs by white/black list membership (amp/__init__.py)
    if _amp is None:
        from .. import amp as _amp_mod
        _amp = _amp_mod
    st = _amp.amp_state()
    if st.enabled:
        vals = _amp.cast_inputs_for_op(
            name or getattr(fn, "__name__", ""), vals, st)
    record = (
        any_tensor
        and tape_enabled()
        and _subst_map() is None
        and any(_is_diff(a) for i, a in enumerate(args) if i not in nondiff_args)
    )
    if not record:
        out = fn(*vals, **kwargs)
        if _check_nan_inf:
            _nan_inf_scan(name or getattr(fn, "__name__", None), out)
        if not any_tensor:
            return out
        return jax.tree_util.tree_map(lambda v: wrap(v), out)

    diff_pos = [
        i for i, a in enumerate(args) if _is_diff(a) and i not in nondiff_args
    ]

    def f(*diff_vals):
        vv = list(vals)
        for p, v in zip(diff_pos, diff_vals):
            vv[p] = v
        return fn(*vv, **kwargs)

    out_vals, vjp = jax.vjp(f, *[vals[p] for p in diff_pos])
    if _check_nan_inf:
        _nan_inf_scan(name or getattr(fn, "__name__", None), out_vals)
    flat, treedef = jax.tree_util.tree_flatten(out_vals)
    node = Node(
        parents=[args[p] for p in diff_pos],
        n_outputs=len(flat),
        name=name or getattr(fn, "__name__", "op"),
    )
    node._treedef = treedef
    node._raw_vjp = vjp
    node._out_avals = [(v.shape, v.dtype) for v in flat]
    outs = []
    for i, v in enumerate(flat):
        t = wrap(v, stop_gradient=False)
        t._node = node
        t._out_index = i
        outs.append(t)
    return jax.tree_util.tree_unflatten(treedef, outs)


def _ones_like(v):
    return jnp.ones_like(v)


def _run_hooks(hooks, g):
    """Apply register_hook callbacks to a raw cotangent value. A hook gets a
    Tensor and may return a replacement (Tensor/array) or None (keep)."""
    for _hid, h in list(hooks):
        r = h(wrap(g))
        if r is not None:
            g = unwrap(r)
    return g


def backward(tensor, grad_tensor=None, retain_graph=False):
    """Reverse-mode traversal (reference: egr::RunBackward, backward.cc:104).

    Seeds the cotangent of ``tensor``, walks reachable Nodes in reverse
    creation order, runs each vjp once all its output cotangents are known
    (creation order guarantees readiness), accumulates into leaf ``.grad``.
    Tensor hooks (register_hook, reference eager/hooks.h TensorHook) fire on
    the finalized cotangent of their tensor: for intermediates just before
    the producing node's vjp consumes it, for leaves once per backward with
    the fully accumulated gradient, before accumulation into ``.grad``.
    """
    if tensor._node is None:
        if not tensor.stop_gradient:
            g = _ones_like(tensor._value) if grad_tensor is None else unwrap(grad_tensor)
            if tensor._hooks:
                g = _run_hooks(tensor._hooks, g)
            tensor.grad = wrap(g) if tensor.grad is None else wrap(tensor.grad._value + g)
        return

    seed = _ones_like(tensor._value) if grad_tensor is None else unwrap(grad_tensor)
    tensor._node.out_ct[tensor._out_index] = seed

    # Collect reachable nodes from the seed node.
    reachable = {}
    stack = [tensor._node]
    while stack:
        n = stack.pop()
        if n.id in reachable:
            continue
        reachable[n.id] = n
        for p in n.parents:
            if p._node is not None:
                stack.append(p._node)

    pending_leaf = {}  # id(tensor) -> [tensor, accumulated g] for hooked leaves
    for nid in sorted(reachable, reverse=True):
        node = reachable[nid]
        if all(ct is None for ct in node.out_ct):
            continue  # not on the path from the seed
        cts = [
            ct if ct is not None
            else jnp.zeros(node._out_avals[i][0], node._out_avals[i][1])
            for i, ct in enumerate(node.out_ct)
        ]
        if node.out_hooks:
            for idx, hooks in node.out_hooks.items():
                # fire only when gradient actually reached this output
                # (paddle semantics: no phantom hook calls on zero fills)
                if node.out_ct[idx] is not None:
                    cts[idx] = _run_hooks(hooks, cts[idx])
        in_cts = node._raw_vjp(jax.tree_util.tree_unflatten(node._treedef, cts))
        for parent, g in zip(node.parents, in_cts):
            if parent._node is not None and parent._node.id in reachable:
                slot = parent._node
                cur = slot.out_ct[parent._out_index]
                slot.out_ct[parent._out_index] = g if cur is None else cur + g
            if parent._node is None or parent.is_leaf:
                if parent._hooks:
                    ent = pending_leaf.get(id(parent))
                    if ent is None:
                        pending_leaf[id(parent)] = [parent, g]
                    else:
                        ent[1] = ent[1] + g
                else:
                    parent.grad = (
                        wrap(g) if parent.grad is None else wrap(parent.grad._value + g)
                    )
        if not retain_graph:
            node.release()

    for parent, g in pending_leaf.values():
        g = _run_hooks(parent._hooks, g)
        parent.grad = (
            wrap(g) if parent.grad is None else wrap(parent.grad._value + g)
        )


class Tensor:
    """Eager tensor. Value semantics follow paddle.Tensor where sensible."""

    __slots__ = ("_value", "stop_gradient", "grad", "_node", "_out_index",
                 "name", "_hooks", "__weakref__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None):
        dtype = dtypes.convert_dtype(dtype)
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value, dtype=dtype)
        elif dtype is not None and value.dtype != dtype:
            value = value.astype(dtype)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_index = 0
        self.name = name
        self._hooks = None

    # -- structural properties ------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def value(self):
        return unwrap(self)

    def numpy(self):
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        return self._value.shape[0]

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
            f"stop_gradient={self.stop_gradient},\n{self._value})"
        )

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __bool__(self):
        return bool(self._value)

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    # -- autograd -------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        return wrap(unwrap(self), stop_gradient=True)

    def clone(self):
        return dispatch(lambda v: v + 0, self, name="clone")

    def register_hook(self, hook):
        """Register a gradient hook (paddle.Tensor.register_hook parity;
        reference: eager/hooks.h TensorHook + tensor_wrapper registration).
        ``hook(grad) -> Tensor|None`` runs during backward on this tensor's
        cotangent; a non-None return replaces the gradient. Returns a
        removable handle (``handle.remove()``)."""
        if self.stop_gradient:
            raise RuntimeError(
                "register_hook on a tensor with stop_gradient=True has no "
                "effect; set stop_gradient=False first")
        hid = _next_hook_id()
        entry = (hid, hook)
        if self._node is not None:
            if self._node.out_hooks is None:
                self._node.out_hooks = {}
            hooks = self._node.out_hooks.setdefault(self._out_index, [])
            hooks.append(entry)
        else:
            if self._hooks is None:
                self._hooks = []
            hooks = self._hooks
            hooks.append(entry)
        return _HookHandle(hooks, hid)

    # -- mutation (eager convenience; invisible to any recorded graph) --------
    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        self._value = v.astype(self._value.dtype)

    def copy_(self, other):
        self.set_value(other)

    def _replace_value(self, value):
        self._value = value

    # Methods attached dynamically by paddle_tpu.ops (astype, reshape, matmul,
    # sum, mean, ...) — see ops/registry.py:install_tensor_methods.


class Parameter(Tensor):
    """Trainable tensor (reference: paddle Parameter / phi DenseTensor+grad)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "no_sync", "_sharding_axes")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.no_sync = False
        self._sharding_axes = None  # PartitionSpec-like hint for auto-parallel


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (place maps to jax default device)."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
