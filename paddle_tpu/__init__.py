"""paddle_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of
PaddlePaddle (reference mounted at /root/reference; see SURVEY.md for the
layer map). The eager API feels like paddle dygraph; the performance path is
one jitted XLA step (paddle_tpu.jit), parallelism is mesh + GSPMD/shard_map
(paddle_tpu.distributed), and hot kernels are Pallas (paddle_tpu.ops.pallas).
"""
__version__ = "0.1.0"

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import ops  # noqa: F401
from .core import random as _random_mod  # noqa: F401
from .core.random import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tape import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64, int8,
    int16, int32, int64, uint8,
)
from .ops.registry import OPS as _OPS
from .ops.registry import install_tensor_methods as _install_tm

# second pass: nn.functional etc. registered more ops (relu, softmax, …)
# after paddle_tpu.ops ran its install — pick up their method/inplace
# variants too (idempotent)
_install_tm()

# re-export every registered op at top level (paddle.* flat namespace parity)
_g = globals()
for _name, _op in _OPS.items():
    _g.setdefault(_name, _op)
del _g


def __getattr__(name):
    # ops registered after import (e.g. distributed extensions)
    if name in _OPS:
        return _OPS[name]
    if name == "distributed":  # canonical home is paddle_tpu.parallel
        import importlib
        mod = importlib.import_module(".parallel", __name__)
        globals()[name] = mod
        return mod
    if name in ("parallel", "io", "hapi", "metric", "profiler", "vision",
                "models", "utils", "incubate", "static", "device", "runtime",
                "inference", "sparse", "text", "audio", "geometric",
                "quantization", "distribution", "fft", "signal",
                "regularizer"):
        import importlib
        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ImportError as e:  # keep hasattr() working for probes
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def Model(*args, **kwargs):
    from .hapi.model import Model as _M
    return _M(*args, **kwargs)


def DataParallel(*args, **kwargs):
    from .parallel.api import DataParallel as _DP
    return _DP(*args, **kwargs)


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return True


def get_default_dtype():
    return _dtype_mod.float32


_default_dtype = [_dtype_mod.float32]


def set_default_dtype(d):
    _default_dtype[0] = _dtype_mod.convert_dtype(d)


def disable_static(place=None):
    """paddle.disable_static parity: leave global static-graph mode."""
    from .static import graph as _g
    _g.disable_static_mode()


def enable_static():
    """paddle.enable_static parity: ops on static.data Variables record
    into default_main_program (reference: paddle/fluid/framework.py
    _dygraph_guard off). Eager Tensors keep working — recording only
    triggers on symbolic Variables, so the trace-based eager path and the
    recorded static path coexist."""
    from .static import graph as _g
    _g.enable_static_mode()


def in_dynamic_mode():
    from .static import graph as _g
    return not _g.in_static_mode()


def grad(*args, **kwargs):
    return autograd.grad(*args, **kwargs)


def device_count():
    import jax
    return jax.device_count()


def set_device(device):
    return device


def get_device():
    import jax
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def synchronize():
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def save(obj, path, **kwargs):
    from .io.save_load import save as _save
    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .io.save_load import load as _load
    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops
    return _flops(net, input_size)
