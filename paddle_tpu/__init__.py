"""paddle_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of
PaddlePaddle (reference mounted at /root/reference; see SURVEY.md for the
layer map). The eager API feels like paddle dygraph; the performance path is
one jitted XLA step (paddle_tpu.jit), parallelism is mesh + GSPMD/shard_map
(paddle_tpu.distributed), and hot kernels are Pallas (paddle_tpu.ops.pallas).
"""
__version__ = "0.1.0"

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import ops  # noqa: F401
from .core import random as _random_mod  # noqa: F401
from .core.random import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tape import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64, int8,
    int16, int32, int64, uint8,
)
from .ops.registry import OPS as _OPS
from .ops.registry import install_method_tail as _install_mt
from .ops.registry import install_tensor_methods as _install_tm

# second pass: nn.functional etc. registered more ops (relu, softmax, …)
# after paddle_tpu.ops ran its install — pick up their method/inplace
# variants too (idempotent)
_install_tm()
_install_mt()

# re-export every registered op at top level (paddle.* flat namespace parity)
_g = globals()
for _name, _op in _OPS.items():
    _g.setdefault(_name, _op)
del _g


def __getattr__(name):
    # ops registered after import (e.g. distributed extensions)
    if name in _OPS:
        return _OPS[name]
    if name == "distributed":  # canonical home is paddle_tpu.parallel
        import importlib
        mod = importlib.import_module(".parallel", __name__)
        globals()[name] = mod
        return mod
    if name in ("parallel", "io", "hapi", "metric", "profiler", "vision",
                "models", "utils", "incubate", "static", "device", "runtime",
                "inference", "sparse", "text", "audio", "geometric",
                "quantization", "distribution", "fft", "signal",
                "regularizer", "linalg", "onnx", "callbacks", "hub",
                "sysconfig", "reader", "cost_model", "telemetry",
                "reliability"):
        import importlib
        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ImportError as e:  # keep hasattr() working for probes
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def Model(*args, **kwargs):
    from .hapi.model import Model as _M
    return _M(*args, **kwargs)


def DataParallel(*args, **kwargs):
    from .parallel.api import DataParallel as _DP
    return _DP(*args, **kwargs)


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return True


def get_default_dtype():
    return _dtype_mod.float32


_default_dtype = [_dtype_mod.float32]


def set_default_dtype(d):
    _default_dtype[0] = _dtype_mod.convert_dtype(d)


def disable_static(place=None):
    """paddle.disable_static parity: leave global static-graph mode."""
    from .static import graph as _g
    _g.disable_static_mode()


def enable_static():
    """paddle.enable_static parity: ops on static.data Variables record
    into default_main_program (reference: paddle/fluid/framework.py
    _dygraph_guard off). Eager Tensors keep working — recording only
    triggers on symbolic Variables, so the trace-based eager path and the
    recorded static path coexist."""
    from .static import graph as _g
    _g.enable_static_mode()


def in_dynamic_mode():
    from .static import graph as _g
    return not _g.in_static_mode()


def grad(*args, **kwargs):
    return autograd.grad(*args, **kwargs)


def device_count():
    import jax
    return jax.device_count()


def set_device(device):
    return device


def get_device():
    import jax
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def synchronize():
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def save(obj, path, **kwargs):
    from .io.save_load import save as _save
    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .io.save_load import load as _load
    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops
    return _flops(net, input_size)


# ------------------------------------------------ top-level parity tail
# (reference python/paddle/__init__.py __all__)

dtype = _dtype_mod.DType if hasattr(_dtype_mod, "DType") else str
bool = _dtype_mod.bool_          # noqa: A001 — paddle.bool dtype alias


def iinfo(dt):
    import numpy as _np
    return _np.iinfo(_dtype_mod.convert_dtype(dt))


def finfo(dt):
    import numpy as _np
    return _np.finfo(_dtype_mod.convert_dtype(dt))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    import jax.numpy as _jnp
    d = x.dtype if hasattr(x, "dtype") else x
    return _jnp.issubdtype(_dtype_mod.convert_dtype(d), _jnp.complexfloating)


def is_integer(x):
    import jax.numpy as _jnp
    d = x.dtype if hasattr(x, "dtype") else x
    return _jnp.issubdtype(_dtype_mod.convert_dtype(d), _jnp.integer)


def is_floating_point(x):
    import jax.numpy as _jnp
    d = x.dtype if hasattr(x, "dtype") else x
    return _jnp.issubdtype(_dtype_mod.convert_dtype(d), _jnp.floating)


def rank(x):
    """paddle.rank: 0-d tensor holding ndim."""
    import jax.numpy as _jnp
    v = x._value if isinstance(x, Tensor) else x
    return to_tensor(_jnp.asarray(v.ndim, _jnp.int32))


def is_grad_enabled():
    from .core.tape import tape_enabled
    return tape_enabled()


def tolist(x):
    return (x.numpy() if isinstance(x, Tensor) else x).tolist()


def floor_mod(x, y):
    return _OPS["mod"](x, y)


def broadcast_shape(x_shape, y_shape):
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def get_cuda_rng_state():
    """CUDA-API-shaped alias over the TPU/global RNG state."""
    return [get_rng_state()]


def set_cuda_rng_state(state):
    set_rng_state(state[0] if isinstance(state, (list, tuple)) else state)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    pass  # reference installs fault handlers; nothing to disable here


class LazyGuard:
    """paddle.LazyGuard parity: in the reference this defers parameter
    materialization; initialization here is already cheap/deferred to
    first use, so the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class CPUPlace:
    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(gpu:{self.device_id})"


class CUDAPinnedPlace:
    def __repr__(self):
        return "Place(gpu_pinned)"


class NPUPlace(CUDAPlace):
    def __repr__(self):
        return f"Place(npu:{self.device_id})"


class TPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(tpu:{self.device_id})"


def ParamAttr(name=None, initializer=None, learning_rate=1.0,
              regularizer=None, trainable=True, do_model_average=True,
              need_clip=True):
    from .nn.param_attr import ParamAttr as _PA
    return _PA(name=name, initializer=initializer,
               learning_rate=learning_rate, regularizer=regularizer,
               trainable=trainable, do_model_average=do_model_average,
               need_clip=need_clip)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter parity (static+eager helper)."""
    from .nn import initializer as I
    init = default_initializer
    if attr is not None and getattr(attr, "initializer", None) is not None:
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    p = Parameter(init(tuple(shape), _dtype_mod.convert_dtype(dtype)))
    if name:
        p.name = name
    return p


def batch(reader, batch_size, drop_last=False):
    """Legacy reader-decorator (reference python/paddle/batch.py)."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return gen


def _tensor_method_alias(op, name):
    def f(x, *args, **kwargs):
        return _OPS[op](x, *args, **kwargs) if op in _OPS else \
            getattr(x, name)(*args, **kwargs)
    f.__name__ = name
    return f


def tanh_(x):
    return x.tanh_()


def scatter_(x, index, updates, overwrite=True):
    # Tensor method form snapshots the pre-mutation tape identity so the
    # recorded node's parent is the old value, not the rebound self
    return x.scatter_(index, updates, overwrite)


def reshape_(x, shape):
    return x.reshape_(shape)


def squeeze_(x, axis=None):
    return x.squeeze_(axis)


def unsqueeze_(x, axis):
    return x.unsqueeze_(axis)


def set_flags(flags):
    from .runtime import set_flags as _sf
    return _sf(flags)


def get_flags(names):
    from .runtime import get_flags as _gf
    return _gf(names)


def check_shape(x, shape):
    """Assert a tensor's shape (reference static check helper)."""
    import builtins
    got = list(x.shape)
    want = list(shape)
    # NB: bare `all` here would hit the re-exported paddle op
    ok = len(got) == len(want) and builtins.all(
        w in (-1, None) or g == w for g, w in zip(got, want))
    if not ok:
        raise ValueError(f"shape mismatch: got {got}, expected {want}")
    return x


def broadcast_tensors(inputs):
    """paddle.broadcast_tensors parity: broadcast all to a common shape."""
    import numpy as _np
    shapes = [tuple(t.shape) for t in inputs]
    target = _np.broadcast_shapes(*shapes)
    return [_OPS["broadcast_to"](t, list(target)) for t in inputs]


def index_add_(x, index, axis, value):
    return x.index_add_(index, axis, value)


def index_add(x, index, axis, value):
    return _OPS["index_add"](x, index, axis, value)
