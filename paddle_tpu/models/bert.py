"""BERT family (encoder-only, learned positions + token types, GELU).

Reference parity target: the dy2static/hapi BERT suites
(python/paddle/fluid/tests/unittests/dygraph_to_static/test_bert.py,
PaddleNLP-style BertModel surface: sequence output + pooled output,
MLM + NSP pretraining heads). Built from paddle_tpu.nn so one definition
serves eager, jit, GSPMD TP (via dp/mp sharding of the dense layers),
and PipelineLayer segmentation.
"""
from dataclasses import dataclass

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "bert_base", "bert_tiny"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as pt
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = pt.arange(0, seq, 1).astype("int64")
        if token_type_ids is None:
            token_type_ids = pt.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertModel(nn.Layer):
    """Returns (sequence_output [B,S,H], pooled_output [B,H])."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            d_model=cfg.hidden_size, nhead=cfg.num_heads,
            dim_feedforward=cfg.intermediate_size, dropout=cfg.dropout,
            activation="gelu", normalize_before=False)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    @staticmethod
    def _extend_mask(attention_mask):
        """[B, S] 1/0 (or bool) keep-mask -> additive [B, 1, 1, S]
        (PaddleNLP BertModel.get_extended_attention_mask semantics)."""
        if attention_mask is None:
            return None
        m = attention_mask
        if len(m.shape) == 2:
            # [B, S] int/float 1-0 keep-mask: broadcast + additive here
            # (downstream only converts bool masks)
            m = m.unsqueeze(1).unsqueeze(1)
            if "bool" not in str(m.dtype):
                return (m.astype("float32") - 1.0) * 1e9
        # bool masks (any rank) and pre-broadcast additive floats pass
        # through: nn/transformer.py _convert_attention_mask is the single
        # canonical bool->additive conversion
        return m

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None, position_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(h, src_mask=self._extend_mask(attention_mask))
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference BertPretrainingHeads surface)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.mlm_decoder = nn.Linear(cfg.hidden_size, cfg.vocab_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        return self.mlm_decoder(h), self.nsp(pooled)

    def loss(self, mlm_logits, nsp_logits, masked_labels, nsp_labels,
             ignore_index=-100):
        mlm = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
            masked_labels.reshape([-1]), ignore_index=ignore_index)
        nsp = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("dropout", 0.0)
    return BertConfig(**kw)
