"""Model-level text generation on the on-device decode loop.

The reference serves autoregressive models through per-token host loops
around fused ops (fused_multi_transformer_op.cu time_step path); the
generation filters (top-k/top-p/temperature) live in its incubate
generation utils. Here the whole pipeline — prefill, KV-cache decode,
logits filtering, sampling — compiles to two XLA programs (one prefill,
one `lax.scan` decode; inference/decode_loop.py), so host dispatch is
paid once per sequence.

Design: instead of threading mutable cache state through every
``nn.Layer.forward`` (the torch/reference pattern), each CausalLM model
decomposes into PURE step functions over its raw parameter tree — the
same approach its ``pipeline_decompose`` uses for pipeline parallelism.
``GenerationMixin.generate`` is the user API on GPTForCausalLM and
LlamaForCausalLM.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import unwrap, wrap

__all__ = ["GenerationMixin"]


def _stacked(blocks, name):
    return jnp.stack([unwrap(b[name]) for b in blocks])


_QUANT_WEIGHTS = frozenset({
    "wq", "wk", "wv", "wo", "wg", "wu", "wd",            # llama/mixtral
    "attn.qkv.weight", "attn.proj.weight",               # gpt
    "mlp.fc1.weight", "mlp.fc2.weight",
})


def _quantize_tree(p):
    """Weight-only int8: every matmul weight (explicit allowlist) becomes
    an (int8, fp32 scale) pair with per-output-channel scales — decode
    streams HALF the weight bytes from HBM (the decode roofline; cf.
    bench.py decode HBM-util accounting). Norms/embeddings/router/biases
    stay full precision; the lm head does too (logit fidelity)."""
    def q(name, w):
        if name not in _QUANT_WEIGHTS:
            return w
        # reduce over the contraction dim (axis -2): per-(layer, expert,
        # out-channel) scales — NOT shared across the stacked layer dim
        amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
        s = amax.astype(jnp.float32) / 127.0 + 1e-12
        qw = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
        return (qw, s)

    return {k: q(k, v) for k, v in p.items()}


def _apply_mesh(p, mesh, shard_dims, axis="mp"):
    """Tensor-parallel weight placement for decode: ``shard_dims`` maps
    weight name -> dimension index to shard over the mesh's ``axis``
    (column-parallel out-dims, row-parallel contraction dims, or the
    expert dim). Everything else — and any dim not divisible by the axis
    size — is placed replicated, so the whole tree lives on the mesh and
    one jit compiles an SPMD decode (GSPMD inserts the collectives,
    exactly as the training-side TP layers rely on)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    size = mesh.shape[axis]
    rep = NamedSharding(mesh, P())

    def place(name, w):
        main = w[0] if isinstance(w, tuple) else w
        dim = shard_dims.get(name)
        if dim is not None and main.shape[dim] % size == 0:
            spec = P(*[axis if i == dim else None
                       for i in range(main.ndim)])
            sh = NamedSharding(mesh, spec)
        else:
            sh = rep
        if isinstance(w, tuple):          # int8 (weights, scales)
            return (jax.device_put(w[0], sh), jax.device_put(w[1], rep))
        return jax.device_put(w, sh)

    return {k: place(k, v) for k, v in p.items()}


def _mesh_caches(init_caches, mesh):
    """Replicate fresh KV caches over the mesh so every array in the
    decode jit shares one device set."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def init(batch):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            init_caches(batch))

    return init


def paged_pool_shards(mesh, num_kv_heads, axis="mp"):
    """How many ways the paged K/V pool is sharded on ``mesh``: the
    ``axis`` size when it divides the kv-head count, else 1 (the
    replicated fallback, mirroring ``_apply_mesh``'s weight rule).
    Host-side bookkeeping (allocator, prefix cache, postmortems) uses
    this to report per-shard balance without touching device state."""
    if mesh is None:
        return 1
    size = int(dict(mesh.shape).get(axis, 1))
    return size if size > 1 and num_kv_heads % size == 0 else 1


def _mesh_paged_caches(init_caches, mesh, axis="mp"):
    """Mesh placement for a fresh PAGED cache tree: the global K/V page
    pools shard on the kv-head dimension (axis 3 of
    ``[layers, num_pages, page_size, kvh, hd]``) over the mesh's
    ``axis`` — per-device pool bytes shrink by 1/mp at fixed page
    capacity, the capacity unlock of ROADMAP item 1 — while the block
    table stays REPLICATED: page ids are global, so the host-side
    allocator, grow/preempt/donate, and the prefix radix tree never
    learn the mesh exists. A kv-head count the axis size doesn't divide
    falls back to a replicated pool (``paged_pool_shards`` reports 1),
    exactly like ``_apply_mesh`` does for weights."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    rep = NamedSharding(mesh, P())

    def init(batch):
        tree = init_caches(batch)
        kvh = tree["pool"]["k"].shape[3]
        if paged_pool_shards(mesh, kvh, axis) > 1:
            sh = NamedSharding(mesh, P(None, None, None, axis, None))
        else:
            sh = rep
        return dict(tree,
                    pool={n: jax.device_put(a, sh)
                          for n, a in tree["pool"].items()},
                    bt=jax.device_put(tree["bt"], rep))

    return init


def _mm(x, w):
    """x @ w where w is a raw array or an (int8, scale) pair. The int8
    path casts tile-wise inside the fused matmul (XLA folds the convert
    into the HBM read) and applies the per-channel scale on the out."""
    if isinstance(w, tuple):
        qw, s = w
        return (x @ qw.astype(x.dtype)) * s.astype(x.dtype)
    return x @ w


def _emm(spec, x, w):
    """einsum analogue of _mm for stacked expert weights."""
    if isinstance(w, tuple):
        qw, s = w
        out = jnp.einsum(spec, x, qw.astype(x.dtype))
        # out [..., E, s, F]; scale [E, 1, F] broadcasts over the token dim
        return out * s.astype(x.dtype)
    return jnp.einsum(spec, x, w)


def _rms(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
            ).astype(x.dtype) * w


def _ln(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _positions(t, b, s):
    """Absolute positions [B, s] for a step at offset ``t`` — scalar
    (all rows aligned) or [B] (per-row offsets, continuous batching)."""
    row = jnp.arange(s, dtype=jnp.int32)
    if jnp.ndim(t) == 0:
        return (t + row)[None, :].repeat(b, 0)
    return t[:, None] + row[None, :]


def _cached_attend(q, k_cache, v_cache, t, s, scale):
    """q [B,s,nh,hd] at positions [t, t+s); caches [B,T,nh,hd] already
    updated through t+s. Masks unwritten/future slots: key position p is
    visible to query row r iff p <= t+r. ``t`` scalar or [B]."""
    T = k_cache.shape[1]
    logits = jnp.einsum("bsnd,btnd->bnst", q, k_cache) * scale
    pos = jnp.arange(T)
    row = _positions(t, q.shape[0], s)                   # [B, s]
    ok = pos[None, None] <= row[:, :, None]              # [B, s, T]
    logits = jnp.where(ok[:, None], logits.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, v_cache)


def _write_cache(cache, kv, t):
    """cache [B,T,h,hd] <- kv [B,s,h,hd] at positions [t, t+s); ``t``
    scalar or [B] (per-row write offsets)."""
    kv = kv.astype(cache.dtype)
    if jnp.ndim(t) == 0:
        return jax.lax.dynamic_update_slice_in_dim(cache, kv, t, axis=1)
    b, s = kv.shape[0], kv.shape[1]
    rows = jnp.arange(b)[:, None].repeat(s, 1)           # [B, s]
    cols = _positions(t, b, s)
    return cache.at[rows, cols].set(kv)


def _kv_write(lc, name, kv, t):
    """Write new k/v rows into this layer's cache dict. With an int8
    cache (a ``<name>s`` scale entry present) the rows are quantized
    per (batch, position, head): amax/127 scale, int8 payload — half
    the cache bytes decode streams every step (its roofline)."""
    if name + "s" in lc:
        amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), -1) + 1e-8
        sc = (amax / 127.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(kv.astype(jnp.float32) / sc[..., None]),
                     -127, 127).astype(jnp.int8)
        return dict(lc, **{name: _write_cache(lc[name], q, t),
                           name + "s": _write_cache(lc[name + "s"], sc,
                                                    t)})
    return dict(lc, **{name: _write_cache(lc[name], kv, t)})


def _kv_read(lc, name, dtype):
    """Full cache view [B,T,h,hd] in compute dtype (dequantized if the
    cache is int8 — the cast+scale fuses into the attention einsum)."""
    c = lc[name]
    if name + "s" in lc:
        return c.astype(dtype) * lc[name + "s"].astype(dtype)[..., None]
    return c


def _init_kv(shape, dtype, cache_dtype):
    lc = {}
    if cache_dtype == "int8":
        lc["k"] = jnp.zeros(shape, jnp.int8)
        lc["v"] = jnp.zeros(shape, jnp.int8)
        lc["ks"] = jnp.zeros(shape[:-1], jnp.float32)
        lc["vs"] = jnp.zeros(shape[:-1], jnp.float32)
    else:
        lc["k"] = jnp.zeros(shape, dtype)
        lc["v"] = jnp.zeros(shape, dtype)
    return lc


# ------------------------------------------------------ paged KV backend

def _check_paged_config(max_cache_len, page_size, num_pages, cache_dtype,
                        mesh):
    """Validate a paged-cache decode bundle request. ``page_size`` must
    divide ``max_cache_len`` so the block-table width times page size
    equals the dense cache length — that equality is what makes the
    paged decode path bit-identical to the dense one. A ``mesh`` is
    accepted as-is: the pool shards on the kv-head dim (or falls back
    to replicated) via ``_mesh_paged_caches`` — nothing to refuse."""
    if cache_dtype == "int8":
        raise NotImplementedError(
            "cache_dtype='int8' is not wired for the paged backend yet "
            "(ROADMAP item 3: quantized paged KV pool); use "
            "cache_backend='dense' with int8 caches")
    del mesh
    if not page_size or int(page_size) < 1:
        raise ValueError("paged backend needs page_size >= 1")
    if not num_pages or int(num_pages) < 2:
        raise ValueError("paged backend needs num_pages >= 2 (page 0 is "
                         "the reserved null page)")
    if max_cache_len % int(page_size):
        raise ValueError(
            f"page_size ({page_size}) must divide max_cache_len "
            f"({max_cache_len}) for dense/paged token parity")


def _init_paged_kv(batch, layers, num_pages, page_size, pages_per_slot,
                   kvh, hd, dtype):
    """Paged decode cache tree: one global K/V page pool per layer plus
    the per-slot block table (a RUNTIME argument of the decode program —
    page churn never recompiles)."""
    shape = (layers, num_pages, page_size, kvh, hd)
    return {"pool": {"k": jnp.zeros(shape, dtype),
                     "v": jnp.zeros(shape, dtype)},
            "bt": jnp.zeros((batch, pages_per_slot), jnp.int32)}


def _page_write(pool, kv, bt, t):
    """pool [P, pg, h, hd] <- kv [B, 1, h, hd] at per-slot positions
    ``t`` ([B] or scalar). A position past the block-table width is
    redirected to the null page (page 0), so the wasted decode steps of
    finished/inactive slots can never corrupt a live slot's pages (the
    dense analogue relies on out-of-bounds writes being dropped). The
    redirected payload is ZEROED: past the cache length, rope gathers
    beyond its table and returns jnp's NaN fill — and since every
    slot's unused block-table entries point at the null page, a stored
    NaN would poison every row's attention through 0-weight * NaN."""
    pg = pool.shape[1]
    b = kv.shape[0]
    maxp = bt.shape[1]
    if jnp.ndim(t) == 0:
        t = jnp.full((b,), t, jnp.int32)
    pidx = t // pg
    oob = pidx >= maxp
    page = jnp.where(oob, jnp.int32(0),
                     bt[jnp.arange(b), jnp.minimum(pidx, maxp - 1)])
    vals = kv[:, 0].astype(pool.dtype)
    vals = jnp.where(oob[:, None, None], jnp.zeros_like(vals), vals)
    return pool.at[page, t % pg].set(vals)


def _paged_attend(q, k_pool, v_pool, bt, t, scale, mesh=None):
    """Decode-step attention through the block table: q [B, 1, nh, hd],
    pools [P, pg, kvh, hd], valid lengths t+1 (cache already written
    through t). Pallas ragged kernel on TPU (per-kv-head-shard launches
    under ``mesh`` — XLA cannot partition a custom call, so the kernel
    path shard_maps itself), bit-exact dense-mirroring gather
    composition elsewhere (GSPMD partitions it from the pool's input
    sharding). Returns [B, 1, nh, hd]."""
    from ..ops.pallas.paged_attention import paged_attention
    b = q.shape[0]
    if jnp.ndim(t) == 0:
        t = jnp.full((b,), t, jnp.int32)
    return paged_attention(q[:, 0], k_pool, v_pool, bt, t + 1, scale,
                           mesh=mesh)[:, None]


def _page_write_seq(pool, kv, bt, t, last=None):
    """Ragged-prefill page write: pool [P, pg, h, hd] <- kv
    [B, s, h, hd] at per-slot position runs [t_b, t_b + s). The
    multi-token analogue of ``_page_write`` with the same null-page
    discipline: any position past the block-table width is redirected
    to page 0 with a ZEROED payload (padded chunk rows of idle slots
    carry rope's out-of-range NaN fill — a stored NaN in the null page
    would poison every slot's attention through 0-weight reads).
    Positions inside the table but past a slot's allocation land in its
    NULL_PAGE tail entries — finite garbage the length masks hide,
    exactly like a wasted decode step.

    ``last`` ([B] int32, optional): each slot's last VALID position —
    rows past it are null-redirected zeroed too. The fused tick passes
    it so a decode slot's C-row group writes exactly its one token
    (the C-1 pad rows never touch the slot's real pages) and an idle
    slot (``last = -1``) writes nothing at all."""
    pg = pool.shape[1]
    b, s = kv.shape[0], kv.shape[1]
    maxp = bt.shape[1]
    if jnp.ndim(t) == 0:
        t = jnp.full((b,), t, jnp.int32)
    P = _positions(t, b, s)                              # [B, s]
    pidx = P // pg
    oob = pidx >= maxp
    if last is not None:
        oob = jnp.logical_or(oob, P > last[:, None])
    page = jnp.where(
        oob, jnp.int32(0),
        jnp.take_along_axis(bt, jnp.minimum(pidx, maxp - 1), axis=1))
    vals = kv.astype(pool.dtype)
    vals = jnp.where(oob[..., None, None], jnp.zeros_like(vals), vals)
    n = b * s
    return pool.at[page.reshape(n), (P % pg).reshape(n)].set(
        vals.reshape((n,) + vals.shape[2:]))


def _paged_prefill_attend(q, k_pool, v_pool, bt, t, scale, mesh=None):
    """Ragged packed-prefill attention through the block table: q
    [B, s, nh, hd] chunk rows starting at per-slot offsets ``t``, pools
    [P, pg, kvh, hd]; row j of slot b attends to positions <= t_b + j
    (cache already written through the chunk). Pallas kernel on TPU,
    bit-exact dense-mirroring gather composition elsewhere. A slot
    carrying the scheduler's idle sentinel (``t`` past the block-table
    extent) is handed ``last = -1`` so the kernel skips its every page
    instead of sweeping NaN garbage; live slots scan at most one chunk
    width past their real frontier (the chunk's own padding rows)."""
    from ..ops.pallas.ragged_prefill import ragged_prefill_attention
    b, s = q.shape[0], q.shape[1]
    if jnp.ndim(t) == 0:
        t = jnp.full((b,), t, jnp.int32)
    limit = bt.shape[1] * k_pool.shape[1]          # tokens a table spans
    last = jnp.where(t >= limit, jnp.int32(-1), t + s - 1)
    return ragged_prefill_attention(q, k_pool, v_pool, bt, t, last=last,
                                    sm_scale=scale, mesh=mesh)


def _fused_attend(q, k_pool, v_pool, bt, t, last, dec, ss, sp, scale):
    """Fused mixed prefill/decode tick attention through the LIVE
    block-table slice: q [B, C, nh, hd] packed row groups (a prefill
    chunk, a single decode row, or idle garbage per slot) at per-slot
    offsets ``t``, DMA schedule ``(ss, sp)`` covering only live pages
    (ops/pallas/fused_tick.py). Decode slots (``dec``) route through
    an s=1-shaped fallback einsum so fused serving stays bit-identical
    to the unfused decode program; idle slots (``last < 0``) read as
    zeros."""
    from ..ops.pallas.fused_tick import fused_tick_attention
    return fused_tick_attention(q, k_pool, v_pool, bt, t, last, dec,
                                ss, sp, sm_scale=scale)


def _rope_gqa_attn(blk, xx, lc, t, pos, dims, tables, eps, bt=None,
                   fused=None, mesh=None):
    """Shared llama-family attention sublayer for the decode scan:
    pre-RMSNorm, rope at absolute positions, GQA cache write + masked
    cached attention, output projection + residual. ``lc`` is this
    layer's cache dict (fp or int8 codec) — or, when ``bt`` (a per-slot
    block table) is given, this layer's K/V page pools, written and
    attended through the table (paged backend). Paged with s == 1 is a
    decode step (ragged paged-attention kernel); s > 1 is a RAGGED
    PREFILL chunk — K/V written straight into pool pages at per-slot
    offsets ``t`` and attended causally through the block table, which
    is what lets the server prefill several admissions as one launch
    with no dense-cache detour. ``fused`` (a ``(last, dec, ss, sp)``
    tuple) switches the paged s > 1 path to the FUSED TICK: ``bt`` is
    then the live block-table slice, rows past ``last`` null-redirect
    zeroed on write, and attention runs the fused kernel whose DMA
    schedule ``(ss, sp)`` covers only live pages — prefill chunks and
    s=1 decode rows (``dec``) of one serving tick in a single launch.
    Returns (xx, lc, h2) with h2 = the post-attention norm for the FFN."""
    b, s, nh, kvh, hd, scale = dims
    cos, sin = tables
    from ..ops.pallas import rope as rope_mod
    h = _rms(xx, blk["ln1"], eps)
    q = _mm(h, blk["wq"]).reshape(b, s, nh, hd)
    k = _mm(h, blk["wk"]).reshape(b, s, kvh, hd)
    v = _mm(h, blk["wv"]).reshape(b, s, kvh, hd)
    q = rope_mod._apply_rotary_jnp(q, cos, sin, position_ids=pos)
    k = rope_mod._apply_rotary_jnp(k, cos, sin, position_ids=pos)
    if bt is not None and fused is not None:
        last, dec, ss, sp = fused
        lc = {"k": _page_write_seq(lc["k"], k, bt, t, last=last),
              "v": _page_write_seq(lc["v"], v, bt, t, last=last)}
        att = _fused_attend(q, lc["k"], lc["v"], bt, t, last, dec,
                            ss, sp, scale)
    elif bt is not None and s > 1:
        lc = {"k": _page_write_seq(lc["k"], k, bt, t),
              "v": _page_write_seq(lc["v"], v, bt, t)}
        att = _paged_prefill_attend(q, lc["k"], lc["v"], bt, t, scale,
                                    mesh=mesh)
    elif bt is not None:
        lc = {"k": _page_write(lc["k"], k, bt, t),
              "v": _page_write(lc["v"], v, bt, t)}
        att = _paged_attend(q, lc["k"], lc["v"], bt, t, scale, mesh=mesh)
    else:
        lc = _kv_write(lc, "k", k, t)
        lc = _kv_write(lc, "v", v, t)
        kc = _kv_read(lc, "k", q.dtype)
        vc = _kv_read(lc, "v", q.dtype)
        rep = nh // kvh
        kk = jnp.repeat(kc, rep, axis=2) if rep > 1 else kc
        vv = jnp.repeat(vc, rep, axis=2) if rep > 1 else vc
        att = _cached_attend(q, kk, vv, t, s, scale)
    xx = xx + _mm(att.reshape(b, s, nh * hd), blk["wo"])
    h2 = _rms(xx, blk["ln2"], eps)
    return xx, lc, h2


def _make_ragged_prefill_fn(step_fn, head_fn, embed_tokens):
    """Build the paged bundle's ragged-prefill entry point: several
    variable-length prompt chunks — one per serving slot — run as ONE
    program, K/V written straight into pool pages through the block
    table (no dense batch-1 cache detour) and attended causally at
    per-slot prefix offsets, so an auto-prefix-cache hit resumes over
    its already-cached pages exactly like decode does.

    Signature: ``(tokens [S, C], t0 [S], caches, out_idx [S]) ->
    (logits [S, V], caches)``. ``tokens`` holds one right-padded chunk
    per slot, ``t0`` the chunk's absolute start position (a slot with
    no prefill work this launch carries t0 = max_cache_len: every one
    of its writes null-redirects and its rows are garbage nobody
    reads), ``out_idx`` the row of each slot's LAST prompt token —
    ``logits[s]`` is that row's next-token distribution, valid only for
    slots whose prompt completes in this launch. All chunk geometry is
    static per (S, C): the server pads C up a power-of-two ladder so
    compiles stay O(log max_cache_len), not O(distinct prompt lengths).
    """
    def ragged_prefill(tokens, t0, caches, out_idx):
        S = tokens.shape[0]
        x = embed_tokens(tokens, t0)
        out, caches = step_fn(x, caches, t0)
        rows = out[jnp.arange(S), out_idx][:, None]        # [S, 1, H]
        return head_fn(rows)[:, -1], caches

    return ragged_prefill


def _make_fused_tick_fn(fused_step, head_fn, embed_tokens):
    """Build the paged bundle's FUSED-TICK entry point (ISSUE 14): one
    whole serving tick — every slot's prefill chunk at its prefix
    offset AND every live slot's s=1 decode row — as ONE program, K/V
    written straight into pool pages and attended through a DMA
    schedule that covers only live pages (ops/pallas/fused_tick.py).

    Signature: ``(tokens [S, C], t0 [S], last [S], dec [S], caches,
    out_idx [S], bt_live [S, W], sched_slot [G], sched_page [G]) ->
    (logits [S, V], caches)``. Per slot: a prefill chunk carries
    ``t0 = fill position``, ``last = t0 + take - 1``; a decode row
    carries its token in column 0 with ``t0 = last = t`` (the write
    position) and ``dec = 1``; an idle slot carries ``last = -1`` (its
    writes null-redirect zeroed, the kernel skips it entirely).
    ``out_idx`` picks the logits row — the last prompt token for a
    completing prefill, row 0 for decode. ``bt_live`` is the block
    tables SLICED to the live page frontier and ``(sched_slot,
    sched_page)`` the pow2-padded live-page DMA schedule
    (``fused_tick.build_schedule``), so the compiled program's HBM
    traffic scales with live tokens, not the configured cache length.
    Geometry (C, W, G) rides pow2 ladders — compiles stay O(log).

    Returned RAW (unjitted), unlike the prefill/ragged entries: the
    server composes its sampling epilogue around it and jits the WHOLE
    tick as one program, which is what collapses the per-tick dispatch
    histogram to ``{"fused": 1}``."""
    def fused_tick(tokens, t0, last, dec, caches, out_idx, bt_live,
                   sched_slot, sched_page):
        S = tokens.shape[0]
        x = embed_tokens(tokens, t0)
        out, caches = fused_step(x, caches, t0, last, dec, bt_live,
                                 sched_slot, sched_page)
        rows = out[jnp.arange(S), out_idx][:, None]        # [S, 1, H]
        return head_fn(rows)[:, -1], caches

    return fused_tick


def _make_llama_decode_fns(model, max_cache_len, weight_dtype=None, mesh=None,
                cache_dtype=None, cache_backend="dense", page_size=None,
                num_pages=None):
    """(init_caches, embed_fn, step_fn, head_fn) for LlamaForCausalLM —
    GQA-aware (kv heads cached unrepeated), rope applied at absolute
    positions. ``cache_backend="paged"`` swaps the dense per-slot cache
    for a global page pool + per-slot block tables (decode steps only;
    prefill runs on a dense batch-1 bundle and is scattered into
    pages)."""
    from ..ops.pallas import rope as rope_mod
    cfg = model.cfg
    nh, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    eps = cfg.rms_eps
    blocks = [dict(blk.raw_params()) for blk in model.model.layers]
    p = {
        "table": unwrap(model.model.embed_tokens.weight),
        "norm": unwrap(model.model.norm.weight),
        "head": unwrap(model.lm_head.weight),            # [H, V]
        "ln1": _stacked(blocks, "input_layernorm.weight"),
        "ln2": _stacked(blocks, "post_attention_layernorm.weight"),
        "wq": _stacked(blocks, "self_attn.q_proj.weight"),
        "wk": _stacked(blocks, "self_attn.k_proj.weight"),
        "wv": _stacked(blocks, "self_attn.v_proj.weight"),
        "wo": _stacked(blocks, "self_attn.o_proj.weight"),
        "wg": _stacked(blocks, "mlp.gate_proj.weight"),
        "wu": _stacked(blocks, "mlp.up_proj.weight"),
        "wd": _stacked(blocks, "mlp.down_proj.weight"),
    }
    cos, sin = rope_mod.precompute_freqs(hd, max_cache_len, cfg.rope_theta)
    if weight_dtype == "int8":
        p = _quantize_tree(p)
    if mesh is not None:
        p = _apply_mesh(p, mesh, {
            "wq": 2, "wk": 2, "wv": 2, "wg": 2, "wu": 2,   # column-parallel
            "wo": 1, "wd": 1,                              # row-parallel
            "head": 1})
    dtype = p["table"].dtype
    L = cfg.num_layers
    scale = 1.0 / np.sqrt(hd)
    paged = cache_backend == "paged"
    if paged:
        _check_paged_config(max_cache_len, page_size, num_pages,
                            cache_dtype, mesh)

    def init_caches(batch):
        if paged:
            return _init_paged_kv(batch, L, num_pages, page_size,
                                  max_cache_len // page_size, kvh, hd,
                                  dtype)
        return _init_kv((L, batch, max_cache_len, kvh, hd), dtype,
                        cache_dtype)

    if mesh is not None:
        init_caches = (_mesh_paged_caches if paged
                       else _mesh_caches)(init_caches, mesh)

    def embed_fn(tok, t):
        return p["table"][tok][:, None, :]

    def _run_layers(x, caches, t, bt, fused=None):
        x = unwrap(x)
        b, s = x.shape[0], x.shape[1]
        pos = _positions(t, b, s)                         # [B, s]

        def layer(xx, xs):
            blk, lc = xs
            xx, lc, h2 = _rope_gqa_attn(
                blk, xx, lc, t, pos, (b, s, nh, kvh, hd, scale),
                (cos, sin), eps, bt=bt, fused=fused, mesh=mesh)
            xx = xx + _mm(jax.nn.silu(_mm(h2, blk["wg"]))
                          * _mm(h2, blk["wu"]), blk["wd"])
            return xx, lc

        blk_tree = {k_: v_ for k_, v_ in p.items()
                    if k_ not in ("table", "norm", "head")}
        if paged:
            x, pool = jax.lax.scan(layer, x, (blk_tree, caches["pool"]))
            return x, dict(caches, pool=pool)
        x, new_caches = jax.lax.scan(layer, x, (blk_tree, caches))
        return x, new_caches

    def step_fn(x, caches, t):
        return _run_layers(x, caches, t, caches["bt"] if paged else None)

    def fused_step(x, caches, t, last, dec, bt_live, ss, sp):
        return _run_layers(x, caches, t, bt_live,
                           fused=(last, dec, ss, sp))

    def head_fn(out):
        return (_rms(unwrap(out), p["norm"], eps) @ p["head"]
                ).astype(jnp.float32)

    if paged:
        embed_tokens = lambda tokens, t0: p["table"][tokens]
        ragged = _make_ragged_prefill_fn(step_fn, head_fn, embed_tokens)
        fused = _make_fused_tick_fn(fused_step, head_fn, embed_tokens)
        return init_caches, embed_fn, step_fn, head_fn, ragged, fused
    return init_caches, embed_fn, step_fn, head_fn


def _moe_topk_ffn(h, router_w, wg, wu, wd, top_k):
    """Dropless dense-expert MoE FFN for decode: every expert runs (E/k
    FLOP overhead — the measured right choice at decode batch sizes, cf.
    benchmarks/moe_dispatch_bench.py) and tokens combine their top-k
    normalized gate weights. Matches the training GShard combine
    (parallel/moe/gate.py _top2_dense_dispatch) whenever capacity drops
    nothing — decode batches are far below capacity."""
    E = router_w.shape[-1]
    logits = h @ router_w                                  # [b, s, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    g1 = probs.max(-1)
    i1 = probs.argmax(-1)
    if top_k >= 2:
        probs2 = probs * (1.0 - jax.nn.one_hot(i1, E, dtype=probs.dtype))
        g2 = probs2.max(-1)
        i2 = probs2.argmax(-1)
        denom = g1 + g2 + 1e-9
        w = (jax.nn.one_hot(i1, E, dtype=probs.dtype)
             * (g1 / denom)[..., None]
             + jax.nn.one_hot(i2, E, dtype=probs.dtype)
             * (g2 / denom)[..., None])
    else:
        w = jax.nn.one_hot(i1, E, dtype=probs.dtype) * g1[..., None]
    g = _emm("bsh,ehf->besf", h, wg)
    u = _emm("bsh,ehf->besf", h, wu)
    o = _emm("besf,efh->besh", jax.nn.silu(g) * u, wd)
    return jnp.einsum("bse,besh->bsh", w.astype(o.dtype), o)


def _make_mixtral_decode_fns(model, max_cache_len, weight_dtype=None, mesh=None,
                  cache_dtype=None, cache_backend="dense", page_size=None,
                  num_pages=None):
    """Llama-style attention + routed-expert FFN (MixtralForCausalLM)."""
    from ..ops.pallas import rope as rope_mod
    cfg = model.cfg
    nh, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    eps = cfg.rms_eps
    blocks = [dict(blk.raw_params()) for blk in model.model.layers]
    p = {
        "table": unwrap(model.model.embed_tokens.weight),
        "norm": unwrap(model.model.norm.weight),
        "head": unwrap(model.lm_head.weight),
        "ln1": _stacked(blocks, "input_layernorm.weight"),
        "ln2": _stacked(blocks, "post_attention_layernorm.weight"),
        "wq": _stacked(blocks, "self_attn.q_proj.weight"),
        "wk": _stacked(blocks, "self_attn.k_proj.weight"),
        "wv": _stacked(blocks, "self_attn.v_proj.weight"),
        "wo": _stacked(blocks, "self_attn.o_proj.weight"),
        "router": _stacked(blocks, "moe.gate.gate.weight"),
        "wg": _stacked(blocks, "moe.experts.w_gate"),
        "wu": _stacked(blocks, "moe.experts.w_up"),
        "wd": _stacked(blocks, "moe.experts.w_down"),
    }
    cos, sin = rope_mod.precompute_freqs(hd, max_cache_len, cfg.rope_theta)
    if weight_dtype == "int8":
        p = _quantize_tree(p)
    if mesh is not None:
        p = _apply_mesh(p, mesh, {
            "wq": 2, "wk": 2, "wv": 2, "wo": 1,
            "wg": 1, "wu": 1, "wd": 1,        # expert-parallel decode
            "head": 1})
    dtype = p["table"].dtype
    L = cfg.num_layers
    top_k = cfg.top_k
    scale = 1.0 / np.sqrt(hd)
    paged = cache_backend == "paged"
    if paged:
        _check_paged_config(max_cache_len, page_size, num_pages,
                            cache_dtype, mesh)

    def init_caches(batch):
        if paged:
            return _init_paged_kv(batch, L, num_pages, page_size,
                                  max_cache_len // page_size, kvh, hd,
                                  dtype)
        return _init_kv((L, batch, max_cache_len, kvh, hd), dtype,
                        cache_dtype)

    if mesh is not None:
        init_caches = (_mesh_paged_caches if paged
                       else _mesh_caches)(init_caches, mesh)

    def embed_fn(tok, t):
        return p["table"][tok][:, None, :]

    def _run_layers(x, caches, t, bt, fused=None):
        x = unwrap(x)
        b, s = x.shape[0], x.shape[1]
        pos = _positions(t, b, s)

        def layer(xx, xs):
            blk, lc = xs
            xx, lc, h2 = _rope_gqa_attn(
                blk, xx, lc, t, pos, (b, s, nh, kvh, hd, scale),
                (cos, sin), eps, bt=bt, fused=fused, mesh=mesh)
            xx = xx + _moe_topk_ffn(h2, blk["router"], blk["wg"],
                                    blk["wu"], blk["wd"], top_k)
            return xx, lc

        blk_tree = {k_: v_ for k_, v_ in p.items()
                    if k_ not in ("table", "norm", "head")}
        if paged:
            x, pool = jax.lax.scan(layer, x, (blk_tree, caches["pool"]))
            return x, dict(caches, pool=pool)
        x, new_caches = jax.lax.scan(layer, x, (blk_tree, caches))
        return x, new_caches

    def step_fn(x, caches, t):
        return _run_layers(x, caches, t, caches["bt"] if paged else None)

    def fused_step(x, caches, t, last, dec, bt_live, ss, sp):
        return _run_layers(x, caches, t, bt_live,
                           fused=(last, dec, ss, sp))

    def head_fn(out):
        return (_rms(unwrap(out), p["norm"], eps) @ p["head"]
                ).astype(jnp.float32)

    if paged:
        embed_tokens = lambda tokens, t0: p["table"][tokens]
        ragged = _make_ragged_prefill_fn(step_fn, head_fn, embed_tokens)
        fused = _make_fused_tick_fn(fused_step, head_fn, embed_tokens)
        return init_caches, embed_fn, step_fn, head_fn, ragged, fused
    return init_caches, embed_fn, step_fn, head_fn


def _make_gpt_decode_fns(model, max_cache_len, weight_dtype=None, mesh=None,
              cache_dtype=None, cache_backend="dense", page_size=None,
              num_pages=None):
    """(init_caches, embed_fn, step_fn, head_fn) for GPTForCausalLM —
    learned positions, fused qkv, tied lm head."""
    cfg = model.cfg
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_eps
    if max_cache_len > cfg.max_seq_len:
        raise ValueError(
            f"max_cache_len ({max_cache_len}) exceeds the learned "
            f"position table ({cfg.max_seq_len}); positions past it "
            f"would silently clamp — shorten the cache or grow wpe")
    blocks = [dict(blk.raw_params()) for blk in model.gpt.blocks]
    p = {
        "table": unwrap(model.gpt.wte.weight),           # [V, H] (tied)
        "wpe": unwrap(model.gpt.wpe.weight),
        "lnf_w": unwrap(model.gpt.ln_f.weight),
        "lnf_b": unwrap(model.gpt.ln_f.bias),
    }
    for name in ("ln1.weight", "ln1.bias", "ln2.weight", "ln2.bias",
                 "attn.qkv.weight", "attn.qkv.bias",
                 "attn.proj.weight", "attn.proj.bias",
                 "mlp.fc1.weight", "mlp.fc1.bias",
                 "mlp.fc2.weight", "mlp.fc2.bias"):
        p[name] = _stacked(blocks, name)
    if weight_dtype == "int8":
        p = _quantize_tree(p)
    if mesh is not None:
        p = _apply_mesh(p, mesh, {
            "attn.qkv.weight": 2, "attn.proj.weight": 1,
            "mlp.fc1.weight": 2, "mlp.fc2.weight": 1})
    dtype = p["table"].dtype
    L = cfg.num_layers
    scale = 1.0 / np.sqrt(hd)
    paged = cache_backend == "paged"
    if paged:
        _check_paged_config(max_cache_len, page_size, num_pages,
                            cache_dtype, mesh)

    def init_caches(batch):
        if paged:
            return _init_paged_kv(batch, L, num_pages, page_size,
                                  max_cache_len // page_size, nh, hd,
                                  dtype)
        return _init_kv((L, batch, max_cache_len, nh, hd), dtype,
                        cache_dtype)

    if mesh is not None:
        init_caches = (_mesh_paged_caches if paged
                       else _mesh_caches)(init_caches, mesh)

    def embed_fn(tok, t):
        pos_emb = p["wpe"][t]                # scalar t: [H]; [B] t: [B,H]
        if jnp.ndim(t) == 0:
            pos_emb = pos_emb[None]
        return (p["table"][tok] + pos_emb)[:, None, :]

    def _run_layers(x, caches, t, bt, fused=None):
        x = unwrap(x)
        b, s = x.shape[0], x.shape[1]

        def layer(xx, xs):
            blk, lc = xs
            h = _ln(xx, blk["ln1.weight"], blk["ln1.bias"], eps)
            qkv = (_mm(h, blk["attn.qkv.weight"]) + blk["attn.qkv.bias"]
                   ).reshape(b, s, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if paged and fused is not None:  # fused serving tick
                last, dec, ss, sp = fused
                lc = {"k": _page_write_seq(lc["k"], k, bt, t, last=last),
                      "v": _page_write_seq(lc["v"], v, bt, t, last=last)}
                att = _fused_attend(q, lc["k"], lc["v"], bt, t, last,
                                    dec, ss, sp, scale)
            elif paged and s > 1:            # ragged prefill chunk
                lc = {"k": _page_write_seq(lc["k"], k, bt, t),
                      "v": _page_write_seq(lc["v"], v, bt, t)}
                att = _paged_prefill_attend(q, lc["k"], lc["v"], bt, t,
                                            scale, mesh=mesh)
            elif paged:
                lc = {"k": _page_write(lc["k"], k, bt, t),
                      "v": _page_write(lc["v"], v, bt, t)}
                att = _paged_attend(q, lc["k"], lc["v"], bt, t, scale,
                                    mesh=mesh)
            else:
                lc = _kv_write(lc, "k", k, t)
                lc = _kv_write(lc, "v", v, t)
                att = _cached_attend(q, _kv_read(lc, "k", q.dtype),
                                     _kv_read(lc, "v", q.dtype), t, s,
                                     scale)
            xx = xx + (_mm(att.reshape(b, s, nh * hd),
                           blk["attn.proj.weight"])
                       + blk["attn.proj.bias"])
            h2 = _ln(xx, blk["ln2.weight"], blk["ln2.bias"], eps)
            ff = jax.nn.gelu(_mm(h2, blk["mlp.fc1.weight"])
                             + blk["mlp.fc1.bias"], approximate=True)
            xx = xx + _mm(ff, blk["mlp.fc2.weight"]) + blk["mlp.fc2.bias"]
            return xx, lc

        blk_tree = {k_: v_ for k_, v_ in p.items()
                    if k_ not in ("table", "wpe", "lnf_w", "lnf_b")}
        if paged:
            x, pool = jax.lax.scan(layer, x, (blk_tree, caches["pool"]))
            return x, dict(caches, pool=pool)
        x, new_caches = jax.lax.scan(layer, x, (blk_tree, caches))
        return x, new_caches

    def step_fn(x, caches, t):
        return _run_layers(x, caches, t, caches["bt"] if paged else None)

    def fused_step(x, caches, t, last, dec, bt_live, ss, sp):
        return _run_layers(x, caches, t, bt_live,
                           fused=(last, dec, ss, sp))

    def head_fn(out):
        h = _ln(unwrap(out), p["lnf_w"], p["lnf_b"], eps)
        return (h @ p["table"].T).astype(jnp.float32)

    if paged:
        def gpt_embed_tokens(tokens, t0):
            # learned positions: per-slot offsets, [S, C] gather (an
            # idle slot's out-of-range rows pick up jnp's NaN fill —
            # zeroed on the null-page write, discarded on the output)
            pos = _positions(t0, tokens.shape[0], tokens.shape[1])
            return p["table"][tokens] + p["wpe"][pos]

        ragged = _make_ragged_prefill_fn(step_fn, head_fn,
                                         gpt_embed_tokens)
        fused = _make_fused_tick_fn(fused_step, head_fn,
                                    gpt_embed_tokens)
        return init_caches, embed_fn, step_fn, head_fn, ragged, fused
    return init_caches, embed_fn, step_fn, head_fn


class GenerationMixin:
    """``generate()`` for causal-LM models (greedy + sampling), running
    prefill and the whole decode loop as on-device XLA programs."""

    def _decode_bundle(self, max_cache_len, weight_dtype=None, mesh=None,
                       cache_dtype=None, cache_backend="dense",
                       page_size=None, num_pages=None):
        key = ("_pt_decode_bundle", max_cache_len, weight_dtype,
               None if mesh is None else id(mesh), cache_dtype,
               cache_backend, page_size, num_pages)
        cached = getattr(self, "_pt_decode_cache", None)
        if cached is None:
            cached = self._pt_decode_cache = {}
        if key in cached:
            cached[key] = cached.pop(key)      # LRU: move to back
            return cached[key]
        from .gpt import GPTForCausalLM
        from .llama import LlamaForCausalLM
        from .mixtral import MixtralForCausalLM
        kw = dict(cache_backend=cache_backend, page_size=page_size,
                  num_pages=num_pages)
        if isinstance(self, MixtralForCausalLM):
            bundle = _make_mixtral_decode_fns(self, max_cache_len,
                                              weight_dtype, mesh,
                                              cache_dtype, **kw)
        elif isinstance(self, LlamaForCausalLM):
            bundle = _make_llama_decode_fns(self, max_cache_len,
                                            weight_dtype, mesh,
                                            cache_dtype, **kw)
        elif isinstance(self, GPTForCausalLM):
            bundle = _make_gpt_decode_fns(self, max_cache_len,
                                          weight_dtype, mesh,
                                          cache_dtype, **kw)
        else:
            # no-roadmap: model-family dispatch, not a scope cut
            raise NotImplementedError(
                f"generate() not wired for {type(self).__name__}")
        # one prefill program per (bundle, prompt-shape): jit here, not
        # inside generate(), so repeated calls reuse the compile. Paged
        # bundles carry a SIXTH element — the jitted ragged-prefill
        # entry point (packed multi-slot prompt chunks straight into
        # pool pages; see _make_ragged_prefill_fn) — and a SEVENTH:
        # the RAW fused-tick entry point (_make_fused_tick_fn; one
        # whole serving tick — prefill chunks + s=1 decode rows — as
        # one program over a live-page DMA schedule). The fused entry
        # stays unjitted so the server can compose its sampling
        # epilogue around it and jit the WHOLE tick as one dispatch.
        # Dense bundles stay 5-tuples for existing consumers
        # (deploy_decode, speculative).
        extras = bundle[4:]
        bundle = bundle[:4] + (jax.jit(bundle[2], donate_argnums=(1,)),)
        if extras:
            bundle = bundle + (jax.jit(extras[0], donate_argnums=(2,)),)
            if len(extras) > 1:
                bundle = bundle + (extras[1],)
        cached[key] = bundle
        # each bundle closes over a full stacked weight copy: cap the
        # cache (LRU) so varied generate() shapes can't accumulate
        # weight copies without bound. 4 covers the server's dense +
        # paged pair twice over.
        while len(cached) > 4:
            cached.pop(next(iter(cached)))
        return bundle

    def _prefill_embed(self, ids, bundle, t0=0):
        """[B, T] ids -> [B, T, H] input embeddings for a multi-token
        step starting at position ``t0`` (prefill: 0; speculative
        verify: the current decode offset)."""
        from .gpt import GPTForCausalLM
        if isinstance(self, GPTForCausalLM):
            table = unwrap(self.gpt.wte.weight)
            wpe = unwrap(self.gpt.wpe.weight)
            return table[ids] + wpe[t0 + jnp.arange(ids.shape[1])][None]
        table = unwrap(self.model.embed_tokens.weight)
        return table[ids]

    def _run_prefill(self, bundle, ids_np, chunk=None, caches=None, t0=0):
        """Prefill ``ids_np`` [B, T] starting at position ``t0`` (fresh
        caches unless ``caches`` resumes a partially-filled tree, e.g. a
        shared-prefix hit); returns (last-position logits [B, V], caches).

        ``chunk``: feed the prompt in fixed-size chunks (prompt padded up
        to a multiple) so ONE compiled prefill program serves every
        prompt length — the serving-side compile-cache bound. Padded
        positions sit above the valid frontier: the causal validity mask
        hides their cache rows, and decode overwrites them."""
        init_caches, embed_fn, step_fn, head_fn, prefill_jit = bundle
        B, T = ids_np.shape
        if caches is None:
            caches = init_caches(B)
        if not chunk or chunk >= T:
            x0 = self._prefill_embed(jnp.asarray(ids_np), bundle, t0=t0)
            out, caches = prefill_jit(x0, caches, jnp.int32(t0))
            return head_fn(out[:, -1:])[:, -1], caches
        pad = (-T) % chunk
        cache_rows = jax.tree_util.tree_leaves(caches)[0].shape[2]
        if t0 + T + pad > cache_rows:
            raise ValueError(
                f"chunked prefill writes rows up to {t0 + T + pad} "
                f"(prompt {T} at offset {t0} padded to a multiple of "
                f"{chunk}) but max_cache_len is {cache_rows} — raise "
                f"max_cache_len by at least {chunk - 1} for chunk "
                f"headroom")
        ids_pad = np.pad(ids_np, ((0, 0), (0, pad)))
        last = None
        for i in range(0, T + pad, chunk):
            x = self._prefill_embed(jnp.asarray(ids_pad[:, i:i + chunk]),
                                    bundle, t0=t0 + i)
            out, caches = prefill_jit(x, caches, jnp.int32(t0 + i))
            if i <= T - 1 < i + chunk:
                last = head_fn(out[:, T - 1 - i:T - i])[:, -1]
        return last, caches

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 seed=None, max_cache_len=None, weight_dtype=None,
                 prefill_chunk=None, mesh=None, cache_dtype=None,
                 num_beams=1, fsm=None):
        """Generate continuations for ``input_ids`` ([B, T] int). Returns
        the FULL sequence (prompt + ``max_new_tokens``) as a framework
        tensor; after every row hits ``eos_token_id`` the tail is padded
        with eos (static shapes — XLA cannot break early).

        Greedy when ``do_sample=False``; otherwise categorical sampling
        with ``temperature``/``top_k``/``top_p`` filtering and a PRNG
        seeded by ``seed`` (``seed=None`` draws a fresh seed from numpy's
        global RNG, so repeated calls differ). Weight-change caveat: decode functions are
        built from the CURRENT weights and cached per ``max_cache_len``;
        call ``model.reset_generate_cache()`` after loading new weights.

        ``weight_dtype="int8"`` turns on weight-only int8 decode: matmul
        weights are stored int8 with per-channel scales, halving the
        weight bytes streamed per decode step (the serving roofline);
        embeddings, norms, routers and the lm head stay full precision.
        """
        from ..inference.decode_loop import (beam_generate, fsm_generate,
                                             greedy_generate,
                                             sample_generate)
        ids_np = np.asarray(unwrap(input_ids))
        if ids_np.ndim == 1:
            ids_np = ids_np[None]
        ids_np = ids_np.astype(np.int32)
        B, T = ids_np.shape
        pad = (-T) % prefill_chunk if prefill_chunk else 0
        if max_cache_len is None:
            max_cache_len = min(self.cfg.max_seq_len,
                                max(T + max_new_tokens, T + pad))
        if T + max_new_tokens > max_cache_len:
            raise ValueError(
                f"prompt ({T}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_cache_len ({max_cache_len})")
        bundle = self._decode_bundle(max_cache_len, weight_dtype, mesh,
                                     cache_dtype)
        init_caches, embed_fn, step_fn, head_fn, prefill_jit = bundle

        last_logits, caches = self._run_prefill(bundle, ids_np,
                                                chunk=prefill_chunk)

        if fsm is not None:
            if num_beams > 1:
                raise ValueError("constrained decoding composes with "
                                 "greedy/sampling, not beam search")
            mask_tab, next_tab = fsm[0], fsm[1]
            start = fsm[2] if len(fsm) > 2 else 0
            if do_sample and seed is None:   # greedy never draws
                seed = int(np.random.randint(0, 2**31))
            new_ids, _ = fsm_generate(
                embed_fn, step_fn, head_fn, caches, last_logits, T,
                max_new_tokens, mask_tab, next_tab, start_state=start,
                do_sample=do_sample,
                key=jax.random.PRNGKey(seed or 0),
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id)
        elif num_beams > 1:
            if do_sample:
                raise ValueError("beam search and sampling are mutually "
                                 "exclusive (reference decode semantics)")
            new_ids, _ = beam_generate(
                embed_fn, step_fn, head_fn, caches, last_logits, T,
                max_new_tokens, num_beams, eos_token_id=eos_token_id)
        elif do_sample:
            if seed is None:        # fresh entropy per call, like the
                seed = int(np.random.randint(0, 2**31))  # reference's
            key = jax.random.PRNGKey(seed)               # global RNG
            new_ids, _ = sample_generate(
                embed_fn, step_fn, head_fn, caches, last_logits, T,
                max_new_tokens, key, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_token_id=eos_token_id)
        else:
            first = jnp.argmax(last_logits, -1).astype(jnp.int32)
            new_ids, _ = greedy_generate(
                embed_fn, step_fn, head_fn, caches, first, T,
                max_new_tokens, eos_token_id=eos_token_id)
        full = np.concatenate([ids_np, np.asarray(new_ids)], axis=1)
        return wrap(jnp.asarray(full))

    def reset_generate_cache(self):
        """Drop cached decode programs (call after loading new weights)."""
        self._pt_decode_cache = None
