"""Llama-2 family (RMSNorm pre-norm, RoPE, SwiGLU, GQA-ready).

The flagship perf model (BASELINE.md: Llama-2 7B/70B TP+PP+sharding
targets). RMSNorm and attention route to the Pallas kernels on TPU; rope is
XLA-fused (ops/pallas/rope.py).
"""
from dataclasses import dataclass

import jax.numpy as jnp

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import dispatch
from paddle_tpu.models.generation import GenerationMixin
from paddle_tpu.ops.pallas import rope as rope_mod
from paddle_tpu.parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama2_7b",
           "llama2_70b", "llama_tiny", "llama_350m"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = None
    intermediate_size: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tensor_parallel: bool = False
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        hd = cfg.head_dim
        q_out = cfg.num_heads * hd
        kv_out = cfg.num_kv_heads * hd
        Lin = ColumnParallelLinear if cfg.tensor_parallel else None
        if cfg.tensor_parallel:
            self.q_proj = ColumnParallelLinear(h, q_out, has_bias=False,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(h, kv_out, has_bias=False,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(h, kv_out, has_bias=False,
                                               gather_output=False)
            self.o_proj = RowParallelLinear(q_out, h, has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(h, q_out, bias_attr=False)
            self.k_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.v_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.o_proj = nn.Linear(q_out, h, bias_attr=False)
        cos, sin = rope_mod.precompute_freqs(hd, cfg.max_seq_len,
                                             cfg.rope_theta)
        from paddle_tpu.core.tensor import wrap
        self.register_buffer("rope_cos", wrap(cos), persistable=False)
        self.register_buffer("rope_sin", wrap(sin), persistable=False)

    def forward(self, x, position_ids=None):
        cfg = self.cfg
        b, s = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([b, s, cfg.num_heads, cfg.head_dim])
        k = self.k_proj(x).reshape([b, s, cfg.num_kv_heads, cfg.head_dim])
        v = self.v_proj(x).reshape([b, s, cfg.num_kv_heads, cfg.head_dim])

        def rot(qv, kv, cosv, sinv):
            return (rope_mod.apply_rotary(qv, cosv, sinv),
                    rope_mod.apply_rotary(kv, cosv, sinv))

        q, k = dispatch(rot, q, k, self.rope_cos, self.rope_sin,
                        nondiff_args=(2, 3), name="rope")
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads

            def repeat_kv(t):
                return jnp.repeat(t, rep, axis=2)

            k = dispatch(repeat_kv, k, name="repeat_kv")
            v = dispatch(repeat_kv, v, name="repeat_kv")
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=self.training)
        out = out.reshape([b, s, cfg.num_heads * cfg.head_dim])
        return self.o_proj(out)


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        if cfg.tensor_parallel:
            self.gate_proj = ColumnParallelLinear(h, m, has_bias=False,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(h, m, has_bias=False,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(m, h, has_bias=False,
                                               input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(h, m, bias_attr=False)
            self.up_proj = nn.Linear(h, m, bias_attr=False)
            self.down_proj = nn.Linear(m, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, position_ids=None):
        x = x + self.self_attn(self.input_layernorm(x), position_ids)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        from paddle_tpu.nn.initializer import Normal
        w = self.embed_tokens.weight
        w._replace_value(Normal(0.0, 0.02)(w.shape, w.dtype))
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids, position_ids=None):
        x = self.embed_tokens(input_ids)
        for blk in self.layers:
            x = blk(x, position_ids)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tensor_parallel:
            self.lm_head = ColumnParallelLinear(cfg.hidden_size,
                                                cfg.vocab_size,
                                                has_bias=False,
                                                gather_output=True)
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None, return_hidden=False):
        h = self.model(input_ids, position_ids)
        if return_hidden:
            # for fused linear+CE losses (ops/fused_ce.py)
            return h
        return self.lm_head(h)

    def loss(self, logits, labels):
        return F.cross_entropy(logits[:, :-1, :], labels[:, 1:])

    def pipeline_decompose(self):
        """Decompose into pure fns + param trees for the 1F1B/hybrid
        builders (reference PipelineLayer's LayerDesc segmentation,
        meta_parallel/parallel_layers/pp_layers.py): returns
        ((block_fn, embed_fn, head_loss_fn), (blocks, embed, head))."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import unwrap
        from ..jit import functional_call
        if self.cfg.tensor_parallel:
            # no-roadmap: API redirect to the hybrid factories, not a cut
            raise NotImplementedError(
                "pipeline_decompose targets the non-TP module; for mp×pp "
                "use parallel.hybrid.make_llama_tp_fns")
        proto = self.model.layers[0]
        blocks = [dict(blk.raw_params()) for blk in self.model.layers]
        embed = {"table": unwrap(self.model.embed_tokens.weight)}
        head = {"norm": unwrap(self.model.norm.weight),
                "wo": unwrap(self.lm_head.weight)}
        eps = self.cfg.rms_eps

        def block_fn(p, x):
            return functional_call(proto, p, x)

        def embed_fn(p, ids):
            return p["table"][ids]

        def _final_norm(p, hidden):
            var = jnp.mean(jnp.square(hidden.astype(jnp.float32)), -1,
                           keepdims=True)
            return (hidden * jax.lax.rsqrt(var + eps).astype(hidden.dtype)
                    ) * p["norm"]

        def head_loss_fn(p, hidden, labels):
            lg = (_final_norm(p, hidden) @ p["wo"]
                  ).astype(jnp.float32)[:, :-1]
            logp = jax.nn.log_softmax(lg, -1)
            return -jnp.take_along_axis(
                logp, labels[:, 1:, None], -1).mean()

        def head_out_fn(p, hidden, labels):
            # Engine.predict through the pipeline: full-seq logits
            return (_final_norm(p, hidden) @ p["wo"]).astype(jnp.float32)

        return ((block_fn, embed_fn, head_loss_fn),
                (blocks, embed, head), {"head_out_fn": head_out_fn})

    def pipeline_recompose(self, params, layout):
        """Write trained stage-stacked pipeline params back into this
        eager module (inverse of pipeline_decompose + the builder's
        stacking). ``params`` = {"blocks": {name: [v,S,C,...]},
        "embed": ..., "head": ...}; ``layout`` = (counts, starts, S, v)."""
        counts, starts, S, v = layout
        for vs in range(S * v):
            v_idx, s_idx = vs // S, vs % S
            for j in range(int(counts[vs])):
                layer = self.model.layers[int(starts[vs]) + j]
                layer.load_raw_params(
                    {n: a[v_idx, s_idx, j]
                     for n, a in params["blocks"].items()})
        self.model.embed_tokens.weight._replace_value(
            params["embed"]["table"])
        self.model.norm.weight._replace_value(params["head"]["norm"])
        self.lm_head.weight._replace_value(params["head"]["wo"])


def llama2_7b(**kw):
    return LlamaConfig(**kw)


def llama2_70b(**kw):
    kw.setdefault("hidden_size", 8192)
    kw.setdefault("num_layers", 80)
    kw.setdefault("num_heads", 64)
    kw.setdefault("num_kv_heads", 8)
    kw.setdefault("intermediate_size", 28672)
    return LlamaConfig(**kw)


def llama_350m(**kw):
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("intermediate_size", 2816)
    kw.setdefault("max_seq_len", 2048)
    return LlamaConfig(**kw)


def llama_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 2)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_seq_len", 128)
    return LlamaConfig(**kw)
