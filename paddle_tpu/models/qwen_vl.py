"""Qwen-VL-style vision-language model (multimodal, functional).

BASELINE.md row "Qwen-VL: multimodal via auto_parallel ... functional".
Architecture: ViT vision tower (patch embed + pre-norm transformer) →
linear projector → visual tokens prepended to the text embedding stream of
a Llama-family decoder (RoPE positions cover the joint sequence). Loss
masks the visual prefix and scores only text targets.

Reference capability: the PaddleNLP/PaddleMIX VL stack layered on the
reference's fleet/auto_parallel APIs; here everything runs on paddle_tpu.nn
with the Pallas attention path, and parameters can be annotated for a
ProcessMesh via `shard_qwen_vl`.
"""
from dataclasses import dataclass, field

import paddle_tpu.nn as nn
from paddle_tpu.ops.manipulation import concat as pt_ops_concat
import paddle_tpu.nn.functional as F

from ._stem import patches_to_seq, shard_params_by_name
from .llama import LlamaConfig, LlamaModel

__all__ = ["ViTConfig", "VisionTransformer", "QwenVLConfig", "QwenVL",
           "qwen_vl_tiny"]


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 14
    in_channels: int = 3
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    mlp_ratio: float = 4.0

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2


class ViTBlock(nn.Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.norm1 = nn.LayerNorm(h)
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)
        self.norm2 = nn.LayerNorm(h)
        m = int(h * cfg.mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(h, m), nn.GELU(approximate=True),
                                 nn.Linear(m, h))

    def forward(self, x):
        b, s, h = x.shape
        hd = h // self.num_heads
        qkv = self.qkv(self.norm1(x)).reshape([b, s, 3, self.num_heads, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = F.scaled_dot_product_attention(q, k, v, is_causal=False,
                                             training=self.training)
        x = x + self.proj(att.reshape([b, s, h]))
        return x + self.mlp(self.norm2(x))


class VisionTransformer(nn.Layer):
    """Pre-norm ViT tower returning patch tokens (no CLS pooling — the VL
    projector consumes the full token grid, Qwen-VL style)."""

    def __init__(self, cfg: ViTConfig):
        super().__init__()
        self.cfg = cfg
        p = cfg.patch_size
        self.patch_embed = nn.Conv2D(cfg.in_channels, cfg.hidden_size,
                                     kernel_size=p, stride=p)
        from paddle_tpu.nn.initializer import Normal
        self.pos_embed = self.create_parameter(
            (1, cfg.num_patches, cfg.hidden_size),
            default_initializer=Normal(0.0, 0.02))
        self.blocks = nn.LayerList([ViTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.LayerNorm(cfg.hidden_size)

    def forward(self, pixel_values):
        h = patches_to_seq(self.patch_embed(pixel_values)) + self.pos_embed
        for blk in self.blocks:
            h = blk(h)
        return self.norm(h)                        # [B, T_img, D_vit]


@dataclass
class QwenVLConfig:
    vision: ViTConfig = field(default_factory=ViTConfig)
    text: LlamaConfig = field(default_factory=LlamaConfig)
    ignore_index: int = -100


class QwenVL(nn.Layer):
    def __init__(self, cfg: QwenVLConfig):
        super().__init__()
        self.cfg = cfg
        self.visual = VisionTransformer(cfg.vision)
        self.projector = nn.Linear(cfg.vision.hidden_size,
                                   cfg.text.hidden_size)
        self.language_model = LlamaModel(cfg.text)
        self.lm_head = nn.Linear(cfg.text.hidden_size, cfg.text.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, pixel_values=None):
        """input_ids: [B, S_txt]; pixel_values: [B, C, H, W] or None.
        Visual tokens are prepended; returns logits over the joint seq."""
        emb = self.language_model.embed_tokens(input_ids)
        if pixel_values is not None:
            vis = self.projector(self.visual(pixel_values))
            emb = pt_ops_concat([vis.astype(emb.dtype), emb], axis=1)
        x = emb
        for blk in self.language_model.layers:
            x = blk(x)
        x = self.language_model.norm(x)
        return self.lm_head(x)

    def loss(self, logits, labels, num_visual_tokens=None):
        """CE over text targets only: the visual prefix is sliced off the
        logits before next-token alignment."""
        if num_visual_tokens is None:
            num_visual_tokens = logits.shape[1] - labels.shape[1]
        if num_visual_tokens > 0:
            logits = logits[:, num_visual_tokens:]
        return F.cross_entropy(logits[:, :-1, :], labels[:, 1:])

    def generate(self, input_ids, pixel_values=None, max_new_tokens=32,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, seed=None, max_cache_len=None):
        """Multimodal generation: the image's visual tokens prefill the
        joint sequence (rope positions cover prefix + text, matching the
        training forward), then the text decodes through the same
        on-device scan loop the pure-text models use. Returns the full
        TEXT sequence (prompt + new tokens); visual tokens are internal.
        """
        import types

        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..core.tensor import unwrap, wrap
        from ..inference.decode_loop import greedy_generate, sample_generate
        from .generation import _make_llama_decode_fns

        ids_np = np.asarray(unwrap(input_ids)).astype(np.int32)
        if ids_np.ndim == 1:
            ids_np = ids_np[None]
        B, T = ids_np.shape

        vis = None
        n_vis = 0
        if pixel_values is not None:
            vis = unwrap(self.projector(self.visual(pixel_values)))
            n_vis = vis.shape[1]
        total = n_vis + T
        if max_cache_len is None:
            max_cache_len = min(self.cfg.text.max_seq_len,
                                total + max_new_tokens)
        if total + max_new_tokens > max_cache_len:
            raise ValueError(
                f"visual ({n_vis}) + prompt ({T}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_cache_len "
                f"({max_cache_len})")

        key = ("_pt_vl_bundle", max_cache_len)
        cached = getattr(self, "_pt_decode_cache", None)
        if cached is None:
            cached = self._pt_decode_cache = {}
        bundle = cached.pop(key, None)
        if bundle is None:
            view = types.SimpleNamespace(cfg=self.cfg.text,
                                         model=self.language_model,
                                         lm_head=self.lm_head)
            fns = _make_llama_decode_fns(view, max_cache_len)
            bundle = fns + (jax.jit(fns[2], donate_argnums=(1,)),)
        cached[key] = bundle                   # LRU: newest at the back
        while len(cached) > 4:                 # bundles pin weight copies
            cached.pop(next(iter(cached)))
        init_caches, embed_fn, step_fn, head_fn, prefill_jit = bundle

        table = unwrap(self.language_model.embed_tokens.weight)
        x0 = table[jnp.asarray(ids_np)]
        if vis is not None:
            x0 = jnp.concatenate([vis.astype(x0.dtype), x0], axis=1)
        caches = init_caches(B)
        out, caches = prefill_jit(x0, caches, jnp.int32(0))
        last_logits = head_fn(out[:, -1:])[:, -1]

        if do_sample:
            if seed is None:
                seed = int(np.random.randint(0, 2**31))
            new_ids, _ = sample_generate(
                embed_fn, step_fn, head_fn, caches, last_logits, total,
                max_new_tokens, jax.random.PRNGKey(seed),
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id)
        else:
            first = jnp.argmax(last_logits, -1).astype(jnp.int32)
            new_ids, _ = greedy_generate(
                embed_fn, step_fn, head_fn, caches, first, total,
                max_new_tokens, eos_token_id=eos_token_id)
        full = np.concatenate([ids_np, np.asarray(new_ids)], axis=1)
        return wrap(jnp.asarray(full))


def shard_qwen_vl(model, process_mesh):
    """auto_parallel annotation for a dp×mp ProcessMesh: wide projections
    sharded over 'mp', everything else replicated; GSPMD completes."""
    return shard_params_by_name(model, process_mesh,
                                ("qkv", "mlp", "gate_proj", "up_proj",
                                 "down_proj", "lm_head"))


def qwen_vl_tiny(**kw):
    vis = ViTConfig(image_size=16, patch_size=4, in_channels=3,
                    hidden_size=32, num_layers=2, num_heads=4)
    txt = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=128,
                      max_seq_len=128)
    return QwenVLConfig(vision=vis, text=txt, **kw)
