from . import bert, gpt, llama  # noqa: F401
from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, BertModel)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
