from . import bert, gpt, llama, mixtral  # noqa: F401
from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, BertModel)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .mixtral import (MixtralConfig, MixtralForCausalLM,  # noqa: F401
                      MixtralModel)
