"""Shared vision-stem and sharding helpers for the model zoo."""
import jax.numpy as jnp

from paddle_tpu.core.tensor import dispatch

__all__ = ["patches_to_seq", "shard_params_by_name"]


def patches_to_seq(conv_out):
    """[B, D, H/p, W/p] conv patch-embed output -> [B, T, D] token seq."""
    def fn(v):
        b, d = v.shape[0], v.shape[1]
        return jnp.transpose(v.reshape(b, d, -1), (0, 2, 1))

    return dispatch(fn, conv_out, name="patch_to_seq")


def shard_params_by_name(model, process_mesh, mp_keys):
    """auto_parallel annotation: 2-D params whose name contains one of
    ``mp_keys`` are sharded [None, 'mp']; everything else replicated.
    GSPMD completes the layout (reference flow: Completer/Partitioner on
    TensorDistAttr, python/paddle/distributed/auto_parallel/completion.py).
    """
    from paddle_tpu.parallel.auto_parallel import shard_tensor
    for name, p in model.named_parameters():
        if p.ndim == 2 and any(k in name for k in mp_keys):
            shard_tensor(p, process_mesh, [None, "mp"])
        else:
            shard_tensor(p, process_mesh, [None] * p.ndim)
    return model
