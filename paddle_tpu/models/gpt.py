"""GPT-2/3 family (decoder-only, learned positions, LayerNorm+GELU).

Reference parity target: the Fleet GPT hybrid-parallel example
(BASELINE.json config 1 — GPT-2 345M). Built from paddle_tpu.nn + the TP
layers, so one model definition serves single-chip, TP (GSPMD), and PP
(via PipelineLayer segmentation in parallel/pipeline.py).
"""
from dataclasses import dataclass

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt2_345m", "gpt2_tiny"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 1024
    intermediate_size: int = None
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    tensor_parallel: bool = False
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        if cfg.tensor_parallel:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.proj = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.proj = nn.Linear(h, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        b = x.shape[0]
        s = x.shape[1]
        nh, hd = self.cfg.num_heads, self.cfg.hidden_size // self.cfg.num_heads
        qkv = self.qkv(x)
        qkv = qkv.reshape([b, s, 3, nh, hd])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=self.training)
        out = out.reshape([b, s, nh * hd])
        return self.dropout(self.proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        if cfg.tensor_parallel:
            self.fc1 = ColumnParallelLinear(h, m, gather_output=False)
            self.fc2 = RowParallelLinear(m, h, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, m)
            self.fc2 = nn.Linear(m, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlp = GPTMLP(cfg)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        # GPT-2 init: N(0, 0.02) embeddings (keeps init CE near ln(V))
        from paddle_tpu.nn.initializer import Normal
        init = Normal(0.0, 0.02)
        self.wte.weight._replace_value(
            init(self.wte.weight.shape, self.wte.weight.dtype))
        self.wpe.weight._replace_value(
            init(self.wpe.weight.shape, self.wpe.weight.dtype))
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids, position_ids=None):
        import paddle_tpu as pt
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = pt.ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        # tied output projection (weight reuse, like the reference example)
        self.lm_head_weight = self.gpt.wte.weight

    def forward(self, input_ids, position_ids=None, return_hidden=False):
        h = self.gpt(input_ids, position_ids)
        if return_hidden:
            # for fused linear+CE losses (ops/fused_ce.py): caller applies
            # the tied lm head inside the chunked loss
            return h
        from ..ops.registry import OPS
        return OPS["matmul"](h, self.lm_head_weight, transpose_y=True)

    def loss(self, logits, labels):
        """Shifted causal LM loss."""
        lg = logits[:, :-1, :]
        lb = labels[:, 1:]
        return F.cross_entropy(lg, lb)


def gpt2_345m(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, max_seq_len=1024, **kw)


def gpt2_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 128)
    return GPTConfig(**kw)
