"""GPT-2/3 family (decoder-only, learned positions, LayerNorm+GELU).

Reference parity target: the Fleet GPT hybrid-parallel example
(BASELINE.json config 1 — GPT-2 345M). Built from paddle_tpu.nn + the TP
layers, so one model definition serves single-chip, TP (GSPMD), and PP
(via PipelineLayer segmentation in parallel/pipeline.py).
"""
from dataclasses import dataclass

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.models.generation import GenerationMixin
from paddle_tpu.parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt2_345m", "gpt2_tiny"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 1024
    intermediate_size: int = None
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    tensor_parallel: bool = False
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        if cfg.tensor_parallel:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.proj = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.proj = nn.Linear(h, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        b = x.shape[0]
        s = x.shape[1]
        nh, hd = self.cfg.num_heads, self.cfg.hidden_size // self.cfg.num_heads
        qkv = self.qkv(x)
        qkv = qkv.reshape([b, s, 3, nh, hd])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=self.training)
        out = out.reshape([b, s, nh * hd])
        return self.dropout(self.proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        if cfg.tensor_parallel:
            self.fc1 = ColumnParallelLinear(h, m, gather_output=False)
            self.fc2 = RowParallelLinear(m, h, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, m)
            self.fc2 = nn.Linear(m, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlp = GPTMLP(cfg)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        # GPT-2 init: N(0, 0.02) embeddings (keeps init CE near ln(V))
        from paddle_tpu.nn.initializer import Normal
        init = Normal(0.0, 0.02)
        self.wte.weight._replace_value(
            init(self.wte.weight.shape, self.wte.weight.dtype))
        self.wpe.weight._replace_value(
            init(self.wpe.weight.shape, self.wpe.weight.dtype))
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids, position_ids=None):
        import paddle_tpu as pt
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = pt.ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        # tied output projection (weight reuse, like the reference example)
        self.lm_head_weight = self.gpt.wte.weight

    def forward(self, input_ids, position_ids=None, return_hidden=False):
        h = self.gpt(input_ids, position_ids)
        if return_hidden:
            # for fused linear+CE losses (ops/fused_ce.py): caller applies
            # the tied lm head inside the chunked loss
            return h
        from ..ops.registry import OPS
        return OPS["matmul"](h, self.lm_head_weight, transpose_y=True)

    def loss(self, logits, labels):
        """Shifted causal LM loss."""
        lg = logits[:, :-1, :]
        lb = labels[:, 1:]
        return F.cross_entropy(lg, lb)

    def pipeline_decompose(self):
        """Pure fns + param trees for the 1F1B/hybrid builders, WITH the
        tied lm head (reference SharedLayerDesc GPT demo): the embedding
        table is the shared weight, so the builder gets
        tie_embed_head=True and stores it pp/mp-sharded; wpe and the
        final LN ride along as replicated extras.

        Returns ((block_fn, embed_fn, head_loss_fn),
                 (blocks, embed, head), {"tie_embed_head": True}).
        """
        import jax
        import jax.numpy as jnp

        from ..core.tensor import unwrap
        from ..jit import functional_call
        if self.cfg.tensor_parallel:
            # no-roadmap: API redirect to the hybrid factories, not a cut
            raise NotImplementedError(
                "pipeline_decompose targets the non-TP module; for mp×pp "
                "use parallel.hybrid factories")
        proto = self.gpt.blocks[0]
        blocks = [dict(blk.raw_params()) for blk in self.gpt.blocks]
        embed = {"table": unwrap(self.gpt.wte.weight),
                 "wpe": unwrap(self.gpt.wpe.weight)}
        head = {"ln_g": unwrap(self.gpt.ln_f.weight),
                "ln_b": unwrap(self.gpt.ln_f.bias)}
        eps = self.cfg.layer_norm_eps

        def block_fn(p, x):
            return functional_call(proto, p, x)

        def embed_fn(p, ids):
            s = ids.shape[-1]
            return p["table"][ids] + p["wpe"][:s][None]

        def _final_ln(p, hidden):
            mu = hidden.mean(-1, keepdims=True)
            var = jnp.var(hidden.astype(jnp.float32), -1, keepdims=True)
            return ((hidden - mu) * jax.lax.rsqrt(var + eps)
                    ) * p["ln_g"] + p["ln_b"]

        def head_loss_fn(p, hidden, labels):
            lg = (_final_ln(p, hidden) @ p["table"].T
                  ).astype(jnp.float32)[:, :-1]
            logp = jax.nn.log_softmax(lg, -1)
            return -jnp.take_along_axis(
                logp, labels[:, 1:, None], -1).mean()

        def head_out_fn(p, hidden, labels):
            # Engine.predict through the pipeline: full-seq logits via
            # the tied table (the builder injects p["table"] gathered)
            return (_final_ln(p, hidden) @ p["table"].T
                    ).astype(jnp.float32)

        return ((block_fn, embed_fn, head_loss_fn),
                (blocks, embed, head),
                {"tie_embed_head": True, "head_out_fn": head_out_fn})

    def pipeline_recompose(self, params, layout):
        """Inverse of pipeline_decompose + stacking: write trained
        stage-stacked params back into this module (the tied table
        writes once — lm_head_weight aliases wte.weight)."""
        counts, starts, S, v = layout
        for vs in range(S * v):
            v_idx, s_idx = vs // S, vs % S
            for j in range(int(counts[vs])):
                layer = self.gpt.blocks[int(starts[vs]) + j]
                layer.load_raw_params(
                    {n: a[v_idx, s_idx, j]
                     for n, a in params["blocks"].items()})
        import numpy as _np
        self.gpt.wte.weight._replace_value(
            _np.asarray(params["embed"]["table"]))
        self.gpt.wpe.weight._replace_value(
            _np.asarray(params["embed"]["wpe"]))
        self.gpt.ln_f.weight._replace_value(
            _np.asarray(params["head"]["ln_g"]))
        self.gpt.ln_f.bias._replace_value(
            _np.asarray(params["head"]["ln_b"]))


def gpt2_345m(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, max_seq_len=1024, **kw)


def gpt2_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 128)
    return GPTConfig(**kw)
