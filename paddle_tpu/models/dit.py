"""DiT (Diffusion Transformer, DiT/SD3-family backbone).

BASELINE.md row "DiT / SD3 ... diffusion via auto_parallel
(ProcessMesh/shard_tensor) path — functional". Reference capability: the
PaddleMIX DiT stack layered on the reference's auto_parallel API
(python/paddle/distributed/auto_parallel/interface.py:28); here the
backbone is built on paddle_tpu.nn with adaLN-Zero conditioning and the
Pallas attention path, and `shard` annotates parameters for a dp×mp
ProcessMesh so GSPMD partitions the transformer.

Training objective (test + example): epsilon-prediction MSE on a
DDPM-style cosine schedule (`DiTForDiffusion.loss`).
"""
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import dispatch

from ._stem import patches_to_seq, shard_params_by_name

__all__ = ["DiTConfig", "DiT", "DiTForDiffusion", "dit_s_4", "dit_tiny"]


@dataclass
class DiTConfig:
    image_size: int = 32          # latent spatial size
    patch_size: int = 4
    in_channels: int = 4
    hidden_size: int = 384
    num_layers: int = 12
    num_heads: int = 6
    num_classes: int = 1000
    mlp_ratio: float = 4.0
    learn_sigma: bool = False
    dtype: str = "float32"

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2

    @property
    def out_channels(self):
        return self.in_channels * (2 if self.learn_sigma else 1)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal timestep embedding (DiT convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class TimestepEmbedder(nn.Layer):
    def __init__(self, hidden_size, freq_dim=256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp = nn.Sequential(
            nn.Linear(freq_dim, hidden_size), nn.SiLU(),
            nn.Linear(hidden_size, hidden_size))

    def forward(self, t):
        emb = dispatch(lambda tv: timestep_embedding(tv, self.freq_dim),
                       t, name="timestep_embedding")
        return self.mlp(emb)


class LabelEmbedder(nn.Layer):
    """Class-conditioning; index num_classes = the null (CFG-dropped) label."""

    def __init__(self, num_classes, hidden_size):
        super().__init__()
        self.table = nn.Embedding(num_classes + 1, hidden_size)

    def forward(self, y):
        return self.table(y)


def _modulate(x, shift, scale):
    return x * (1.0 + scale[:, None, :]) + shift[:, None, :]


class DiTBlock(nn.Layer):
    """adaLN-Zero block: conditioning predicts per-block shift/scale/gate
    for attention and MLP branches; gates start at zero (identity init)."""

    def __init__(self, cfg: DiTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.norm1 = nn.LayerNorm(h)
        self.norm2 = nn.LayerNorm(h)
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)
        m = int(h * cfg.mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(h, m), nn.GELU(approximate=True),
                                 nn.Linear(m, h))
        from paddle_tpu.nn.initializer import Constant
        self.ada = nn.Linear(h, 6 * h,
                             weight_attr=Constant(0.0),
                             bias_attr=Constant(0.0))

    def _attn(self, x):
        b, s, h = x.shape
        hd = h // self.num_heads
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=False,
                                             training=self.training)
        return self.proj(out.reshape([b, s, h]))

    def forward(self, x, c):
        mod = self.ada(F.silu(c))
        h = x.shape[-1]
        sh1, sc1, g1, sh2, sc2, g2 = [mod[:, i * h:(i + 1) * h]
                                      for i in range(6)]
        x = x + g1[:, None, :] * self._attn(
            _modulate(self.norm1(x), sh1, sc1))
        x = x + g2[:, None, :] * self.mlp(
            _modulate(self.norm2(x), sh2, sc2))
        return x


class FinalLayer(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.norm = nn.LayerNorm(h)
        from paddle_tpu.nn.initializer import Constant
        self.ada = nn.Linear(h, 2 * h, weight_attr=Constant(0.0),
                             bias_attr=Constant(0.0))
        self.linear = nn.Linear(
            h, cfg.patch_size * cfg.patch_size * cfg.out_channels,
            weight_attr=Constant(0.0), bias_attr=Constant(0.0))

    def forward(self, x, c):
        mod = self.ada(F.silu(c))
        h = x.shape[-1]
        shift, scale = mod[:, :h], mod[:, h:]
        return self.linear(_modulate(self.norm(x), shift, scale))


class DiT(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__()
        self.cfg = cfg
        p = cfg.patch_size
        self.patch_embed = nn.Conv2D(cfg.in_channels, cfg.hidden_size,
                                     kernel_size=p, stride=p)
        from paddle_tpu.nn.initializer import Normal
        self.pos_embed = self.create_parameter(
            (1, cfg.num_patches, cfg.hidden_size),
            default_initializer=Normal(0.0, 0.02))
        self.t_embedder = TimestepEmbedder(cfg.hidden_size)
        self.y_embedder = LabelEmbedder(cfg.num_classes, cfg.hidden_size)
        self.blocks = nn.LayerList([DiTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.final_layer = FinalLayer(cfg)

    def unpatchify(self, x):
        cfg = self.cfg
        p, c = cfg.patch_size, cfg.out_channels
        hw = cfg.image_size // p

        def fn(v):
            b = v.shape[0]
            v = v.reshape(b, hw, hw, p, p, c)
            v = jnp.einsum("bhwpqc->bchpwq", v)
            return v.reshape(b, c, hw * p, hw * p)

        return dispatch(fn, x, name="unpatchify")

    def forward(self, x, t, y=None):
        """x: [B, C, H, W] latents; t: [B] timesteps; y: [B] labels."""
        cfg = self.cfg
        h = patches_to_seq(self.patch_embed(x)) + self.pos_embed
        c = self.t_embedder(t)
        if y is not None:
            c = c + self.y_embedder(y)
        for blk in self.blocks:
            h = blk(h, c)
        out = self.final_layer(h, c)               # [B, T, p*p*C]
        return self.unpatchify(out)


class DiTForDiffusion(nn.Layer):
    """DDPM epsilon-prediction wrapper: cosine alphā schedule, MSE loss."""

    def __init__(self, cfg: DiTConfig, num_train_timesteps=1000):
        super().__init__()
        self.cfg = cfg
        self.dit = DiT(cfg)
        self.num_train_timesteps = num_train_timesteps
        s = 0.008
        steps = jnp.arange(num_train_timesteps + 1, dtype=jnp.float32)
        f = jnp.cos((steps / num_train_timesteps + s) / (1 + s)
                    * math.pi / 2) ** 2
        self.alphas_cumprod = (f / f[0])[:-1]

    def forward(self, x, t, y=None):
        return self.dit(x, t, y)

    def add_noise(self, x0, noise, t):
        def fn(x0v, nv, tv):
            a = self.alphas_cumprod[tv][:, None, None, None]
            return jnp.sqrt(a) * x0v + jnp.sqrt(1.0 - a) * nv

        return dispatch(fn, x0, noise, t, nondiff_args=(2,),
                        name="ddpm_add_noise")

    def loss(self, x0, t, noise, y=None):
        xt = self.add_noise(x0, noise, t)
        pred = self.dit(xt, t, y)
        if self.cfg.learn_sigma:
            pred = pred[:, :self.cfg.in_channels]
        return F.mse_loss(pred, noise)


def shard_dit(model, process_mesh):
    """auto_parallel annotation: wide qkv/MLP projections over 'mp',
    everything else replicated; GSPMD derives the rest."""
    return shard_params_by_name(model, process_mesh, ("qkv", "mlp"))


def dit_s_4(**kw):
    kw.setdefault("hidden_size", 384)
    kw.setdefault("num_layers", 12)
    kw.setdefault("num_heads", 6)
    kw.setdefault("patch_size", 4)
    return DiTConfig(**kw)


def dit_tiny(**kw):
    kw.setdefault("image_size", 8)
    kw.setdefault("patch_size", 2)
    kw.setdefault("in_channels", 3)
    kw.setdefault("hidden_size", 32)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_classes", 10)
    return DiTConfig(**kw)
