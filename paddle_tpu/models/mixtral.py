"""Mixtral / DeepSeek-MoE family: Llama backbone with a routed SwiGLU
expert FFN (top-k gating, capacity buckets, load-balance aux loss).

BASELINE.md row "DeepSeek-MoE / Mixtral: expert parallel on TPU mesh —
functional + MFU reported". Reference capability:
python/paddle/incubate/distributed/models/moe/moe_layer.py:261 (MoELayer
over global_scatter/global_gather) — here the TPU-native MoELayer
(parallel/moe/layer.py) with GShard grouped einsum dispatch; experts are
sharded over the mesh's model axis (EP via GSPMD on the stacked expert
dim, or lax.all_to_all inside shard_map).
"""
from dataclasses import dataclass

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.models.generation import GenerationMixin
from paddle_tpu.parallel.moe import ExpertSwiGLU, MoELayer

from .llama import LlamaAttention, LlamaConfig

__all__ = ["MixtralConfig", "MixtralModel", "MixtralForCausalLM",
           "mixtral_8x7b", "mixtral_tiny", "moe_350m_8e"]


@dataclass
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    moe_group_size: int = None   # tokens per dispatch group; None = seq len

    @property
    def active_params_ratio(self):
        """Fraction of expert params active per token (for MFU accounting)."""
        return self.top_k / self.num_experts


class MixtralBlock(nn.Layer):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_eps)
        experts = ExpertSwiGLU(cfg.num_experts, cfg.hidden_size,
                               cfg.intermediate_size)
        self.moe = MoELayer(cfg.hidden_size, experts=experts,
                            gate="gshard", top_k=cfg.top_k,
                            capacity_factor=cfg.capacity_factor,
                            group_size=cfg.moe_group_size or cfg.max_seq_len)

    def forward(self, x, position_ids=None):
        x = x + self.self_attn(self.input_layernorm(x), position_ids)
        x = x + self.moe(self.post_attention_layernorm(x))
        return x


class MixtralModel(nn.Layer):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        from paddle_tpu.nn.initializer import Normal
        w = self.embed_tokens.weight
        w._replace_value(Normal(0.0, 0.02)(w.shape, w.dtype))
        self.layers = nn.LayerList([MixtralBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids, position_ids=None):
        x = self.embed_tokens(input_ids)
        for blk in self.layers:
            x = blk(x, position_ids)
        return self.norm(x)


class MixtralForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.cfg = cfg
        self.model = MixtralModel(cfg)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        return self.lm_head(self.model(input_ids, position_ids))

    def collect_aux_loss(self):
        """Sum of per-layer load-balance losses from the last forward
        (valid inside the same jit trace / eager step)."""
        total = None
        for blk in self.model.layers:
            a = blk.moe.aux_loss
            if a is None:
                continue
            total = a if total is None else total + a
        return total

    def loss(self, logits, labels):
        ce = F.cross_entropy(logits[:, :-1, :], labels[:, 1:])
        aux = self.collect_aux_loss()
        if aux is not None:
            ce = ce + self.cfg.aux_loss_coef * aux
        return ce


def mixtral_8x7b(**kw):
    kw.setdefault("hidden_size", 4096)
    kw.setdefault("num_layers", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("num_kv_heads", 8)
    kw.setdefault("intermediate_size", 14336)
    kw.setdefault("num_experts", 8)
    kw.setdefault("top_k", 2)
    return MixtralConfig(**kw)


def moe_350m_8e(**kw):
    """Single-chip MoE bench config: ~190M active / ~530M total params."""
    kw.setdefault("vocab_size", 32000)
    kw.setdefault("hidden_size", 768)
    kw.setdefault("num_layers", 12)
    kw.setdefault("num_heads", 12)
    kw.setdefault("intermediate_size", 2048)
    kw.setdefault("max_seq_len", 1024)
    kw.setdefault("num_experts", 8)
    kw.setdefault("top_k", 2)
    return MixtralConfig(**kw)


def mixtral_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 2)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("num_experts", 4)
    kw.setdefault("top_k", 2)
    return MixtralConfig(**kw)
