"""Benchmark: flagship train-step throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Model: GPT-2 345M causal-LM train step (BASELINE.json config 1), bf16
compute, jitted end-to-end (forward+backward+AdamW). MFU accounting per
BASELINE.md: 6*N*tokens/sec / peak bf16 FLOPs; vs_baseline is the fraction
of the 45%-MFU north star.
"""
import json
import sys
import time

import numpy as np


def _devices_with_retry(attempts=6):
    """Bring up the accelerator backend with retries.

    Round-1 failure mode: the first backend touch raised
    `Unable to initialize backend 'axon': UNAVAILABLE` (remote TPU relay
    still warming up) and the script died with no JSON line. Retry with
    backoff; raise only after all attempts. A "not in the list of known
    backends" failure means plugin *discovery* failed at import — that is
    permanent for the process, so re-exec to retry registration.
    """
    import os
    import jax
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            if devs:
                return devs
        except Exception as e:  # backend init faults are RuntimeError-ish
            last = e
            if "not in the list of known backends" in str(e):
                n = int(os.environ.get("PT_BENCH_REEXEC", "0"))
                if n < 5:
                    os.environ["PT_BENCH_REEXEC"] = str(n + 1)
                    time.sleep(min(2 ** n * 5, 60))
                    os.execv(sys.executable, [sys.executable] + sys.argv)
                raise
            time.sleep(min(2 ** i, 30))
    raise last if last else RuntimeError("no jax devices")


def _cpu_device_or_none():
    """CPU staging device for cheap param init; never fault the run."""
    import jax
    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:
        return None


def peak_flops_bf16():
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


METRICS = {
    "gpt2": "gpt2_345m_train_tokens_per_sec_per_chip",
    "llama350m": "llama_350m_train_tokens_per_sec_per_chip",
    "moe": "mixtral_8e_top2_train_tokens_per_sec_per_chip",
}


def _build_model(config_name):
    """Returns (model, cfg, metric_name, batch, seq)."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_345m
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_350m

    if config_name == "llama350m":
        # BASELINE.md's llama family on the single bench chip: the 7B
        # TP(+sharding) configs need a multi-chip slice; this runs the
        # same architecture (RMSNorm/rope/SwiGLU/flash-attn path) sized
        # for one chip and reports the same tokens/s/chip metric.
        cfg = llama_350m()
        return (LlamaForCausalLM(cfg), cfg, METRICS["llama350m"], 8, 1024)
    if config_name == "moe":
        # BASELINE.md MoE row (DeepSeek-MoE / Mixtral family): top-2 of 8
        # SwiGLU experts, GShard grouped dispatch, aux loss in the step.
        from paddle_tpu.models.mixtral import MixtralForCausalLM, moe_350m_8e
        cfg = moe_350m_8e(moe_group_size=1024)
        return (MixtralForCausalLM(cfg), cfg, METRICS["moe"], 8, 1024)
    cfg = gpt2_345m(dropout=0.0)
    return (GPTForCausalLM(cfg), cfg, METRICS["gpt2"], 8, 1024)


def _probe_device_responsive(timeout_s=75):
    """The relay can wedge AFTER backend init: ops hang forever (observed
    2026-07-30, >7 h outage). Probe with a tiny matmul in a subprocess
    under a hard timeout so the bench fails fast with a JSON line instead
    of hanging the driver.

    Probes are SPREAD across the run window with exponential backoff
    (15 s → 4 min sleeps, ~13 min total worst case) instead of
    back-to-back — a relay recovering mid-window gets caught (round-3
    post-mortem: 3×180 s up-front probes all landed inside one outage).
    Override via PT_BENCH_PROBE_SLEEPS="15,30,60" (seconds, csv).

    Only a TIMEOUT counts as unresponsive — a fast nonzero exit is a
    backend-INIT failure, which _devices_with_retry's backoff/re-exec
    path already knows how to recover; let it run."""
    import os
    import subprocess
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((64, 64));"
            "print(float((x @ x).sum()))")
    sleeps_env = os.environ.get("PT_BENCH_PROBE_SLEEPS", "15,30,60,120,240")
    sleeps = [int(s) for s in sleeps_env.split(",") if s.strip()]
    attempts = len(sleeps) + 1
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, timeout=timeout_s)
            if r.returncode != 0:
                print(f"device probe init error (attempt {i + 1}): "
                      f"{r.stderr.decode(errors='replace')[-300:]}",
                      file=sys.stderr)
            return True   # responsive (even if init failed: retryable)
        except subprocess.TimeoutExpired:
            print(f"device probe {i + 1}/{attempts} timed out "
                  f"({timeout_s}s)", file=sys.stderr)
            if i < attempts - 1:
                time.sleep(sleeps[i])
    return False


def main(config_name="gpt2"):
    # probe FIRST, in a subprocess: when the relay wedges, even
    # jax.devices() in this process can hang with no exception to catch
    if not _probe_device_responsive():
        # emit a parseable failure line (under the REAL metric name so
        # the driver's records line up) rather than hanging
        print(json.dumps({
            "metric": METRICS.get(
                config_name, f"{config_name}_train_tokens_per_sec_per_chip"),
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0,
        }))
        print("DEVICE UNRESPONSIVE: accelerator ops hang (relay outage) "
              "— no measurement possible this run", file=sys.stderr)
        return

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit import functional_call

    devices = _devices_with_retry()

    # Build params on the CPU backend: on remote-execution TPU setups each
    # device-side init op would pay a separate remote compile.
    cpu = _cpu_device_or_none()
    import contextlib
    with (jax.default_device(cpu) if cpu is not None
          else contextlib.nullcontext()):
        model, cfg, metric, batch, seq = _build_model(config_name)
        model.astype("bfloat16")
        model.eval()  # dropout off; still training math
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        init_fn, update_fn = opt.functional()
        params = model.raw_params()
        n_params = sum(int(np.prod(v.shape)) for v in params.values())
        state = init_fn(params)
        # master fp32 moments for stability (cheap on HBM at 345M)
        state = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), state)
    dev = devices[0]
    params = jax.device_put(params, dev)
    state = jax.device_put(state, dev)

    def loss_fn(logits, labels):
        lg = logits[:, :-1]
        lb = labels[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

    is_moe = config_name == "moe"
    # fused chunked linear+CE (ops/fused_ce.py): avoids materializing the
    # [B,S,V] fp32 logits; enabled for the dense LM configs
    import os as _os
    # default off until A/B-measured on the real chip (flip after
    # benchmarks/fused_ce_bench.py shows a win)
    fused_ce = (config_name in ("gpt2", "llama350m")
                and _os.environ.get("PT_BENCH_FUSED_CE", "0") != "0")

    def step(params, state, ids, i):
        def compute(ps):
            if fused_ce:
                from paddle_tpu.ops.fused_ce import (
                    fused_linear_cross_entropy)
                hidden = functional_call(model, ps, ids, return_hidden=True)
                w = (ps["lm_head_weight"].T if config_name == "gpt2"
                     else ps["lm_head.weight"])
                return fused_linear_cross_entropy(
                    hidden[:, :-1], w, ids[:, 1:], chunk_size=2046)
            logits = functional_call(model, ps, ids)
            l = loss_fn(logits, ids)
            if is_moe:
                from paddle_tpu.core.tensor import unwrap
                aux = model.collect_aux_loss()
                if aux is not None:
                    l = l + cfg.aux_loss_coef * unwrap(aux)
            return l

        loss, grads = jax.value_and_grad(compute)(params)
        new_p, new_s = update_fn(grads, params, state, step=i)
        return loss, new_p, new_s

    step = jax.jit(step, donate_argnums=(0, 1))

    ids = np.random.randint(0, cfg.vocab_size, size=(batch, seq)).astype(
        np.int32)
    ids = jax.device_put(ids, dev)

    # warmup / compile (float() forces a host fetch — robust under the
    # remote-execution relay where block_until_ready alone is unreliable)
    loss, params, state = step(params, state, ids, 1)
    float(loss)
    loss, params, state = step(params, state, ids, 2)
    float(loss)

    iters = 10
    t0 = time.perf_counter()
    for i in range(iters):
        loss, params, state = step(params, state, ids, i + 3)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    n_active = n_params
    if is_moe:
        # MoE MFU counts ACTIVE params per token (top_k of num_experts);
        # capacity padding/drops are overhead, not useful FLOPs.
        exp = sum(int(np.prod(v.shape)) for k, v in params.items()
                  if ".experts." in k)
        n_active = n_params - exp + exp * cfg.top_k / cfg.num_experts
    flops_per_token = 6 * n_active
    # causal attention flops: 12 * L * S^2 * H per token pair accounting
    attn_flops = 12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tokens_per_sec * (flops_per_token + attn_flops) / peak_flops_bf16()

    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    print(f"  loss={final_loss:.4f} mfu={mfu:.3f} "
          f"params={n_params/1e6:.1f}M step_time={dt/iters*1000:.1f}ms",
          file=sys.stderr)


if __name__ == "__main__":
    _argv = sys.argv[1:]
    _cfg = "gpt2"
    for _name in ("llama350m", "moe"):
        if f"--config={_name}" in _argv or _name in _argv:
            _cfg = _name
    main(_cfg)
