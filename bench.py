"""Benchmark: flagship train-step throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Model: GPT-2 345M causal-LM train step (BASELINE.json config 1), bf16
compute, jitted end-to-end (forward+backward+AdamW). MFU accounting per
BASELINE.md: 6*N*tokens/sec / peak bf16 FLOPs; vs_baseline is the fraction
of the 45%-MFU north star.
"""
import json
import sys
import time

import numpy as np

import os as _os
BENCHLOG = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                         "BENCHLOG.jsonl")


def emit(record):
    """Print the driver's JSON line and, for real measurements, append
    to BENCHLOG.jsonl (committed) — the durable record of every number
    this chip actually produced, cited on later outage runs."""
    print(json.dumps(record))
    import os
    if record.get("value") and not os.environ.get("PT_BENCH_FORCE_CPU"):
        try:
            with open(BENCHLOG, "a") as f:
                f.write(json.dumps(
                    dict(record, ts=time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()))) + "\n")
        except OSError:
            pass


def last_measurement(metric):
    try:
        with open(BENCHLOG) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        return None
    recs = [r for r in recs if r.get("metric") == metric]
    return recs[-1] if recs else None


def _devices_with_retry(attempts=6):
    """Bring up the accelerator backend with retries.

    Round-1 failure mode: the first backend touch raised
    `Unable to initialize backend 'axon': UNAVAILABLE` (remote TPU relay
    still warming up) and the script died with no JSON line. Retry with
    backoff; raise only after all attempts. A "not in the list of known
    backends" failure means plugin *discovery* failed at import — that is
    permanent for the process, so re-exec to retry registration.
    """
    import os
    import jax
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            if devs:
                return devs
        except Exception as e:  # backend init faults are RuntimeError-ish
            last = e
            if "not in the list of known backends" in str(e):
                n = int(os.environ.get("PT_BENCH_REEXEC", "0"))
                if n < 5:
                    os.environ["PT_BENCH_REEXEC"] = str(n + 1)
                    time.sleep(min(2 ** n * 5, 60))
                    os.execv(sys.executable, [sys.executable] + sys.argv)
                raise
            time.sleep(min(2 ** i, 30))
    raise last if last else RuntimeError("no jax devices")


def _cpu_device_or_none():
    """CPU staging device for cheap param init; never fault the run."""
    import jax
    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:
        return None


def peak_flops_bf16():
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def peak_hbm_bw():
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 819e9
    if "v5p" in kind or "v5" in kind:
        return 2765e9
    if "v4" in kind:
        return 1228e9
    if "v6" in kind or "trillium" in kind:
        return 1640e9
    return 819e9


METRICS = {
    "gpt2": "gpt2_345m_train_tokens_per_sec_per_chip",
    "llama350m": "llama_350m_train_tokens_per_sec_per_chip",
    "moe": "mixtral_8e_top2_train_tokens_per_sec_per_chip",
    "llama1b3": "llama_1b3_train_tokens_per_sec_per_chip",
    "llama2b7": "llama_2b7_train_tokens_per_sec_per_chip",
    "decode": "gpt2_345m_decode_tokens_per_sec",
    "serve": "gpt2_345m_serve_tokens_per_sec",
}


def _build_model(config_name):
    """Returns (model, cfg, metric_name, batch, seq)."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_345m
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_350m

    if config_name == "llama350m":
        # BASELINE.md's llama family on the single bench chip: the 7B
        # TP(+sharding) configs need a multi-chip slice; this runs the
        # same architecture (RMSNorm/rope/SwiGLU/flash-attn path) sized
        # for one chip and reports the same tokens/s/chip metric.
        cfg = llama_350m()
        return (LlamaForCausalLM(cfg), cfg, METRICS["llama350m"], 8, 1024)
    if config_name == "moe":
        # BASELINE.md MoE row (DeepSeek-MoE / Mixtral family): top-2 of 8
        # SwiGLU experts, GShard grouped dispatch, aux loss in the step.
        from paddle_tpu.models.mixtral import MixtralForCausalLM, moe_350m_8e
        cfg = moe_350m_8e(moe_group_size=1024)
        return (MixtralForCausalLM(cfg), cfg, METRICS["moe"], 8, 1024)
    cfg = gpt2_345m(dropout=0.0)
    return (GPTForCausalLM(cfg), cfg, METRICS["gpt2"], 8, 1024)


def _probe_device_responsive(timeout_s=75):
    """The relay can wedge AFTER backend init: ops hang forever (observed
    2026-07-30, >7 h outage). Probe with a tiny matmul in a subprocess
    under a hard timeout so the bench fails fast with a JSON line instead
    of hanging the driver.

    Probes are SPREAD across the run window with exponential backoff
    (15 s → 4 min sleeps, ~13 min total worst case) instead of
    back-to-back — a relay recovering mid-window gets caught (round-3
    post-mortem: 3×180 s up-front probes all landed inside one outage).
    Override via PT_BENCH_PROBE_SLEEPS="15,30,60" (seconds, csv).

    Only a TIMEOUT counts as unresponsive — a fast nonzero exit is a
    backend-INIT failure, which _devices_with_retry's backoff/re-exec
    path already knows how to recover; let it run."""
    import os
    import subprocess
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((64, 64));"
            "print(float((x @ x).sum()))")
    sleeps_env = os.environ.get("PT_BENCH_PROBE_SLEEPS", "15,30,60,120,240")
    sleeps = [int(s) for s in sleeps_env.split(",") if s.strip()]
    attempts = len(sleeps) + 1
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, timeout=timeout_s)
            if r.returncode != 0:
                print(f"device probe init error (attempt {i + 1}): "
                      f"{r.stderr.decode(errors='replace')[-300:]}",
                      file=sys.stderr)
            return True   # responsive (even if init failed: retryable)
        except subprocess.TimeoutExpired:
            print(f"device probe {i + 1}/{attempts} timed out "
                  f"({timeout_s}s)", file=sys.stderr)
            if i < attempts - 1:
                time.sleep(sleeps[i])
    return False


def main_llama1b3(config_name="llama1b3"):
    """Largest-fits single-chip runs (VERDICT r5 #2).

    llama1b3: a 1.26B llama (TinyLlama-class: L=22, H=2048, F=5632,
    16 heads x 128) trained bf16 with per-block rematerialization,
    Pallas flash attention, and chunked fused linear+CE — HBM budget
    (16 GB): params 2.5 GB + grads 2.5 GB + bf16 Adam moments 5 GB +
    remat'd activations ~0.8 GB.

    llama2b7: the stretch point — ~2.7B (L=32, H=2560, F=6912, 20
    heads x 128) with an Adafactor-style factored second moment (+
    first-moment-free) update: params 5.4 GB + grads 5.4 GB + factored
    state ~15 MB + remat'd activations; the moment memory Adam would
    need (11 GB) does not fit beside them. The measured trend across
    345M -> 1.26B -> 2.7B is the evidence line toward the 7B row.

    The step builds from raw stacked arrays (no Layer objects) so
    device init is ONE jitted program instead of per-param transfers
    through the relay.
    """
    import os
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.parallel.hybrid import _rope_tables_np

    big = config_name == "llama2b7"
    if big:
        L_, H_, F_, V_ = 32, 2560, 6912, 32000
        NH = 20
    else:
        L_, H_, F_, V_ = 22, 2048, 5632, 32000
        NH = 16
    opt = os.environ.get("PT_BENCH_2B_OPT",
                         "adafactor" if big else "adam")
    dims = os.environ.get("PT_BENCH_2B_DIMS")    # "L,H,F,V,NH" (smoke)
    if dims:
        L_, H_, F_, V_, NH = (int(x) for x in dims.split(","))
    HD = H_ // NH
    B = int(os.environ.get("PT_BENCH_2B_BATCH", "2" if big else "4"))
    S = int(os.environ.get("PT_BENCH_2B_SEQ", "2048"))
    fused = os.environ.get("PT_BENCH_2B_FUSED", "1") != "0"
    eps = 1e-5

    devices = _devices_with_retry()
    dev = devices[0]

    def init(key):
        ks = jax.random.split(key, 10)
        sd = 0.02

        def nrm(k, *shape):
            return (jax.random.normal(k, shape, jnp.float32) * sd
                    ).astype(jnp.bfloat16)

        return {
            "table": nrm(ks[0], V_, H_),
            "blocks": {
                "ln1": jnp.ones((L_, H_), jnp.bfloat16),
                "ln2": jnp.ones((L_, H_), jnp.bfloat16),
                "wq": nrm(ks[1], L_, H_, H_), "wk": nrm(ks[2], L_, H_, H_),
                "wv": nrm(ks[3], L_, H_, H_), "wo": nrm(ks[4], L_, H_, H_),
                "wg": nrm(ks[5], L_, H_, F_), "wu": nrm(ks[6], L_, H_, F_),
                "wd": nrm(ks[7], L_, F_, H_),
            },
            "norm": jnp.ones((H_,), jnp.bfloat16),
            "head": nrm(ks[8], H_, V_),
        }

    with jax.default_device(dev):
        params = jax.jit(init)(jax.random.PRNGKey(0))
        params = jax.tree_util.tree_map(
            lambda a: a.block_until_ready(), params)
        if opt == "adafactor":
            # factored second moment (Shazeer-Stern): row/col accumulators
            # over the trailing matrix dims — ~15 MB of state for 2.7B
            state = {
                "vr": jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        p.shape[:-1] if p.ndim >= 2 else p.shape,
                        jnp.float32), params),
                "vc": jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        p.shape[:-2] + p.shape[-1:] if p.ndim >= 2
                        else (1,), jnp.float32), params),
            }
        else:
            # bf16 moments: the 20-step bench measures throughput; fp32
            # moments (+5 GB) would not fit beside grads at this size
            state = {"m": jax.tree_util.tree_map(jnp.zeros_like, params),
                     "v": jax.tree_util.tree_map(jnp.zeros_like, params)}
    n_params = sum(int(np.prod(v.shape))
                   for v in jax.tree_util.tree_leaves(params))

    cos_np, sin_np = _rope_tables_np(HD, S, 10000.0)
    cos = jnp.asarray(cos_np, jnp.bfloat16)
    sin = jnp.asarray(sin_np, jnp.bfloat16)

    def rms(x, w):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                       keepdims=True)
        return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
                ).astype(x.dtype) * w

    def rope(t):
        # t [B, S, NH, HD]; tables [S, HD/2]
        t1, t2 = jnp.split(t, 2, axis=-1)
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return jnp.concatenate([t1 * c - t2 * s, t1 * s + t2 * c], -1)

    use_flash = fa.available()

    def attn(q, k, v):
        if use_flash:
            return fa._flash(q.transpose(0, 2, 1, 3),
                             k.transpose(0, 2, 1, 3),
                             v.transpose(0, 2, 1, 3), 1.0 / np.sqrt(HD),
                             True).transpose(0, 2, 1, 3)
        # CPU smoke-test fallback (the real bench always runs on TPU)
        lg = jnp.einsum("bqnd,bknd->bnqk", q, k) / np.sqrt(HD)
        mask = jnp.tril(jnp.ones((S, S), bool))
        lg = jnp.where(mask, lg, jnp.finfo(lg.dtype).min)
        p_ = jax.nn.softmax(lg.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bnqk,bknd->bqnd", p_, v)

    def block(p, x):
        hn = rms(x, p["ln1"])
        q = rope((hn @ p["wq"]).reshape(B, S, NH, HD))
        k = rope((hn @ p["wk"]).reshape(B, S, NH, HD))
        v = (hn @ p["wv"]).reshape(B, S, NH, HD)
        x = x + attn(q, k, v).reshape(B, S, H_) @ p["wo"]
        hn = rms(x, p["ln2"])
        return x + (jax.nn.silu(hn @ p["wg"]) * (hn @ p["wu"])) @ p["wd"]

    def fwd(ps, ids):
        x = ps["table"][ids]

        def body(xx, blk):
            return block(blk, xx), None

        x, _ = jax.lax.scan(jax.checkpoint(body), x, ps["blocks"])
        h = rms(x, ps["norm"])
        if fused:
            return fused_linear_cross_entropy(
                h[:, :-1], ps["head"], ids[:, 1:], chunk_size=2046)
        lg = (h[:, :-1] @ ps["head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids[:, 1:, None], -1).mean()

    b1, b2, lr, adam_eps = 0.9, 0.999, 1e-4, 1e-8

    def step(params, state, ids, i):
        loss, grads = jax.value_and_grad(fwd)(params, ids)

        is_tup = lambda t: isinstance(t, tuple)  # noqa: E731

        if opt == "adafactor":
            def upd(p, g, vr, vc):
                g2 = jnp.square(g.astype(jnp.float32)) + 1e-30
                if p.ndim >= 2:
                    vr2 = b2 * vr + (1 - b2) * g2.mean(-1)
                    vc2 = b2 * vc + (1 - b2) * g2.mean(-2)
                    vhat = (vr2[..., :, None] * vc2[..., None, :]
                            / (vr2.sum(-1, keepdims=True)[..., None]
                               + 1e-30))
                else:
                    vr2 = b2 * vr + (1 - b2) * g2
                    vc2 = vc
                    vhat = vr2
                vhat = vhat / (1 - jnp.power(b2, i))
                u = g.astype(jnp.float32) / jnp.sqrt(vhat + 1e-30)
                rms = jnp.sqrt(jnp.mean(jnp.square(u)) + 1e-30)
                u = u / jnp.maximum(1.0, rms)     # update clipping d=1
                p2 = p.astype(jnp.float32) - lr * u
                return (p2.astype(p.dtype), vr2, vc2)

            out = jax.tree_util.tree_map(upd, params, grads,
                                         state["vr"], state["vc"])
            return (loss,
                    jax.tree_util.tree_map(lambda t: t[0], out,
                                           is_leaf=is_tup),
                    {"vr": jax.tree_util.tree_map(lambda t: t[1], out,
                                                  is_leaf=is_tup),
                     "vc": jax.tree_util.tree_map(lambda t: t[2], out,
                                                  is_leaf=is_tup)})

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            m2 = b1 * m.astype(jnp.float32) + (1 - b1) * g32
            v2 = b2 * v.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
            mhat = m2 / (1 - jnp.power(b1, i))
            vhat = v2 / (1 - jnp.power(b2, i))
            p2 = p.astype(jnp.float32) - lr * mhat / (jnp.sqrt(vhat)
                                                      + adam_eps)
            return (p2.astype(p.dtype), m2.astype(m.dtype),
                    v2.astype(v.dtype))

        out = jax.tree_util.tree_map(upd, params, grads, state["m"],
                                     state["v"])
        new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                       is_leaf=is_tup)
        new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                       is_leaf=is_tup)
        new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                       is_leaf=is_tup)
        return loss, new_p, {"m": new_m, "v": new_v}

    step = jax.jit(step, donate_argnums=(0, 1))

    ids = jax.device_put(np.random.randint(
        0, V_, size=(B, S)).astype(np.int32), dev)

    def fi(i):
        return jnp.asarray(i, jnp.float32)

    loss, params, state = step(params, state, ids, fi(1))
    float(loss)
    loss, params, state = step(params, state, ids, fi(2))
    float(loss)

    iters = 8
    t0 = time.perf_counter()
    for i in range(iters):
        loss, params, state = step(params, state, ids, fi(i + 3))
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = B * S * iters / dt
    flops_per_token = 6 * n_params
    attn_flops = 12 * L_ * H_ * S      # causal-pair accounting per token
    mfu = tokens_per_sec * (flops_per_token + attn_flops) / peak_flops_bf16()
    emit({
        "metric": METRICS[config_name],
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    })
    print(f"  loss={final_loss:.4f} mfu={mfu:.3f} "
          f"params={n_params/1e6:.1f}M step_time={dt/iters*1000:.1f}ms "
          f"B={B} S={S} fused_ce={fused} opt={opt}", file=sys.stderr)


def main_decode():
    """Serving decode metric (VERDICT r5 #7): static-KV-cache
    autoregressive decode through incubate fused_multi_transformer at
    GPT-2 345M shapes — prefill 512 then 128 decode steps, batch 8 and
    batch 1. The JSON value is batch-8 SCAN-decode tokens/s: the whole
    decode loop runs on device as one lax.scan program
    (inference/decode_loop.py) so host dispatch is paid once per
    sequence — the per-step-dispatch loop is also measured for
    comparison (over the axon relay it is dispatch-bound at ~8.6 ms per
    token). vs_baseline is the HBM-bandwidth utilization (decode is
    memory-bound: each step streams the 2-byte weights once), the
    roofline the reference's fused_multi_transformer_op.cu serving path
    also chases.
    """
    import jax
    import jax.numpy as jnp
    import paddle_tpu.incubate.nn.functional as IF

    import os
    L, D, H, FF = 24, 1024, 16, 4096
    T_PRE, T_MAX, steps = 512, 1024, 128
    dims = os.environ.get("PT_BENCH_DEC_DIMS")   # "L,D,H,FF,TPRE,TMAX,steps"
    if dims:
        L, D, H, FF, T_PRE, T_MAX, steps = (int(x) for x in dims.split(","))
    rng = np.random.default_rng(0)
    dt = jnp.bfloat16

    def mk(*s):
        return jnp.asarray(
            rng.standard_normal(s).astype("float32") * 0.02, dt)

    weights = dict(
        ln_scales=[jnp.ones((D,), dt) for _ in range(L)],
        ln_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        qkv_weights=[mk(D, 3 * D) for _ in range(L)],
        qkv_biases=[jnp.zeros((3 * D,), dt) for _ in range(L)],
        linear_weights=[mk(D, D) for _ in range(L)],
        linear_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        ffn_ln_scales=[jnp.ones((D,), dt) for _ in range(L)],
        ffn_ln_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        ffn1_weights=[mk(D, FF) for _ in range(L)],
        ffn1_biases=[jnp.zeros((FF,), dt) for _ in range(L)],
        ffn2_weights=[mk(FF, D) for _ in range(L)],
        ffn2_biases=[jnp.zeros((D,), dt) for _ in range(L)],
    )
    n_params = sum(int(np.prod(w.shape)) for ws in weights.values()
                   for w in ws)

    def step_fn(x, caches, t, ws):
        out, new_caches = IF.fused_multi_transformer(
            x, num_heads=H, trans_qkvw=False, cache_kvs=caches,
            time_step=t, **ws)
        return out, new_caches

    jit_step = jax.jit(step_fn, donate_argnums=(1,))

    # scan decode: the WHOLE loop on device as one program (the
    # TPU-native serving design — host dispatch once per sequence, not
    # once per token; inference/decode_loop.py)
    from paddle_tpu.inference import scan_decode

    def bound_step(x, caches, t):
        return step_fn(x, caches, t, weights)

    results = {}
    scan_results = {}
    for B in (8, 1):
        caches = [jnp.zeros((2, B, H, T_MAX, D // H), dt)
                  for _ in range(L)]
        x_pre = mk(B, T_PRE, D)
        x_dec = mk(B, 1, D)
        t0 = time.perf_counter()
        out, caches = jit_step(x_pre, caches, jnp.int32(0), weights)
        float(out.sum())
        prefill_s = time.perf_counter() - t0
        out, caches = jit_step(x_dec, caches, jnp.int32(T_PRE), weights)
        float(out.sum())
        t0 = time.perf_counter()
        for i in range(1, steps):
            out, caches = jit_step(x_dec, caches,
                                   jnp.int32(T_PRE + i), weights)
        float(out.sum())
        dt_dec = time.perf_counter() - t0
        results[B] = (B * (steps - 1) / dt_dec, prefill_s)

        # scan variant over fresh caches (donate=False: reuse below).
        # Warmup MUST use the same `steps` as the timed call — the scan
        # length is part of the compiled program.
        caches2 = [jnp.zeros((2, B, H, T_MAX, D // H), dt)
                   for _ in range(L)]
        _, caches2 = jit_step(x_pre, caches2, jnp.int32(0), weights)
        out, _ = scan_decode(bound_step, x_dec, caches2, T_PRE, steps,
                             donate=False)         # warmup/compile
        float(np.asarray(out).sum())
        t0 = time.perf_counter()
        out, _ = scan_decode(bound_step, x_dec, caches2, T_PRE, steps,
                             donate=False)
        float(np.asarray(out).sum())
        dt_scan = time.perf_counter() - t0
        scan_results[B] = B * steps / dt_scan

    toks8 = scan_results[8]
    # weights stream once per STEP (B tokens): steps/s x bytes / BW
    bw_util = (toks8 / 8) * 2.0 * n_params / peak_hbm_bw()
    emit({
        "metric": METRICS["decode"],
        "value": round(toks8, 1),
        "unit": "tokens/s",
        "vs_baseline": round(bw_util, 4),
    })
    print(f"  scan decode B=8: {toks8:,.0f} tok/s | B=1: "
          f"{scan_results[1]:,.0f} tok/s || per-step-dispatch B=8: "
          f"{results[8][0]:,.0f} tok/s (prefill+compile "
          f"{results[8][1]:.2f}s) | B=1: {results[1][0]:,.0f} tok/s "
          f"| params {n_params/1e6:.0f}M "
          f"| HBM util {bw_util:.2f}", file=sys.stderr)


def main_serve():
    """Continuous-batching server throughput (VERDICT r5 #7 follow-on):
    GPT-2 345M through inference.ContinuousBatchingServer — 16 requests
    (prompt 256, 128 new tokens each) over 8 slots, chunked prefill,
    tick_block=16 so each host dispatch runs 16 batched decode steps on
    device. Value = generated tokens/s; vs_baseline = HBM-bandwidth
    utilization of the decode phase (weights stream once per step for
    the whole slot batch).
    """
    import os
    import jax

    import paddle_tpu as pt
    from paddle_tpu.core.tensor import unwrap
    from paddle_tpu.inference import ContinuousBatchingServer
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt2_345m

    dims = os.environ.get("PT_BENCH_SERVE_DIMS")   # "H,L,NH,V" smoke
    slots = int(os.environ.get("PT_BENCH_SERVE_SLOTS", "8"))
    n_req = int(os.environ.get("PT_BENCH_SERVE_REQS", "16"))
    t_pre = int(os.environ.get("PT_BENCH_SERVE_PROMPT", "256"))
    t_new = int(os.environ.get("PT_BENCH_SERVE_NEW", "128"))
    tick = int(os.environ.get("PT_BENCH_SERVE_TICK", "16"))

    devices = _devices_with_retry()
    dev = devices[0]
    cpu = _cpu_device_or_none()
    import contextlib
    with (jax.default_device(cpu) if cpu is not None
          else contextlib.nullcontext()):
        if dims:
            H, L, NH, V = (int(x) for x in dims.split(","))
            cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L,
                            num_heads=NH, max_seq_len=t_pre + t_new)
        else:
            cfg = gpt2_345m(dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        model.astype("bfloat16")
    n_params = 0
    for _, prm in model.named_parameters():
        v = unwrap(prm)
        n_params += int(np.prod(v.shape))
        prm._replace_value(jax.device_put(v, dev))
    for _, buf in model.named_buffers():
        buf._replace_value(jax.device_put(unwrap(buf), dev))

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (t_pre,)).astype(np.int32)
               for _ in range(n_req)]
    max_cache = min(cfg.max_seq_len, t_pre + t_new)

    srv = ContinuousBatchingServer(
        model, max_slots=slots, max_cache_len=max_cache,
        prefill_chunk=t_pre, tick_block=tick)

    def run_batch():
        for p in prompts:
            srv.submit(p, max_new_tokens=t_new)
        t0 = time.perf_counter()
        outs = srv.run()
        dt = time.perf_counter() - t0
        total = sum(len(v) for v in outs.values())
        return total, dt

    run_batch()                    # warmup/compile (same server: the
    total, dt = run_batch()        # timed run reuses every program)
    toks = total / dt
    bw_util = (toks / slots) * 2.0 * n_params / peak_hbm_bw()
    emit({
        "metric": METRICS["serve"],
        "value": round(toks, 1),
        "unit": "tokens/s",
        "vs_baseline": round(bw_util, 4),
    })
    print(f"  serve: {n_req} reqs x {t_new} new @ prompt {t_pre}, "
          f"{slots} slots, tick_block={tick}: {toks:,.0f} tok/s "
          f"({dt:.2f}s) | params {n_params/1e6:.0f}M | HBM util "
          f"{bw_util:.2f}", file=sys.stderr)


def main(config_name="gpt2"):
    import os
    if os.environ.get("PT_BENCH_FORCE_CPU"):
        # CPU smoke path (numbers meaningless): the env's sitecustomize
        # force-registers the TPU relay platform for every process, so a
        # plain JAX_PLATFORMS=cpu env var is overridden — only the
        # post-import config update opts out (same trick as
        # tests/conftest.py). Skips the relay probe.
        import jax
        jax.config.update("jax_platforms", "cpu")
    # probe FIRST, in a subprocess: when the relay wedges, even
    # jax.devices() in this process can hang with no exception to catch
    elif not _probe_device_responsive():
        # emit a parseable failure line (under the REAL metric name so
        # the driver's records line up) rather than hanging
        metric = METRICS.get(
            config_name, f"{config_name}_train_tokens_per_sec_per_chip")
        print(json.dumps({
            "metric": metric,
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0,
        }))
        print("DEVICE UNRESPONSIVE: accelerator ops hang (relay outage) "
              "— no measurement possible this run", file=sys.stderr)
        prev = last_measurement(metric)
        if prev:
            print(f"  last real measurement of {metric}: "
                  f"{prev['value']} {prev['unit']} (vs_baseline "
                  f"{prev['vs_baseline']}) at {prev['ts']} — see "
                  f"BENCHLOG.jsonl", file=sys.stderr)
        return

    if config_name in ("llama1b3", "llama2b7"):
        return main_llama1b3(config_name)
    if config_name == "decode":
        return main_decode()
    if config_name == "serve":
        return main_serve()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit import functional_call

    devices = _devices_with_retry()

    # Build params on the CPU backend: on remote-execution TPU setups each
    # device-side init op would pay a separate remote compile.
    cpu = _cpu_device_or_none()
    import contextlib
    with (jax.default_device(cpu) if cpu is not None
          else contextlib.nullcontext()):
        model, cfg, metric, batch, seq = _build_model(config_name)
        model.astype("bfloat16")
        model.eval()  # dropout off; still training math
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        init_fn, update_fn = opt.functional()
        params = model.raw_params()
        n_params = sum(int(np.prod(v.shape)) for v in params.values())
        state = init_fn(params)
        # master fp32 moments for stability (cheap on HBM at 345M)
        state = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), state)
    dev = devices[0]
    params = jax.device_put(params, dev)
    state = jax.device_put(state, dev)

    def loss_fn(logits, labels):
        lg = logits[:, :-1]
        lb = labels[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

    is_moe = config_name == "moe"
    # fused chunked linear+CE (ops/fused_ce.py): avoids materializing the
    # [B,S,V] fp32 logits; enabled for the dense LM configs
    import os as _os
    # default off until A/B-measured on the real chip (flip after
    # benchmarks/fused_ce_bench.py shows a win)
    fused_ce = (config_name in ("gpt2", "llama350m")
                and _os.environ.get("PT_BENCH_FUSED_CE", "0") != "0")

    def step(params, state, ids, i):
        def compute(ps):
            if fused_ce:
                from paddle_tpu.ops.fused_ce import (
                    fused_linear_cross_entropy)
                hidden = functional_call(model, ps, ids, return_hidden=True)
                w = (ps["lm_head_weight"].T if config_name == "gpt2"
                     else ps["lm_head.weight"])
                return fused_linear_cross_entropy(
                    hidden[:, :-1], w, ids[:, 1:], chunk_size=2046)
            logits = functional_call(model, ps, ids)
            l = loss_fn(logits, ids)
            if is_moe:
                from paddle_tpu.core.tensor import unwrap
                aux = model.collect_aux_loss()
                if aux is not None:
                    l = l + cfg.aux_loss_coef * unwrap(aux)
            return l

        loss, grads = jax.value_and_grad(compute)(params)
        new_p, new_s = update_fn(grads, params, state, step=i)
        return loss, new_p, new_s

    step = jax.jit(step, donate_argnums=(0, 1))

    ids = np.random.randint(0, cfg.vocab_size, size=(batch, seq)).astype(
        np.int32)
    ids = jax.device_put(ids, dev)

    # warmup / compile (float() forces a host fetch — robust under the
    # remote-execution relay where block_until_ready alone is unreliable)
    loss, params, state = step(params, state, ids, 1)
    float(loss)
    loss, params, state = step(params, state, ids, 2)
    float(loss)

    # PT_BENCH_TRACE=<dir>: capture a jax.profiler trace of the steady
    # state (VERDICT r5 #8 — profiler-verified step: inspect for host
    # syncs / gaps between device kernels in the timed window)
    import contextlib
    trace_dir = _os.environ.get("PT_BENCH_TRACE")
    trace_cm = (jax.profiler.trace(trace_dir) if trace_dir
                else contextlib.nullcontext())

    iters = 10
    with trace_cm:
        t0 = time.perf_counter()
        for i in range(iters):
            loss, params, state = step(params, state, ids, i + 3)
        final_loss = float(loss)
        dt = time.perf_counter() - t0
    if trace_dir:
        print(f"  profiler trace written to {trace_dir}", file=sys.stderr)

    tokens_per_sec = batch * seq * iters / dt
    n_active = n_params
    if is_moe:
        # MoE MFU counts ACTIVE params per token (top_k of num_experts);
        # capacity padding/drops are overhead, not useful FLOPs.
        exp = sum(int(np.prod(v.shape)) for k, v in params.items()
                  if ".experts." in k)
        n_active = n_params - exp + exp * cfg.top_k / cfg.num_experts
    flops_per_token = 6 * n_active
    # causal attention flops: 12 * L * S^2 * H per token pair accounting
    attn_flops = 12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tokens_per_sec * (flops_per_token + attn_flops) / peak_flops_bf16()

    emit({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    })
    print(f"  loss={final_loss:.4f} mfu={mfu:.3f} "
          f"params={n_params/1e6:.1f}M step_time={dt/iters*1000:.1f}ms",
          file=sys.stderr)


if __name__ == "__main__":
    _argv = sys.argv[1:]
    _cfg = "gpt2"
    for _name in ("llama350m", "moe", "llama1b3", "llama2b7", "decode",
                  "serve"):
        if f"--config={_name}" in _argv or _name in _argv:
            _cfg = _name
    main(_cfg)
