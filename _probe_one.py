import sys, time, numpy as np, jax, jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu.jit import functional_call
from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_345m

kind, batch = sys.argv[1], int(sys.argv[2])
cpu = jax.local_devices(backend="cpu")[0]
t0 = time.time()
with jax.default_device(cpu):
    cfg = gpt2_345m(dropout=0.0)
    model = GPTForCausalLM(cfg); model.astype("bfloat16"); model.eval()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    init_fn, update_fn = opt.functional()
    params = model.raw_params()
    state = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), init_fn(params))
print("init", round(time.time()-t0, 1), flush=True)
dev = jax.devices()[0]
params = jax.device_put(params, dev); state = jax.device_put(state, dev)
n_params = sum(int(np.prod(v.shape)) for v in params.values())

def loss_softmax(logits, labels):
    lg = logits[:, :-1]; lb = labels[:, 1:]
    logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
    return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

def loss_lse(logits, labels):
    lg = logits[:, :-1]; lb = labels[:, 1:]
    tgt = jnp.take_along_axis(lg, lb[..., None], -1)[..., 0].astype(jnp.float32)
    lse = jax.nn.logsumexp(lg.astype(jnp.float32), axis=-1)
    return (lse - tgt).mean()

loss_fn = {"softmax": loss_softmax, "lse": loss_lse}[kind]

def step(params, state, ids, i):
    def compute(ps):
        return loss_fn(functional_call(model, ps, ids), ids)
    loss, grads = jax.value_and_grad(compute)(params)
    new_p, new_s = update_fn(grads, params, state, step=i)
    return loss, new_p, new_s
step = jax.jit(step, donate_argnums=(0, 1))
ids = jax.device_put(np.random.randint(0, cfg.vocab_size, size=(batch, 1024)).astype(np.int32), dev)
t0 = time.time()
loss, params, state = step(params, state, ids, 1); float(loss)
print("compile+first", round(time.time()-t0, 1), flush=True)
t0 = time.perf_counter(); iters = 6
for i in range(iters):
    loss, params, state = step(params, state, ids, i+2)
fl = float(loss); dt = (time.perf_counter()-t0)/iters
tok = batch*1024/dt
print(f"RESULT {kind} b{batch}: {dt*1000:.1f} ms/step {tok:,.0f} tok/s mfu={tok*6*n_params/197e12:.3f}", flush=True)
