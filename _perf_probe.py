import time, numpy as np, jax, jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu.jit import functional_call
from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_345m

cpu = jax.local_devices(backend="cpu")[0]
with jax.default_device(cpu):
    cfg = gpt2_345m(dropout=0.0)
    model = GPTForCausalLM(cfg); model.astype("bfloat16"); model.eval()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    init_fn, update_fn = opt.functional()
    params0 = model.raw_params()
    state0 = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), init_fn(params0))
dev = jax.devices()[0]
n_params = sum(int(np.prod(v.shape)) for v in params0.values())
print("init done", flush=True)

def loss_softmax(logits, labels):
    lg = logits[:, :-1]; lb = labels[:, 1:]
    logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
    return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

def loss_lse(logits, labels):
    lg = logits[:, :-1]; lb = labels[:, 1:]
    tgt = jnp.take_along_axis(lg, lb[..., None], -1)[..., 0].astype(jnp.float32)
    lse = jax.nn.logsumexp(lg.astype(jnp.float32), axis=-1)
    return (lse - tgt).mean()

def bench(loss_fn, batch, tag, iters=6):
    params = jax.device_put(params0, dev)
    state = jax.device_put(state0, dev)
    def step(params, state, ids, i):
        def compute(ps):
            return loss_fn(functional_call(model, ps, ids), ids)
        loss, grads = jax.value_and_grad(compute)(params)
        new_p, new_s = update_fn(grads, params, state, step=i)
        return loss, new_p, new_s
    step = jax.jit(step, donate_argnums=(0, 1))
    ids = jax.device_put(np.random.randint(0, cfg.vocab_size, size=(batch, 1024)).astype(np.int32), dev)
    loss, params, state = step(params, state, ids, 1); float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        loss, params, state = step(params, state, ids, i+2)
    fl = float(loss); dt = (time.perf_counter()-t0)/iters
    tok = batch*1024/dt
    print(f"{tag}: {dt*1000:.1f} ms/step, {tok:,.0f} tok/s, mfu={tok*6*n_params/197e12:.3f}", flush=True)

import sys
which = sys.argv[1]
if which == "a":
    bench(loss_softmax, 8, "b8-softmax")
    bench(loss_lse, 8, "b8-lse")
else:
    bench(loss_lse, 16, "b16-lse")
    bench(loss_lse, 32, "b32-lse")
