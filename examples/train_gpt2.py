"""Causal-LM pretraining: one jitted XLA step (fwd+bwd+AdamW), LR warmup,
checkpoint save/restore. Scale `CFG` up on real hardware."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.jit import train_step_fn
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

CFG = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, dropout=0.0)
BATCH, SEQ, STEPS = 8, 32, 20


def main():
    pt.seed(0)
    model = GPTForCausalLM(CFG)
    sched = pt.optimizer.lr.LinearWarmup(
        pt.optimizer.lr.CosineAnnealingDecay(3e-3, STEPS), 5, 0.0, 3e-3)
    opt = pt.optimizer.AdamW(learning_rate=sched,
                             parameters=model.parameters())

    def _ce(logits, labels):
        import jax
        import jax.numpy as jnp
        lg = logits[:, :-1]
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, labels[:, 1:, None], -1).mean()

    step = train_step_fn(model, _ce, opt)
    params = model.raw_params()
    state = opt.functional()[0](params)

    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        ids = rng.randint(0, CFG.vocab_size, (BATCH, SEQ)).astype(np.int32)
        loss, params, state = step(params, state,
                                   {"inputs": (ids,), "labels": (ids,)},
                                   i + 1)
        sched.step()
        v = float(loss)
        first = v if first is None else first
        last = v
        if i % 5 == 0:
            print(f"step {i:3d} loss {v:.4f} lr {sched.get_lr():.2e}")

    model.load_raw_params(params) if hasattr(model, "load_raw_params") else \
        _write_back(model, params)
    pt.save(model.state_dict(), "/tmp/gpt2_example.pdparams")
    model.set_state_dict(pt.load("/tmp/gpt2_example.pdparams"))
    print(f"done: loss {first:.3f} -> {last:.3f} (checkpoint round-trip ok)")
    assert last < first


def _write_back(model, params):
    named = dict(model.named_parameters())
    for k, v in params.items():
        named[k]._replace_value(v)


if __name__ == "__main__":
    main()
