"""Deploy path: jit.save -> StableHLO archive -> inference.Predictor."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.static import InputSpec


def main():
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(16, 32), pt.nn.GELU(),
                           pt.nn.Linear(32, 4))
    net.eval()
    spec = [InputSpec(shape=[None, 16], dtype="float32", name="x")]
    pt.jit.save(net, "/tmp/served_model", input_spec=spec)

    from paddle_tpu.inference import Config, create_predictor
    cfg = Config("/tmp/served_model")
    pred = create_predictor(cfg)
    x = np.random.randn(3, 16).astype("float32")
    names = pred.get_input_names()
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    ref = net(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    print("predictor output matches eager:", out.shape)


if __name__ == "__main__":
    main()
