"""BERT sequence classification fine-tune (eager loop, tiny config)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.bert import BertConfig, BertForSequenceClassification

STEPS = 10


def main():
    pt.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64, max_position_embeddings=32, dropout=0.0)
    model = BertForSequenceClassification(cfg, num_classes=2)
    opt = pt.optimizer.AdamW(learning_rate=3e-3,
                             parameters=model.parameters())
    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        ids = rng.randint(0, 128, (8, 16)).astype(np.int64)
        labels = (ids[:, 0] > 64).astype(np.int64)
        logits = model(pt.to_tensor(ids))
        loss = pt.nn.functional.cross_entropy(logits, pt.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = v if first is None else first
        last = v
    print(f"bert ft loss {first:.3f} -> {last:.3f}")
    assert last < first


if __name__ == "__main__":
    main()
