"""Mixtral-style MoE pretraining: top-2 of 8 SwiGLU experts, GShard
grouped dispatch, load-balance aux loss folded into the objective."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.tensor import unwrap
from paddle_tpu.jit import functional_call
from paddle_tpu.models.mixtral import MixtralForCausalLM, mixtral_tiny

BATCH, SEQ, STEPS = 4, 64, 12


def main():
    pt.seed(0)
    cfg = mixtral_tiny(num_experts=4, top_k=2)
    model = MixtralForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=2e-3,
                             parameters=model.parameters())
    init_fn, update_fn = opt.functional()
    params = model.raw_params()
    state = init_fn(params)

    def loss_of(ps, ids):
        logits = functional_call(model, ps, ids)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(lp, ids[:, 1:, None], -1).mean()
        aux = model.collect_aux_loss()
        return ce + cfg.aux_loss_coef * unwrap(aux)

    @jax.jit
    def step(params, state, ids, i):
        loss, grads = jax.value_and_grad(loss_of)(params, ids)
        new_p, new_s = update_fn(grads, params, state, step=i)
        return loss, new_p, new_s

    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        ids = rng.randint(0, cfg.vocab_size, (BATCH, SEQ)).astype(np.int32)
        loss, params, state = step(params, state, ids, i + 1)
        v = float(loss)
        first = v if first is None else first
        last = v
        if i % 3 == 0:
            print(f"step {i:3d} loss+aux {v:.4f}")
    print(f"done: {first:.3f} -> {last:.3f}")
    assert last < first


if __name__ == "__main__":
    main()
