"""The flagship composition: TP x PP x ZeRO (x DP/SP/EP) in ONE jitted
program — train a llama-style model with tensor-parallel blocks inside a
1F1B pipeline, ZeRO-1 optimizer-state sharding, and (optionally) tied
embeddings, ring-attention context parallelism or MoE experts.

Runs on the 8-device virtual CPU mesh in ~a minute:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        PYTHONPATH=. python examples/train_hybrid.py

On a real slice, raise the shape constants and mesh degrees; the same
program scales (see benchmarks/compile_hybrid.py for Llama-7B/70B,
Mixtral-8x7B and 7B@32k-sequence compile checks).
"""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                        init_llama_tp_params,
                                        make_llama_tp_fns)

LAYERS, HIDDEN, FFN, VOCAB, HEADS = 4, 32, 64, 128, 4
BATCH, SEQ, MICRO, STEPS = 8, 16, 2, 10


def main():
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)   # 8 devices
    fns, specs = make_llama_tp_fns(
        n_heads=HEADS, mp_degree=2, rope_theta=10000.0, use_flash=True)
    blocks, embed, head = init_llama_tp_params(
        LAYERS, HIDDEN, FFN, VOCAB, rng=np.random.RandomState(0),
        n_heads=HEADS)
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    step, params, opt_state, (p_sh, s_sh) = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=MICRO,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1)
    print(f"mesh: {dict(mesh.degrees)}; block wq sharding "
          f"{p_sh['blocks']['wq'].spec}; Adam m sharding "
          f"{s_sh['m']['blocks']['wq'].spec}")

    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)
    for i in range(1, STEPS + 1):
        loss, params, opt_state = step(params, opt_state, ids, ids, i)
        if i in (1, STEPS):
            print(f"step {i}: loss {float(loss):.4f}")
    print("hybrid tp2 x pp2 x zero1 training OK")


if __name__ == "__main__":
    main()
