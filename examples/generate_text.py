"""Text generation + continuous-batching serving on the decode loop.

Greedy and sampled `model.generate()`, then the slot-pool server: three
requests of different lengths share two decode slots, results identical
to solo runs. Runs in seconds on CPU; the same programs serve on TPU.
"""
import numpy as np

import paddle_tpu as pt


def main():
    from paddle_tpu.inference import ContinuousBatchingServer
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    rng = np.random.default_rng(0)

    prompt = rng.integers(0, 256, (1, 6)).astype(np.int32)
    greedy = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                            max_cache_len=64)
    print("greedy :", greedy.numpy()[0, 6:].tolist())

    sampled = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                             do_sample=True, top_p=0.9, temperature=1.2,
                             seed=7, max_cache_len=64)
    print("sampled:", sampled.numpy()[0, 6:].tolist())

    int8 = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                          weight_dtype="int8", max_cache_len=64)
    print("int8   :", int8.numpy()[0, 6:].tolist())

    srv = ContinuousBatchingServer(model, max_slots=2, max_cache_len=64)
    srv.register_prefix(prompt[0])            # shared system-prompt rows
    reqs = [rng.integers(0, 256, (n,)).astype(np.int32) for n in (4, 7)]
    # third request extends the registered prefix -> prefills only its tail
    reqs.append(np.concatenate([prompt[0],
                                rng.integers(0, 256, (3,)).astype(np.int32)]))
    rids = [srv.submit(r, max_new_tokens=8) for r in reqs]
    outs = srv.run()
    for rid in rids:
        print(f"server request {rid}:", outs[rid].tolist())
    print("continuous batching returned", len(outs), "results;",
          srv.stats)

    # speculative decoding: the model drafts for itself (gamma accepted
    # every round); a smaller model would draft in practice
    from paddle_tpu.inference import speculative_generate
    spec, stats = speculative_generate(model, model, pt.to_tensor(prompt),
                                       max_new_tokens=12, gamma=4,
                                       max_cache_len=64,
                                       return_stats=True)
    assert (spec.numpy() == greedy.numpy()).all()
    print(f"speculative == greedy in {stats['rounds']} target forwards "
          f"(mean accepted {stats['mean_accepted']:.1f})")

    # deployment: serialize prefill+decode, reload without model code
    import tempfile
    from paddle_tpu.inference import export_decode, load_decode
    with tempfile.TemporaryDirectory() as d:
        export_decode(f"{d}/gen", model, prompt_len=6, max_new_tokens=12,
                      batch=1, max_cache_len=64)
        deployed = load_decode(f"{d}/gen")
        out = deployed.generate(prompt)
        assert (out == greedy.numpy()).all()
        print("deployed archives reproduce generate():", out[0, 6:].tolist())


if __name__ == "__main__":
    main()
