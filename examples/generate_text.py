"""Text generation + continuous-batching serving on the decode loop.

Greedy and sampled `model.generate()`, then the slot-pool server: three
requests of different lengths share two decode slots, results identical
to solo runs. Runs in seconds on CPU; the same programs serve on TPU.
"""
import numpy as np

import paddle_tpu as pt


def main():
    from paddle_tpu.inference import ContinuousBatchingServer
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    rng = np.random.default_rng(0)

    prompt = rng.integers(0, 256, (1, 6)).astype(np.int32)
    greedy = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                            max_cache_len=64)
    print("greedy :", greedy.numpy()[0, 6:].tolist())

    sampled = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                             do_sample=True, top_p=0.9, temperature=1.2,
                             seed=7, max_cache_len=64)
    print("sampled:", sampled.numpy()[0, 6:].tolist())

    int8 = model.generate(pt.to_tensor(prompt), max_new_tokens=12,
                          weight_dtype="int8", max_cache_len=64)
    print("int8   :", int8.numpy()[0, 6:].tolist())

    srv = ContinuousBatchingServer(model, max_slots=2, max_cache_len=64)
    rids = [srv.submit(rng.integers(0, 256, (n,)).astype(np.int32),
                       max_new_tokens=8) for n in (4, 7, 5)]
    outs = srv.run()
    for rid in rids:
        print(f"server request {rid}:", outs[rid].tolist())
    # parity: request 0 re-run solo
    print("continuous batching returned", len(outs), "results")


if __name__ == "__main__":
    main()
