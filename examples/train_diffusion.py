"""DiT diffusion: epsilon-prediction training on toy data, then a short
DDPM ancestral-sampling loop with the trained net."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.dit import DiTForDiffusion, dit_tiny

STEPS = 15


def main():
    pt.seed(0)
    cfg = dit_tiny()
    model = DiTForDiffusion(cfg, num_train_timesteps=100)
    opt = pt.optimizer.AdamW(learning_rate=2e-3,
                             parameters=model.parameters())
    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        x0 = pt.to_tensor(rng.randn(8, 3, 8, 8).astype("float32") * 0.5)
        t = pt.to_tensor(rng.randint(0, 100, (8,)).astype("int32"))
        noise = pt.to_tensor(rng.randn(8, 3, 8, 8).astype("float32"))
        y = pt.to_tensor(rng.randint(0, cfg.num_classes, (8,)).astype("int32"))
        loss = model.loss(x0, t, noise, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = v if first is None else first
        last = v
        if i % 5 == 0:
            print(f"step {i:3d} mse {v:.4f}")
    assert last < first

    # a few DDPM sampling steps (x_t -> x_{t-1})
    import jax.numpy as jnp
    x = pt.to_tensor(rng.randn(2, 3, 8, 8).astype("float32"))
    ac = model.alphas_cumprod
    for t_i in (99, 66, 33, 0):
        t = pt.to_tensor(np.array([t_i, t_i], "int32"))
        eps = model(x, t)
        a_t = float(ac[t_i])
        a_prev = float(ac[t_i - 33]) if t_i > 0 else 1.0
        x0_pred = (x - pt.to_tensor(np.float32((1 - a_t) ** 0.5)) * eps) \
            / np.float32(a_t ** 0.5)
        x = pt.to_tensor(np.float32(a_prev ** 0.5)) * x0_pred + \
            pt.to_tensor(np.float32((1 - a_prev) ** 0.5)) * eps
    print("sampled", x.shape, "finite:", bool(np.isfinite(x.numpy()).all()))
    print(f"done: {first:.3f} -> {last:.3f}")


if __name__ == "__main__":
    main()
