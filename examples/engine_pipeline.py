"""auto_parallel Engine through the pipeline: fit + evaluate + predict.

The reference journey (auto_parallel/engine.py): wrap a model in Engine
with a Strategy, call fit/evaluate/predict and let the parallelizer do
the rest. Here strategy.pipeline routes to the 1F1B tick table,
strategy.amp float16 turns on DYNAMIC loss scaling, and
strategy.gradient_merge accumulates across steps — all inside ONE
jitted SPMD program per phase (train and a forward-only table for
evaluate/predict).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PYTHONPATH=. python examples/engine_pipeline.py
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
from paddle_tpu.parallel.auto_parallel import Engine, Strategy


def main():
    dist.init_mesh(dp=4, pp=2)
    pt.seed(0)
    cfg = gpt2_tiny(dropout=0.0)
    model = GPTForCausalLM(cfg)

    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2      # microbatches per step
    strat.amp.enable = True
    strat.amp.dtype = "float16"              # dynamic GradScaler
    strat.gradient_merge.enable = True
    strat.gradient_merge.k_steps = 2         # update every 2nd step

    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=3e-3, parameters=model.parameters()),
                 strategy=strat)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    data = [{"inputs": (ids,), "labels": (ids,)}] * 8

    eng.fit(data, epochs=2, verbose=0)
    first, last = eng.history["loss"][0], eng.history["loss"][-1]
    print(f"fit:      loss {first:.4f} -> {last:.4f} "
          f"(fp16 + merge through pp2)")
    assert last < first

    ev = eng.evaluate([{"inputs": (ids,), "labels": (ids,)}])
    print(f"evaluate: eval_loss {ev['eval_loss']:.4f} "
          f"(forward-only tick table)")

    preds = eng.predict([{"inputs": (ids,)}])
    print(f"predict:  logits {preds[0].shape} via the pipeline head")
    assert preds[0].shape == (8, 16, cfg.vocab_size)


if __name__ == "__main__":
    main()
