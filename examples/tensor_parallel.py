"""Megatron-style tensor parallelism: Column/RowParallelLinear over the
'mp' mesh axis; GSPMD inserts the collectives."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.mp_layers import (ColumnParallelLinear,
                                           RowParallelLinear)

STEPS = 10


class MpMLP(pt.nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = ColumnParallelLinear(32, 128, gather_output=False)
        self.act = pt.nn.GELU()
        self.down = RowParallelLinear(128, 10, input_is_parallel=True)

    def forward(self, x):
        return self.down(self.act(self.up(x)))


def main():
    mesh = dist.init_mesh(dp=2, mp=4)
    pt.seed(0)
    net = MpMLP()
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=net.parameters())

    def loss_fn(logits, labels):
        import jax
        import jax.numpy as jnp
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, labels[:, None], -1).mean()

    step, params, state, _ = dist.parallel_train_step(net, loss_fn, opt,
                                                      mesh)
    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        x = rng.randn(16, 32).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int32) * 9
        loss, params, state = step(params, state,
                                   {"inputs": (x,), "labels": (y,)},
                                   i + 1, None)
        v = float(loss)
        first = v if first is None else first
        last = v
    print(f"dp=2 mp=4 loss {first:.3f} -> {last:.3f}")
    assert last < first


if __name__ == "__main__":
    main()
