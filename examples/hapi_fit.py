"""High-level hapi training: paddle.Model.fit with callbacks."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.io import DataLoader, TensorDataset


def main():
    pt.seed(0)
    np.random.seed(0)
    y = (np.random.rand(128) > 0.5).astype(np.int64)
    # class-conditional mean shift: a clearly separable toy task
    x = (np.random.randn(128, 1, 16, 16)
         + y[:, None, None, None] * 1.5).astype("float32")
    ds = TensorDataset([pt.to_tensor(x), pt.to_tensor(y)])
    loader = DataLoader(ds, batch_size=16, shuffle=True)

    net = pt.nn.Sequential(
        pt.nn.Conv2D(1, 8, 3, padding=1), pt.nn.ReLU(),
        pt.nn.AdaptiveAvgPool2D(1), pt.nn.Flatten(),
        pt.nn.Linear(8, 2))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters()),
        loss=pt.nn.CrossEntropyLoss(),
        metrics=pt.metric.Accuracy())
    model.fit(loader, epochs=3, verbose=1)
    res = model.evaluate(loader, verbose=0)
    print("eval:", res)
    assert res["acc"] > 0.8


if __name__ == "__main__":
    main()
