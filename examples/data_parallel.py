"""GSPMD data parallelism: one jitted step sharded over the mesh's 'dp'
axis — the TPU-native equivalent of paddle.DataParallel + launch."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.parallel as dist

STEPS = 10


def main():
    mesh = dist.init_mesh(dp=8)      # 8 virtual CPU devices; v5e-8 as-is
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(32, 64), pt.nn.GELU(),
                           pt.nn.Linear(64, 10))
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=net.parameters())

    def loss_fn(logits, labels):
        import jax
        import jax.numpy as jnp
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, labels[:, None], -1).mean()

    step, params, state, _ = dist.parallel_train_step(net, loss_fn, opt,
                                                      mesh)
    rng = np.random.RandomState(0)
    first = last = None
    for i in range(STEPS):
        x = rng.randn(64, 32).astype(np.float32)      # global batch
        y = (x[:, 0] > 0).astype(np.int32) * 9
        loss, params, state = step(params, state,
                                   {"inputs": (x,), "labels": (y,)},
                                   i + 1, None)
        v = float(loss)
        first = v if first is None else first
        last = v
    print(f"dp=8 loss {first:.3f} -> {last:.3f}")
    assert last < first


if __name__ == "__main__":
    main()
