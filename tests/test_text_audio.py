"""text (viterbi, datasets) + audio (mel/stft features) tests."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt


# ---------------------------------------------------------------- viterbi
def _viterbi_oracle(pot, trans, lengths, tag):
    """Brute-force per-batch oracle."""
    B, L, T = pot.shape
    scores, paths = [], []
    maxlen = lengths.max()
    for b in range(B):
        n = lengths[b]
        best, best_path = -np.inf, None
        import itertools
        for comb in itertools.product(range(T), repeat=int(n)):
            s = pot[b, 0, comb[0]]
            if tag:
                s += trans[-1, comb[0]]
            for i in range(1, n):
                s += trans[comb[i - 1], comb[i]] + pot[b, i, comb[i]]
            if tag:
                # reference kernel adds the stop ROW (viterbi_decode_kernel.cc
                # splits transitions along rows: stop = trans[-2, :])
                s += trans[-2, comb[n - 1]]
            if s > best:
                best, best_path = s, comb
        scores.append(best)
        paths.append(list(best_path) + [0] * (maxlen - n))
    return np.asarray(scores, np.float32), np.asarray(paths)


@pytest.mark.parametrize("tag", [False, True])
def test_viterbi_decode_matches_bruteforce(tag):
    from paddle_tpu.text import viterbi_decode
    rng = np.random.RandomState(0)
    B, L, T = 3, 4, 4
    pot = rng.randn(B, L, T).astype(np.float32)
    trans = rng.randn(T, T).astype(np.float32)
    lengths = np.array([4, 2, 3])
    scores, path = viterbi_decode(pt.to_tensor(pot), pt.to_tensor(trans),
                                  pt.to_tensor(lengths),
                                  include_bos_eos_tag=tag)
    ref_s, ref_p = _viterbi_oracle(pot, trans, lengths, tag)
    np.testing.assert_allclose(scores.numpy(), ref_s, rtol=1e-5)
    np.testing.assert_array_equal(path.numpy(), ref_p)


def test_viterbi_decoder_layer():
    from paddle_tpu.text import ViterbiDecoder
    rng = np.random.RandomState(1)
    pot = rng.randn(2, 3, 3).astype(np.float32)
    trans = rng.randn(3, 3).astype(np.float32)
    dec = ViterbiDecoder(pt.to_tensor(trans), include_bos_eos_tag=False)
    scores, path = dec(pt.to_tensor(pot), pt.to_tensor(np.array([3, 3])))
    assert scores.shape == [2] and path.shape == [2, 3]


# ---------------------------------------------------------------- datasets
def test_uci_housing_dataset():
    from paddle_tpu.text import UCIHousing
    rng = np.random.RandomState(0)
    data = rng.rand(50, 14).astype(np.float32)
    with tempfile.NamedTemporaryFile("w", suffix=".data",
                                     delete=False) as f:
        np.savetxt(f, data)
        path = f.name
    tr = UCIHousing(data_file=path, mode="train")
    te = UCIHousing(data_file=path, mode="test")
    assert len(tr) == 40 and len(te) == 10
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
    os.unlink(path)


def test_imikolov_dataset():
    from paddle_tpu.text import Imikolov
    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        f.write("the cat sat on the mat\nthe dog sat on the log\n")
        path = f.name
    ds = Imikolov(data_file=path, window_size=3, min_word_freq=1)
    assert len(ds) > 0
    ex = ds[0]
    assert len(ex) == 3  # 3-gram
    os.unlink(path)


def test_wmt_dataset():
    from paddle_tpu.text import WMT14
    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        f.write("hello world\tbonjour monde\ngood day\tbonne journee\n")
        path = f.name
    ds = WMT14(data_file=path)
    assert len(ds) == 2
    src, trg, lbl = ds[0]
    assert trg[0] == ds.trg_ids["<s>"] and lbl[-1] == ds.trg_ids["<e>"]
    os.unlink(path)


def test_dataset_missing_file_raises():
    from paddle_tpu.text import Imdb
    with pytest.raises(RuntimeError, match="no network access"):
        Imdb(data_file="/nonexistent/imdb.tar.gz")


# ---------------------------------------------------------------- audio
def test_mel_scale_roundtrip():
    from paddle_tpu.audio import functional as AF
    for htk in (False, True):
        hz = 440.0
        mel = AF.hz_to_mel(hz, htk=htk)
        back = AF.mel_to_hz(mel, htk=htk)
        assert abs(back - hz) < 1e-2
    # slaney reference values (librosa convention)
    assert abs(AF.hz_to_mel(1000.0) - 15.0) < 1e-4


def test_fft_frequencies():
    from paddle_tpu.audio import functional as AF
    f = AF.fft_frequencies(16000, 512).numpy()
    assert f.shape == (257,)
    assert f[0] == 0 and abs(f[-1] - 8000) < 1e-3


def test_fbank_matrix_shape_and_norm():
    from paddle_tpu.audio import functional as AF
    fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    assert (fb.sum(axis=1) > 0).all()  # every filter non-empty


def test_power_to_db():
    from paddle_tpu.audio import functional as AF
    s = np.array([1.0, 0.1, 1e-12], np.float32)
    db = AF.power_to_db(pt.to_tensor(s), top_db=None).numpy()
    np.testing.assert_allclose(db[:2], [0.0, -10.0], atol=1e-4)
    # amin floor; f32 log10 rounds differently across XLA backends
    np.testing.assert_allclose(db[2], -100.0, atol=1e-4)
    db = AF.power_to_db(pt.to_tensor(s), top_db=5.0).numpy()
    assert db.min() >= db.max() - 5.0


def test_create_dct_ortho():
    from paddle_tpu.audio import functional as AF
    d = AF.create_dct(13, 40).numpy()
    assert d.shape == (40, 13)
    # ortho columns have unit norm
    np.testing.assert_allclose(np.linalg.norm(d, axis=0), np.ones(13),
                               rtol=1e-5)


@pytest.mark.parametrize("win", ["hann", "hamming", "blackman", "triang",
                                 "cosine", ("kaiser", 12.0),
                                 ("gaussian", 7.0), ("tukey", 0.5)])
def test_get_window(win):
    from paddle_tpu.audio import functional as AF
    w = AF.get_window(win, 64).numpy()
    assert w.shape == (64,)
    assert w.max() <= 1.0 + 1e-6 and w.min() >= -1e-6


def test_spectrogram_parseval():
    from paddle_tpu.audio.features import Spectrogram
    rng = np.random.RandomState(0)
    x = rng.randn(2, 2048).astype(np.float32)
    spec = Spectrogram(n_fft=256, hop_length=128)(pt.to_tensor(x))
    n_frames = 1 + 2048 // 128
    assert spec.shape == [2, 129, n_frames]
    # pure tone concentrates energy at its bin
    t = np.arange(2048) / 16000
    tone = np.sin(2 * np.pi * 1000 * t).astype(np.float32)
    s = Spectrogram(n_fft=256, hop_length=128)(
        pt.to_tensor(tone[None])).numpy()[0]
    peak_bin = s.mean(axis=1).argmax()
    expect_bin = round(1000 / (16000 / 256))
    assert abs(int(peak_bin) - expect_bin) <= 1


def test_mel_mfcc_pipeline():
    from paddle_tpu.audio.features import (LogMelSpectrogram, MelSpectrogram,
                                           MFCC)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(1, 4096).astype(np.float32))
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert mel.shape[1] == 40
    logmel = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert logmel.shape == mel.shape
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
    assert mfcc.shape[1] == 13
