"""Elastic scale-in + hybrid mesh-change restore, end to end
(VERDICT r4 #6). Reference: fleet/elastic/manager.py:469-604 (endpoint
rewrite + np adjustment + relaunch) composed with
auto_parallel/converter.py (mesh-change restore) — here the TCPStore
heartbeat manager, the endpoint registry, and the hybrid restack
helpers drive the same story on the virtual TPU mesh.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.parallel as dist

RESTART_RC = 31


@pytest.mark.slow
def test_elastic_scale_in_hybrid_restore(tmp_path):
    """2 nodes -> node 1 dies -> manager records the scale plan ->
    relaunch at np=1 -> hybrid ckpt (pp2) restores onto pp4 with Adam
    moments -> losses continue the uninterrupted trajectory exactly."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "elastic_scale_worker.py")
    ckdir = str(tmp_path / "ckpts")
    os.makedirs(ckdir)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    # per-run ports: a fixed pair collides when suites overlap
    base = 20000 + (os.getpid() % 20000)
    env.update({"CKPT_DIR": ckdir, "TOTAL_STEPS": "5",
                "CRASH_RANK": "1", "CRASH_STEP": "2",
                "ELASTIC_MASTER": f"127.0.0.1:{base}",
                "RESUME_MASTER": f"127.0.0.1:{base + 1}",
                "PYTHONUNBUFFERED": "1"})

    def launch(nproc, phase):
        e = dict(env)
        e["PHASE"] = phase
        cmd = [sys.executable, "-m", "paddle_tpu.parallel.launch.main",
               "--nproc_per_node", str(nproc),
               "--log_dir", str(tmp_path / f"log_{phase}"),
               "--max_restart", "0",
               worker]
        # own process group: a timeout must take the WORKERS down too,
        # or zombies hold the store ports/CPU and poison later runs
        proc = subprocess.Popen(cmd, env=e, cwd=repo,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
        try:
            out, err = proc.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            import signal
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            raise
        return subprocess.CompletedProcess(cmd, proc.returncode, out,
                                           err)

    r1 = launch(2, "train")
    assert r1.returncode != 0, (r1.stdout[-1500:], r1.stderr[-1500:])
    # the manager detected the loss and recorded the scale plan
    plan_path = os.path.join(ckdir, "PLAN.json")
    if not os.path.exists(plan_path):
        logs = ""
        for w in (0, 1):
            lp = os.path.join(str(tmp_path / "log_train"),
                              f"workerlog.{w}")
            if os.path.exists(lp):
                logs += f"\n--- workerlog.{w} ---\n" + \
                    open(lp).read()[-1500:]
        raise AssertionError(
            "node 0 never recorded the scale plan (it likely died "
            "before detection — resource pressure?):" + logs)
    plan = json.load(open(plan_path))
    assert plan["np"] == 1 and plan["endpoints"] == ["127.0.0.1:9400"]
    saved = int(open(os.path.join(ckdir, "LATEST")).read())
    assert saved >= 1

    r2 = launch(1, "resume")
    assert r2.returncode == 0, (
        r2.stdout[-1500:], r2.stderr[-1500:],
        open(os.path.join(str(tmp_path / "log_resume"),
                          "workerlog.0")).read()[-2000:])
    res = json.load(open(os.path.join(ckdir, "result.json")))
    assert res["resumed_from"] == saved

    # ---- uninterrupted single-process reference trajectory ----------
    from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                            init_llama_tp_params,
                                            make_llama_tp_fns)
    NH, L, H, F, V = 4, 4, 16, 32, 64
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(77))
    fns, specs = make_llama_tp_fns(NH, 2)
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    step_fn, params, opt_state, _sh = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh,
        pt.optimizer.AdamW(learning_rate=1e-2), num_micro=2,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1, donate=False)

    def ids(i):
        return jnp.asarray(np.random.RandomState(1000 + i)
                           .randint(0, V, size=(8, 8)).astype(np.int32))

    ref = []
    for i in range(1, 6):
        loss, params, opt_state = step_fn(params, opt_state, ids(i),
                                          ids(i), i)
        ref.append(float(loss))

    got = res["train_losses"] + res["losses"]
    np.testing.assert_allclose(got, ref, rtol=1e-4, err_msg=(
        "resumed trajectory diverged from the uninterrupted run"))
    assert ref[-1] < ref[0]
