"""PTQ observer zoo (reference observers/{abs_max,ema,avg,hist,kl,mse})."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.quantization import (AbsmaxObserver, AVGObserver,
                                     EMAObserver, HistObserver, KLObserver,
                                     MSEObserver)


def _feed(layer_cls_factory, batches):
    obs = layer_cls_factory._layer_cls(None)
    for b in batches:
        obs(pt.to_tensor(b))
    return obs


RNG = np.random.RandomState(0)
GAUSS = [RNG.randn(512).astype(np.float32) for _ in range(8)]


def test_absmax_tracks_running_max():
    obs = _feed(AbsmaxObserver, [np.array([1.0, -3.0], np.float32),
                                 np.array([2.0], np.float32)])
    assert float(obs.scales().numpy()) == 3.0


def test_ema_smooths():
    obs = EMAObserver._layer_cls(None, moving_rate=0.5)
    obs(pt.to_tensor(np.array([4.0], np.float32)))
    obs(pt.to_tensor(np.array([2.0], np.float32)))
    assert abs(float(obs.scales().numpy()) - 3.0) < 1e-6


def test_avg_means_batch_maxima():
    obs = _feed(AVGObserver, [np.array([4.0], np.float32),
                              np.array([2.0], np.float32)])
    assert abs(float(obs.scales().numpy()) - 3.0) < 1e-6


def test_hist_percentile_clips_outlier():
    data = list(GAUSS) + [np.array([100.0], np.float32)]  # one outlier
    obs = _feed(HistObserver, data)
    obs.cal_thresholds()
    s = float(obs.scales().numpy())
    # the 99.9th percentile threshold must clip far below the outlier
    assert s < 50.0
    assert s > 1.0


def test_kl_threshold_reasonable():
    obs = _feed(KLObserver, GAUSS)
    obs.cal_thresholds()
    s = float(obs.scales().numpy())
    mx = max(float(np.abs(g).max()) for g in GAUSS)
    assert 0.5 < s <= mx + 1e-6


def test_mse_threshold_below_max_for_heavy_tail():
    data = list(GAUSS) + [np.array([30.0], np.float32)]
    obs = _feed(MSEObserver, data)
    obs.cal_thresholds()
    s = float(obs.scales().numpy())
    assert s < 30.0  # clipping the single outlier wins on MSE


def test_hist_scale_invalidated_by_new_data():
    # review regression: observing after a scales() read must recompute
    obs = HistObserver._layer_cls(None)
    obs(pt.to_tensor(np.ones(64, np.float32)))
    s1 = float(obs.scales().numpy())
    obs(pt.to_tensor(np.full(512, 50.0, np.float32)))
    s2 = float(obs.scales().numpy())
    assert s2 > s1 * 5


def test_observer_in_ptq_flow():
    from paddle_tpu.quantization import PTQ, QuantConfig
    net = pt.nn.Sequential(pt.nn.Linear(8, 8))
    cfg = QuantConfig(activation=HistObserver(), weight=AbsmaxObserver())
    ptq = PTQ(cfg)
    qmodel = ptq.quantize(net)
    for _ in range(4):   # calibration batches
        qmodel(pt.to_tensor(RNG.randn(4, 8).astype(np.float32)))
    frozen = ptq.convert(qmodel)
    out = frozen(pt.to_tensor(RNG.randn(4, 8).astype(np.float32)))
    assert np.isfinite(out.numpy()).all()
