"""Unit tests for paddle_tpu.reliability: retry backoff, circuit
breaker, health state machine, and the deterministic fault injector —
all on fake clocks / seeded RNGs (no sleeps, no wall-time flake)."""
import pytest

from paddle_tpu.reliability import (CallbackError, CircuitBreaker,
                                    DEAD, DEGRADED, DRAINING,
                                    FaultInjector, HEALTHY,
                                    HealthMonitor, InjectedFault,
                                    ReliabilityError, RetryPolicy,
                                    ServeSupervisor, faults)
from paddle_tpu.telemetry import FakeClock


# ------------------------------------------------------------- retry

class TestRetryPolicy:
    def test_exponential_growth_and_cap(self):
        p = RetryPolicy(base_delay_s=0.01, multiplier=2.0,
                        max_delay_s=0.05, jitter=0.0)
        assert [p.delay(a) for a in range(5)] == \
            pytest.approx([0.01, 0.02, 0.04, 0.05, 0.05])

    def test_jitter_bounded_and_seeded(self):
        a = RetryPolicy(base_delay_s=1.0, multiplier=1.0, max_delay_s=1.0,
                        jitter=0.25, seed=5)
        b = RetryPolicy(base_delay_s=1.0, multiplier=1.0, max_delay_s=1.0,
                        jitter=0.25, seed=5)
        da = [a.delay(0) for _ in range(50)]
        assert da == [b.delay(0) for _ in range(50)]   # same seed, same
        assert all(0.75 <= d <= 1.25 for d in da)
        assert len(set(da)) > 1                        # jitter is live

    def test_sleep_hook_receives_delays(self):
        slept = []
        p = RetryPolicy(base_delay_s=0.5, multiplier=2.0, max_delay_s=8.0,
                        jitter=0.0, sleep=slept.append)
        for attempt in range(3):
            p.sleep(attempt)
        assert slept == pytest.approx([0.5, 1.0, 2.0])
        assert p.slept == slept

    def test_zero_delay_never_calls_sleep(self):
        p = RetryPolicy(base_delay_s=0.0, jitter=0.0,
                        sleep=lambda s: pytest.fail("slept"))
        assert p.sleep(3) == 0.0

    def test_validation(self):
        with pytest.raises(ValueError, match="multiplier"):
            RetryPolicy(multiplier=0.5)
        with pytest.raises(ValueError, match="jitter"):
            RetryPolicy(jitter=1.5)


# ----------------------------------------------------------- breaker

class TestCircuitBreaker:
    def test_opens_after_threshold_and_half_open_probe(self):
        fc = FakeClock()
        br = CircuitBreaker(failure_threshold=3, reset_after_s=10.0,
                            clock=fc)
        assert br.allow()
        assert br.record_failure() is False
        assert br.record_failure() is False
        assert br.record_failure() is True        # opened exactly here
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()                     # cooldown running
        fc.advance(9.0)
        assert not br.allow()
        fc.advance(1.5)
        assert br.allow()                         # half-open probe
        assert br.state == CircuitBreaker.HALF_OPEN
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow()

    def test_failed_probe_reopens_immediately(self):
        fc = FakeClock()
        br = CircuitBreaker(failure_threshold=5, reset_after_s=1.0,
                            clock=fc)
        for _ in range(5):
            br.record_failure()
        fc.advance(2.0)
        assert br.allow()                          # probe admitted
        assert br.record_failure() is True         # 1 failure re-opens
        assert br.state == CircuitBreaker.OPEN
        assert br.open_total == 2

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failure_threshold=2, clock=FakeClock())
        br.record_failure()
        br.record_success()
        assert br.record_failure() is False        # streak restarted

    def test_half_open_admits_exactly_one_probe(self):
        """ISSUE 8 satellite (PR-7 known cut): racing submits at the
        cooldown edge must not all probe at once — the first allow()
        takes the single probe token, every racer is denied until the
        probe resolves."""
        fc = FakeClock()
        br = CircuitBreaker(failure_threshold=1, reset_after_s=5.0,
                            clock=fc)
        br.record_failure()
        fc.advance(6.0)
        assert br.allow()                       # probe taken
        assert br.state == CircuitBreaker.HALF_OPEN
        assert not br.allow()                   # racer denied
        assert not br.allow()                   # and again
        assert not br.would_allow()             # filter agrees
        assert br.record_failure() is True      # probe fails: re-open
        assert not br.allow()                   # cooldown restarts
        fc.advance(6.0)
        assert br.allow()                       # next single probe
        assert not br.allow()
        br.record_success()                     # probe succeeds
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow() and br.allow()        # closed: no gating

    def test_probe_token_is_atomic_under_racing_threads(self):
        """The race the token exists to gate IS concurrent: many
        threads calling allow() at the cooldown edge must yield exactly
        ONE True, and a thread that never took the token must not be
        able to release another thread's probe."""
        import threading
        fc = FakeClock()
        br = CircuitBreaker(failure_threshold=1, reset_after_s=5.0,
                            clock=fc)
        br.record_failure()
        fc.advance(6.0)
        got, start = [], threading.Barrier(16)

        def racer():
            start.wait()
            if br.allow():
                got.append(threading.get_ident())
            else:
                # non-owners abandoning must NOT free the real probe
                br.release_probe()

        ts = [threading.Thread(target=racer) for _ in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(got) == 1, f"{len(got)} concurrent probes admitted"
        assert not br.allow()              # token still held
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED

    def test_release_probe_unwedges_abandoned_attempt(self):
        """A caller that took the probe token but never touched the
        guarded resource (request expired, replica shed) hands it back
        — otherwise the breaker stays half-open denying everyone
        forever, with no probe outcome ever possible."""
        fc = FakeClock()
        br = CircuitBreaker(failure_threshold=1, reset_after_s=5.0,
                            clock=fc)
        br.record_failure()
        fc.advance(6.0)
        assert br.allow()
        assert not br.allow()                   # token held
        br.release_probe()                      # attempt abandoned
        assert br.would_allow()
        assert br.allow()                       # someone else probes
        assert not br.allow()
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        br.release_probe()                      # closed: harmless no-op
        assert br.allow()


# ------------------------------------------------------------ health

class TestHealthMonitor:
    def test_transitions_and_codes(self):
        seen = []
        hm = HealthMonitor(on_change=lambda s, c: seen.append((s, c)))
        assert hm.state == HEALTHY and hm.code == 0 and hm.is_serving
        hm.to(DEGRADED)
        assert hm.code == 1 and hm.is_serving
        hm.to(HEALTHY)
        hm.to(DRAINING)
        assert not hm.is_serving
        assert hm.to(HEALTHY) == DRAINING          # draining is one-way
        hm.to(DEAD)
        assert hm.to(DEGRADED) == DEAD             # dead is terminal
        assert seen == [(DEGRADED, 1), (HEALTHY, 0), (DRAINING, 2),
                        (DEAD, 3)]

    def test_reset_restarts(self):
        hm = HealthMonitor()
        hm.to(DEGRADED)
        hm.to(DEAD)
        assert hm.reset() == HEALTHY
        with pytest.raises(ValueError, match="unknown"):
            hm.to("sideways")

    def test_raising_observer_never_blocks_transition(self):
        """ISSUE 7 satellite regression: a raising on_change observer
        used to propagate out of to() and wedge the state transition
        mid-flight — health moves happen on FAILURE paths (breaker
        opens, drains, thread death), exactly where an extra exception
        does the most damage. Observers are now isolated: the
        transition commits, nothing raises, the error is kept."""
        boom = RuntimeError("telemetry sink is down")

        def observer(state, code):
            raise boom

        hm = HealthMonitor(on_change=observer)
        assert hm.to(DEGRADED) == DEGRADED      # committed, no raise
        assert hm.state == DEGRADED
        assert hm.to(DRAINING) == DRAINING
        assert hm.to(DEAD) == DEAD
        assert hm.reset() == HEALTHY            # reset path isolated too
        assert [s for s, _ in hm.observer_errors] == \
            [DEGRADED, DRAINING, DEAD, HEALTHY]
        assert all(e is boom for _, e in hm.observer_errors)

    def test_observer_errors_bounded(self):
        hm = HealthMonitor(on_change=lambda s, c: 1 / 0)
        for _ in range(3 * HealthMonitor.MAX_OBSERVER_ERRORS):
            hm.to(DEGRADED)
            hm.to(HEALTHY)
        assert len(hm.observer_errors) == HealthMonitor.MAX_OBSERVER_ERRORS


class TestWouldAllow:
    def test_would_allow_is_a_pure_read(self):
        """ISSUE 7: the router filters candidates with would_allow()
        (pure) and gates the actual dispatch with allow() (mutating) —
        a scan that routes elsewhere must not flip a breaker half-open
        with no probe outcome ever recorded."""
        fc = FakeClock()
        b = CircuitBreaker(failure_threshold=1, reset_after_s=5.0,
                           clock=fc)
        b.record_failure()
        assert b.state == b.OPEN
        assert not b.would_allow()
        fc.advance(6.0)
        assert b.would_allow()
        assert b.state == b.OPEN          # unchanged: no side effect
        assert b.would_allow()            # idempotent
        assert b.allow()                  # the dispatch gate mutates
        assert b.state == b.HALF_OPEN


# -------------------------------------------------------- supervisor

class TestServeSupervisor:
    def test_retry_then_open(self):
        slept = []
        sup = ServeSupervisor(
            retry=RetryPolicy(base_delay_s=0.1, multiplier=2.0,
                              jitter=0.0, sleep=slept.append),
            breaker=CircuitBreaker(failure_threshold=3,
                                   clock=FakeClock()))
        boom = RuntimeError("boom")
        assert sup.failure(boom) == "retry"
        assert sup.failure(boom) == "retry"
        assert sup.failure(boom) == "open"          # breaker trips; no
        assert slept == pytest.approx([0.1, 0.2])   # backoff on "open"
        assert sup.last_error is boom
        sup.success()
        assert sup.attempt == 0 and sup.last_error is None


# ------------------------------------------------------------ faults

class TestFaultInjector:
    def test_schedule_fires_exact_visits(self):
        fi = FaultInjector().on("pt", schedule=[1, 3])
        fired = []
        for i in range(5):
            try:
                fi.check("pt")
            except InjectedFault as e:
                fired.append(i)
                assert e.point == "pt" and e.visit == i
        assert fired == [1, 3]
        assert fi.trace == [("pt", 1), ("pt", 3)]
        assert fi.visits("pt") == 5 and fi.fired("pt") == 2

    def test_probability_deterministic_per_seed(self):
        def trace(seed):
            fi = FaultInjector(seed=seed).on("a", probability=0.4) \
                                         .on("b", probability=0.4)
            for _ in range(30):
                for pt in ("a", "b"):
                    try:
                        fi.check(pt)
                    except InjectedFault:
                        pass
            return fi.trace
        assert trace(7) == trace(7)
        assert trace(7) != trace(8)

    def test_per_point_streams_ignore_interleaving(self):
        """Fire decisions at one point must not depend on visits to
        another — the property that makes chaos traces reproducible
        when unrelated code paths add or drop visits."""
        fi1 = FaultInjector(seed=3).on("a", probability=0.5).on("b")
        for _ in range(20):
            try:
                fi1.check("a")
            except InjectedFault:
                pass
            fi1.check("b")               # interleaved unarmed visits
        fi2 = FaultInjector(seed=3).on("a", probability=0.5)
        for _ in range(20):
            try:
                fi2.check("a")
            except InjectedFault:
                pass
        assert [e for e in fi1.trace if e[0] == "a"] == fi2.trace

    def test_window_and_max_fires(self):
        fi = FaultInjector(seed=0).on("w", probability=1.0, start=2,
                                      stop=6, max_fires=3)
        fired = []
        for i in range(10):
            try:
                fi.check("w")
            except InjectedFault:
                fired.append(i)
        assert fired == [2, 3, 4]          # window opens at 2, cap 3

    def test_reset_replays_identically(self):
        fi = FaultInjector(seed=9).on("p", probability=0.3)
        for _ in range(25):
            try:
                fi.check("p")
            except InjectedFault:
                pass
        first = list(fi.trace)
        fi.reset()
        assert fi.trace == [] and fi.visits("p") == 0
        for _ in range(25):
            try:
                fi.check("p")
            except InjectedFault:
                pass
        assert fi.trace == first

    def test_disarm_counts_but_never_fires(self):
        fi = FaultInjector().on("p", schedule=[0, 1, 2, 3]).disarm()
        for _ in range(3):
            fi.check("p")                # visits 0-2 counted, no fire
        assert fi.visits("p") == 3 and fi.fired() == 0
        fi.arm()
        with pytest.raises(InjectedFault):
            fi.check("p")                # visit 3 fires once re-armed

    def test_custom_error_class_and_ctx(self):
        class Boom(RuntimeError):
            pass
        fi = FaultInjector().on("p", schedule=[0], error=Boom)
        with pytest.raises(Boom) as ei:
            fi.check("p", rid=42)
        assert ei.value.ctx == {"rid": 42}

    def test_wired_point_names_exported(self):
        assert faults.PREFILL == "server.prefill"
        assert faults.DECODE_TICK == "server.decode_tick"
        assert faults.PAGE_ALLOC == "kv.alloc"
        assert faults.ON_TOKEN == "server.on_token"


# ------------------------------------------------------------ errors

class TestErrors:
    def test_callback_error_carries_rids(self):
        z = ZeroDivisionError("x")
        e = CallbackError([(3, z), (5, ValueError("y"))])
        assert e.rid == 3 and e.__cause__ is z
        assert [r for r, _ in e.errors] == [3, 5]
        assert isinstance(e, ReliabilityError)

    def test_injected_fault_is_typed(self):
        e = InjectedFault("server.prefill", 4)
        assert isinstance(e, ReliabilityError)
        assert "visit 4" in str(e)
