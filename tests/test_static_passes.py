"""Static-graph pass infrastructure (reference paddle/fluid/framework/ir
Pass/PassRegistry; python paddle.static.apply_pass)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.static as static


def _build():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3])
        a = x * 2.0
        b = x * 2.0          # CSE duplicate
        _dead = x + 100.0    # dead once fetches are declared
        c = a + b
        y = c * 1.0
    return main, x, y


def test_cse_and_dce_shrink_and_preserve_semantics():
    main, x, y = _build()
    static.normalize_program(main, [x], [y])
    n0 = len(main.global_block.ops)
    static.apply_pass(main, ["common_subexpression_elimination",
                             "dead_code_elimination"])
    n1 = len(main.global_block.ops)
    assert n1 < n0
    (out,) = static.Executor().run(
        main, feed={"x": np.ones((2, 3), "float32")}, fetch_list=[y])
    np.testing.assert_allclose(out, 4.0)


def test_dce_conservative_without_declared_fetches():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        _t1 = x * 1.0
        _t2 = x * 5.0
    n0 = len(main.global_block.ops)
    static.apply_pass(main, "dead_code_elimination")
    assert len(main.global_block.ops) == n0


def test_build_strategy_runs_and_tags_fusion():
    bs = static.BuildStrategy()
    bs.memory_optimize = True
    bs.fuse_elewise_add_act_ops = True
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        h = pt.nn.functional.relu(x + 1.0)
    static.normalize_program(main, [x], [h])
    static.apply_build_strategy(main, None, bs)
    relu_op = [op for op in main.global_block.ops
               if op.op_type == "relu"][0]
    assert relu_op.attrs.get("_fused_with_add")
    (out,) = static.Executor().run(
        main, feed={"x": np.array([-2.0, 2.0], "float32")},
        fetch_list=[h])
    np.testing.assert_allclose(out, [0.0, 3.0])


def test_unknown_pass_raises():
    import pytest

    main, x, y = _build()
    with pytest.raises(ValueError):
        static.apply_pass(main, "nonexistent_pass")


def test_cse_with_list_valued_attrs():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3, 4])
        a = pt.ops.sum(x, axis=[0, 1])
        b = pt.ops.sum(x, axis=[0, 1])
        y = a + b
    static.normalize_program(main, [x], [y])
    static.apply_pass(main, "common_subexpression_elimination")
    (out,) = static.Executor().run(
        main, feed={"x": np.ones((2, 3, 4), "float32")}, fetch_list=[y])
    np.testing.assert_allclose(out, 12.0)


def test_dce_keeps_grad_targets():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3])
        loss = (x * x).sum()
        grads = static.gradients([loss], [x])
    static.normalize_program(main, [x], grads)
    static.apply_pass(main, "dead_code_elimination")
    (g,) = static.Executor().run(
        main, feed={"x": np.array([1., 2., 3.], "float32")},
        fetch_list=grads)
    np.testing.assert_allclose(g, [2, 4, 6])
