"""Static-graph pass infrastructure (reference paddle/fluid/framework/ir
Pass/PassRegistry; python paddle.static.apply_pass)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.static as static


def _build():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3])
        a = x * 2.0
        b = x * 2.0          # CSE duplicate
        _dead = x + 100.0    # dead once fetches are declared
        c = a + b
        y = c * 1.0
    return main, x, y


def test_cse_and_dce_shrink_and_preserve_semantics():
    main, x, y = _build()
    static.normalize_program(main, [x], [y])
    n0 = len(main.global_block.ops)
    static.apply_pass(main, ["common_subexpression_elimination",
                             "dead_code_elimination"])
    n1 = len(main.global_block.ops)
    assert n1 < n0
    (out,) = static.Executor().run(
        main, feed={"x": np.ones((2, 3), "float32")}, fetch_list=[y])
    np.testing.assert_allclose(out, 4.0)


def test_dce_conservative_without_declared_fetches():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        _t1 = x * 1.0
        _t2 = x * 5.0
    n0 = len(main.global_block.ops)
    static.apply_pass(main, "dead_code_elimination")
    assert len(main.global_block.ops) == n0


def test_build_strategy_runs_and_tags_fusion():
    bs = static.BuildStrategy()
    bs.memory_optimize = True
    bs.fuse_elewise_add_act_ops = True
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        h = pt.nn.functional.relu(x + 1.0)
    static.normalize_program(main, [x], [h])
    static.apply_build_strategy(main, None, bs)
    relu_op = [op for op in main.global_block.ops
               if op.op_type == "relu"][0]
    assert relu_op.attrs.get("_fused_with_add")
    (out,) = static.Executor().run(
        main, feed={"x": np.array([-2.0, 2.0], "float32")},
        fetch_list=[h])
    np.testing.assert_allclose(out, [0.0, 3.0])


def test_unknown_pass_raises():
    import pytest

    main, x, y = _build()
    with pytest.raises(ValueError):
        static.apply_pass(main, "nonexistent_pass")


def test_cse_with_list_valued_attrs():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 3, 4])
        a = pt.ops.sum(x, axis=[0, 1])
        b = pt.ops.sum(x, axis=[0, 1])
        y = a + b
    static.normalize_program(main, [x], [y])
    static.apply_pass(main, "common_subexpression_elimination")
    (out,) = static.Executor().run(
        main, feed={"x": np.ones((2, 3, 4), "float32")}, fetch_list=[y])
    np.testing.assert_allclose(out, 12.0)


def test_dce_keeps_grad_targets():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3])
        loss = (x * x).sum()
        grads = static.gradients([loss], [x])
    static.normalize_program(main, [x], grads)
    static.apply_pass(main, "dead_code_elimination")
    (g,) = static.Executor().run(
        main, feed={"x": np.array([1., 2., 3.], "float32")},
        fetch_list=grads)
    np.testing.assert_allclose(g, [2, 4, 6])


def _literalize_x(main, xname, value):
    """Replace VarRef inputs named `xname` with a literal array — mimics a
    program whose upstream producer was already folded to a constant."""
    from paddle_tpu.static.graph import VarRef
    for op in main.global_block.ops:
        op.inputs = [value if isinstance(i, VarRef) and i.name == xname
                     else i for i in op.inputs]


def test_constant_folding_keeps_fetch_roots():
    # ADVICE r3: a var produced by a folded op must remain fetchable
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        f = static.data("f", [2])
        c = x * 3.0                   # becomes all-literal below
        y = f + c
    static.normalize_program(main, [f], [c, y])
    _literalize_x(main, x.name, np.array([1.0, 1.0], "float32"))
    static.apply_pass(main, "constant_folding")
    # the op producing c was folded; c must still be fetchable
    c_out, y_out = static.Executor().run(
        main, feed={"f": np.array([1.0, 2.0], "float32")},
        fetch_list=[c, y])
    np.testing.assert_allclose(c_out, [3.0, 3.0])
    np.testing.assert_allclose(y_out, [4.0, 5.0])


def test_constant_folding_skips_stateful_ops():
    # ADVICE r3: random ops must not be frozen to one pass-time sample
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4])
        f = static.data("f", [4])
        o = x * 1.0                   # becomes all-literal below
        r = pt.nn.functional.dropout(o, p=0.5, training=True)
        y = f + r
    static.normalize_program(main, [f], [y])
    _literalize_x(main, x.name, np.ones(4, "float32"))
    static.apply_pass(main, "constant_folding")
    assert any("dropout" in op.op_type.lower()
               for op in main.global_block.ops), \
        "stateful dropout op was folded away"


def test_static_dropout_resamples_per_run():
    # reference static-graph semantics: runtime generator state, a fresh
    # sample each Executor.run (not a trace-time frozen mask)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [32])
        y = pt.nn.functional.dropout(x, p=0.5, training=True)
    static.normalize_program(main, [x], [y])
    ex = static.Executor()
    feed = {"x": np.ones(32, "float32")}
    draws = {tuple(np.asarray(ex.run(main, feed=feed, fetch_list=[y])[0])
                   .tolist()) for _ in range(6)}
    assert len(draws) > 1, "static dropout frozen across runs"
    # program.random_seed pins the sequence (reference Program.random_seed)
    main.random_seed = 1234
    main._version += 1
    a = ex.run(main, feed=feed, fetch_list=[y])[0]
    b = ex.run(main, feed=feed, fetch_list=[y])[0]
    np.testing.assert_array_equal(a, b)


def test_constant_folding_keeps_grad_wrt_leaves():
    # code-review r4: folding the producer of a grad-wrt var must leave a
    # producer so Executor's add_grads can read the leaf value
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        f = static.data("f", [2])
        c = x * 3.0                   # becomes all-literal below
        y = f + c
        grads = static.gradients([y], [c])
    static.normalize_program(main, [f], grads)
    _literalize_x(main, x.name, np.array([1.0, 1.0], "float32"))
    static.apply_pass(main, "constant_folding")
    (g,) = static.Executor().run(
        main, feed={"f": np.array([1.0, 2.0], "float32")},
        fetch_list=grads)
    np.testing.assert_allclose(g, [1.0, 1.0])


def test_constant_folding_keeps_grad_chain_through_wrt():
    # code-review r4 #2: consumers of a grad-wrt leaf must not fold, or
    # the target becomes a pass-time constant and the gradient zeroes
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2])
        c = x * 3.0                   # becomes all-literal below
        y = c * 2.0                   # consumer of the wrt leaf
        grads = static.gradients([y], [c])
    static.normalize_program(main, [], grads)
    _literalize_x(main, x.name, np.array([1.0, 1.0], "float32"))
    static.apply_pass(main, "constant_folding")
    (g,) = static.Executor().run(main, feed={}, fetch_list=grads)
    np.testing.assert_allclose(g, [2.0, 2.0])
