"""Live KV-page migration (ISSUE 18): drains hand off mid-decode
state instead of flushing partials.

Contracts pinned here:

- a mid-decode request migrated source -> target continues BIT-EXACT
  (greedy AND seeded-sampled) against a never-migrated oracle, with
  ZERO re-prefill on the target (``prefill_tokens`` and ``admissions``
  stay 0; a fused target's ``prefill_dispatches`` stays frozen too);
- every failure degrades to requeue-replay, typed and leak-free:
  checksum mismatch, injected ``migrate.gather``/``migrate.restore``
  chaos, a target with no free slot, a SIGKILLed target process — the
  source resumes the paused slot bit-exactly and counts
  ``server_migrations_total{result="fallback"}``;
- the wire protocol ships one sha256-checked binary frame per page and
  the client's ``fetch_tokens`` backfill heals token-push gaps a
  ``net.send`` drop storm tears into the stream (the ``_on_tokens``
  regression);
- a 25% chaos storm over ``net.*`` + ``migrate.*`` replays identically
  under the same seed (single-threaded, step()-driven, so the fault
  trace is exact);
- per-shard gathers/scatters are topology-neutral: pages migrate
  between mp=1 and mp=2 pools bit-exactly (real llama sampling, so
  the restored PRNG chain is genuinely exercised).
"""
import random
import socket
import time

import numpy as np
import pytest

import jax

from _remote_stub import make_stub_server
from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import OutOfPages
from paddle_tpu.inference.remote import ReplicaHost, RemoteReplica
from paddle_tpu.inference.transport import Connection, NetDelay, NetDrop
from paddle_tpu.reliability import (MIGRATE_GATHER, MIGRATE_RESTORE,
                                    NET_PAGE_SEND, NET_RECV, NET_SEND,
                                    FaultInjector, InjectedFault,
                                    MigrationError)

SERVER_KW = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                 page_size=8, num_pages=17)
PROMPT = (np.arange(1, 12, dtype=np.int32) % 13)
BUDGET = 48          # prompt 11 + 48 <= max_cache_len 64; big enough
#                      that the handoff reliably lands mid-decode


def _loopback_available():
    try:
        s = socket.create_server(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _sink(got, dt=0.003):
    """A throttling stream callback: 3 ms per chunk keeps the decode
    loop slow enough that migrate_out always catches the request
    mid-decode (callbacks fire on the serving thread)."""
    def cb(rid, toks):
        got.extend(int(t) for t in toks)
        time.sleep(dt)
    return cb


def _wait(pred, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timed out on: {msg}"
        time.sleep(0.005)


def _servers(n, **overrides):
    kw = dict(SERVER_KW)
    kw.update(overrides)
    return [ContinuousBatchingServer(StubModel(), **kw)
            for _ in range(n)]


class _Throttle(NetDelay):
    """Every host send dawdles 10 ms: the deferred token-push callbacks
    fire on the serving thread, so this paces the decode loop and the
    wire drills reliably catch the request MID-decode (the StubModel
    otherwise finishes a 48-token budget in the round-trip window)."""
    SECONDS = 0.01


def _throttle_fi():
    return FaultInjector(seed=1).on(NET_SEND, probability=1.0,
                                    error=_Throttle)


class _StormFactory:
    """probability-1.0 ``net.send`` rule for the drop-storm drill:
    every send fires — most resolve to the pacing delay (keeping the
    stream stretched mid-air), a seeded fraction DROP the frame
    outright, capped so the tail of the stream gets through clean and
    the backfill's repair pushes eventually land."""

    def __init__(self, seed, p_drop=0.25, max_drops=6):
        self.rng = random.Random(seed)
        self.p_drop, self.max_drops = p_drop, max_drops
        self.drops = 0

    def __call__(self):
        if self.drops < self.max_drops \
                and self.rng.random() < self.p_drop:
            self.drops += 1
            return NetDrop("storm drop")
        return _Throttle("pacing")


# =================================================== in-process parity
class TestMigrationInProcess:
    # the greedy half is the tier-1 canary; sampled PRNG re-derivation
    # stays covered tier-1 by the abort test below and in full by the
    # slow wire-sampled parity case
    @pytest.mark.parametrize(
        "do_sample", [False, pytest.param(True, marks=pytest.mark.slow)],
        ids=["greedy", "sampled"])
    def test_mid_decode_migration_bitexact_zero_reprefill(self,
                                                          do_sample):
        """The acceptance drill: pause mid-decode, gather, restore on
        a sibling, resume mid-chain — tokens bit-exact vs a
        never-migrated oracle, zero prefill work on the target, zero
        leaked pages on either end, journey + metrics attributed."""
        kw = dict(do_sample=do_sample)
        if do_sample:
            kw.update(temperature=0.7, top_k=8)
        tgt, oracle = _servers(2, **kw)
        src = ContinuousBatchingServer(
            StubModel(), telemetry=True, journeys=True, recorder=True,
            **dict(SERVER_KW, **kw))
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            assert state["seed"] == 5          # resolved seed travels
            assert state["sha256"] and len(state["sha256"]) \
                == len(payloads)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            out = tgt.wait(new_rid, timeout=60)
            ref = oracle.wait(rid_o, timeout=60)
            np.testing.assert_array_equal(out, ref)
            if not do_sample:
                np.testing.assert_array_equal(
                    out, stub_tokens(PROMPT, BUDGET))
            # the stream healed across the handoff: every token once,
            # in order, no re-delivery of the pre-migration prefix
            _wait(lambda: len(got) >= BUDGET, timeout=10,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            # zero re-prefill on the target: no admission, no prompt
            # tokens pushed — the restore scatter is priced as
            # page_migrate bytes, not prefill
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            assert src.stats["migrations"] == 1
            assert tgt.stats["migrated_in"] == 1
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
            # attribution: the journey crossed a "migrating" phase and
            # the source counted {result="ok"} with a latency sample
            timeline = src.journey(rid)
            assert any(e["phase"] == "migrating" for e in timeline)
            snap = src._tele.registry.snapshot()
            assert snap["server_migrations_total"]["samples"][
                ("ok",)] == 1
            assert snap["serving_migration_seconds"]["samples"][()][
                "count"] == 1
        finally:
            src.stop(); tgt.stop(); oracle.stop()

    def test_fused_target_prefill_dispatches_frozen(self):
        """A fused-tick target restores through the same path with its
        prefill dispatch counter EXACTLY frozen (split targets count
        state pushes there; fused has no push op to excuse)."""
        src, oracle = _servers(2)
        (tgt,) = _servers(1, serving_mode="fused",
                          prefill_mode="ragged")
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            before = tgt.stats["prefill_dispatches"]
            state, payloads = src.migrate_out(rid)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            np.testing.assert_array_equal(tgt.wait(new_rid, timeout=60),
                                          oracle.wait(rid_o, timeout=60))
            assert tgt.stats["prefill_dispatches"] == before
            assert tgt.stats["prefill_tokens"] == 0
        finally:
            src.stop(); tgt.stop(); oracle.stop()

    def test_abort_resumes_bitexact_and_counts_fallback(self):
        """migrate_abort re-primes the paused slot (pending token,
        write cursor, PRNG key mid-chain) so the SOURCE finishes the
        stream bit-exactly — the universal fallback every failure
        path below degrades to."""
        src, oracle = _servers(2, do_sample=True, temperature=0.7,
                               top_k=8)
        got = []
        src.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=9)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=9,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            assert src.migrate_abort(rid) is True
            assert src.migrate_abort(rid) is False   # idempotent
            np.testing.assert_array_equal(src.wait(rid, timeout=60),
                                          oracle.wait(rid_o, timeout=60))
            assert src.stats["migration_fallbacks"] == 1
            assert src.stats["migrations"] == 0
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); oracle.stop()

    def test_refusals_typed_and_leak_free(self):
        """Non-migratable requests refuse with ``MigrationError`` (a
        named, wire-marshallable class) without touching the slot:
        unknown rids, finished rids, double migrations, dense pools,
        and tampered payloads/geometry at the restore end."""
        src, tgt = _servers(2)
        (dense,) = _servers(1, cache_backend="dense")
        got = []
        src.start(); tgt.start(); dense.start()
        try:
            with pytest.raises(MigrationError):
                src.migrate_out(12345)                 # unknown rid
            with pytest.raises(MigrationError):
                dense.migrate_out(0)                   # no page pool
            done = src.submit(PROMPT, max_new_tokens=4)
            src.wait(done, timeout=60)
            with pytest.raises(MigrationError):
                src.migrate_out(done)                  # finished rid
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            with pytest.raises(MigrationError):
                src.migrate_out(rid)                   # already paused
            # target-side refusals, each before any page sticks:
            bad = dict(state, page_size=4)
            with pytest.raises(MigrationError):
                tgt.migrate_in(bad, payloads)          # geometry
            with pytest.raises(MigrationError):
                tgt.migrate_in(state, payloads[:-1])   # page count
            tampered = [[np.array(a) for a in p] for p in payloads]
            tampered[0][0].flat[0] += 1.0
            with pytest.raises(MigrationError):
                tgt.migrate_in(state, tampered)        # e2e sha256
            assert tgt.pool_balance()[1] == 0          # nothing stuck
            assert tgt.stats["migrated_in"] == 0
            # the source still resumes cleanly after all that
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop(); dense.stop()

    def test_chaos_gather_and_restore_fall_back(self):
        """``migrate.gather`` fires BEFORE the pause (the faulted
        attempt leaves the slot decoding untouched); ``migrate.restore``
        fires before any allocation on the target — both degrade to
        abort/resume with zero leaked pages anywhere."""
        fi_src = FaultInjector(seed=6).on(MIGRATE_GATHER, schedule=[0])
        fi_tgt = FaultInjector(seed=6).on(MIGRATE_RESTORE, schedule=[0])
        kw = dict(SERVER_KW)
        src = ContinuousBatchingServer(StubModel(),
                                       fault_injector=fi_src, **kw)
        tgt = ContinuousBatchingServer(StubModel(),
                                       fault_injector=fi_tgt, **kw)
        got = []
        src.start(); tgt.start()
        try:
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            with pytest.raises(InjectedFault):
                src.migrate_out(rid)                  # gather chaos
            state, payloads = src.migrate_out(rid)    # fault spent
            with pytest.raises(InjectedFault):
                tgt.migrate_in(state, payloads)       # restore chaos
            assert tgt.pool_balance()[1] == 0
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            assert src.stats["migration_fallbacks"] == 1
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop()

    def test_target_without_free_slot_refuses_typed(self):
        """A packed target raises ``OutOfPages`` from the normal admit
        path — the router treats it like any restore failure and falls
        back; the source resumes bit-exactly."""
        src, tgt = _servers(2)
        got = []
        src.start(); tgt.start()
        try:
            hold = [tgt.submit(PROMPT, max_new_tokens=BUDGET,
                               on_token=_sink([], dt=0.005))
                    for _ in range(2)]         # both target slots busy
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            with pytest.raises(OutOfPages):
                tgt.migrate_in(state, payloads)
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            for h in hold:
                tgt.wait(h, timeout=60)
            assert src.pool_balance()[1] == 0
            assert tgt.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop()


# ============================================== wire + router + drills
@pytest.mark.net
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestWireMigration:
    @pytest.fixture
    def fleet(self):
        opened = []

        def pair(src_faults=None, **kw):
            src = make_stub_server(num_pages=17, **kw)
            tgt = make_stub_server(num_pages=17, **kw)
            hs = ReplicaHost(src, heartbeat_s=30,
                             fault_injector=src_faults).start()
            ht = ReplicaHost(tgt, heartbeat_s=30).start()
            rs = RemoteReplica(hs.address)
            rt = RemoteReplica(ht.address)
            src.start(); tgt.start()
            opened.extend([(rs, rt), (hs, ht), (src, tgt)])
            return src, tgt, hs, ht, rs, rt

        yield pair
        for rs, rt in opened[0::3]:
            rs.close(); rt.close()
        for hs, ht in opened[1::3]:
            hs.close(); ht.close()
        for src, tgt in opened[2::3]:
            src.stop(); tgt.stop()

    @pytest.mark.parametrize(
        "do_sample",
        [False, pytest.param(True, marks=pytest.mark.slow)],
        ids=["greedy", "sampled"])
    def test_wire_migration_bitexact(self, fleet, do_sample):
        """The tentpole over real sockets: binary page frames out of
        the source host, restored on the target host, the client
        stream re-homed — bit-exact vs a never-migrated oracle with
        zero re-prefill and zero leaks on both processes' pools."""
        kw = dict(do_sample=do_sample)
        if do_sample:
            kw.update(temperature=0.7, top_k=8)
        src, tgt, hs, ht, rs, rt = fleet(src_faults=_throttle_fi(),
                                         **kw)
        oracle = make_stub_server(num_pages=17, **kw)
        oracle.start()
        got = []
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                            on_token=lambda r, t: got.extend(
                                int(x) for x in t))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = rs.migrate_out(rid)
            # client-truth delivery offset rides with the state so the
            # target's mirror starts exactly where this client stopped
            assert state.get("delivered") is not None
            new_rid = rt.migrate_in(
                state, payloads,
                on_token=lambda r, t: got.extend(int(x) for x in t))
            assert rs.migrate_finish(rid) is True
            out = rt.wait(new_rid, timeout=60)
            ref = oracle.wait(rid_o, timeout=60)
            np.testing.assert_array_equal(out, ref)
            if not do_sample:
                np.testing.assert_array_equal(
                    out, stub_tokens(PROMPT, BUDGET))
            _wait(lambda: len(got) >= BUDGET, timeout=10,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            assert src.stats["migrations"] == 1
            assert tgt.stats["migrated_in"] == 1
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            oracle.stop()

    def test_wire_checksum_mismatch_falls_back(self, fleet):
        """A payload corrupted between hosts fails the END-TO-END
        sha256 at restore (typed, over the wire) — the source aborts,
        resumes, and finishes the stream itself; zero leaks."""
        src, tgt, hs, ht, rs, rt = fleet(src_faults=_throttle_fi())
        got = []
        rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                        on_token=lambda r, t: got.extend(
                            int(x) for x in t))
        _wait(lambda: len(got) >= 6, msg="first streamed tokens")
        state, payloads = rs.migrate_out(rid)
        tampered = [np.array(p) for p in payloads]
        tampered[0].flat[0] += 1.0
        with pytest.raises(MigrationError):
            rt.migrate_in(state, tampered)
        assert rs.migrate_abort(rid) is True
        np.testing.assert_array_equal(rs.wait(rid, timeout=60),
                                      stub_tokens(PROMPT, BUDGET))
        assert src.stats["migration_fallbacks"] == 1
        assert tgt.stats["migrated_in"] == 0
        for s in (src, tgt):
            assert s.pool_balance()[1] == 0

    def test_drop_storm_backfill_heals_token_stream(self, fleet):
        """The ``remote._on_tokens`` regression (satellite): a
        ``net.send`` drop storm on the HOST side eats token-push
        frames mid-stream; the client detects each gap and repairs it
        with ``fetch_tokens`` backfill from the host's stash — the
        delivered stream ends COMPLETE and exact, not truncated at the
        first hole."""
        storm = _StormFactory(seed=8)
        fi = FaultInjector(seed=8, enabled=False) \
            .on(NET_SEND, probability=1.0, error=storm)
        src, tgt, hs, ht, rs, rt = fleet(src_faults=fi)
        got = []
        rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                        on_token=lambda r, t: got.extend(
                            int(x) for x in t))
        _wait(lambda: len(got) >= 4, msg="stream started")
        fi.arm()                       # the storm eats mid-stream pushes
        np.testing.assert_array_equal(rs.wait(rid, timeout=60),
                                      stub_tokens(PROMPT, BUDGET))
        _wait(lambda: len(got) >= BUDGET, timeout=15,
              msg="backfill healed the stream")
        assert got == [int(t) for t in stub_tokens(PROMPT, BUDGET)]
        assert storm.drops >= 1        # the storm actually tore frames


# ============================================= kill drill (real SIGKILL)
@pytest.mark.net
@pytest.mark.slow
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestMidMigrationKillDrill:
    @pytest.fixture
    def procs(self):
        spawned = []
        yield spawned
        for proc in spawned:
            if proc.is_alive():
                proc.kill()
            proc.join(10)

    def test_sigkill_target_falls_back_zero_leaks_one_flow(
            self, procs, tmp_path):
        """Mid-migration SIGKILL: the target PROCESS dies between the
        source's gather and the restore. The router degrades to
        fallback (``migration_fallbacks`` counts, ``migrations`` does
        not), the source resumes the paused slot and finishes the
        stream BIT-EXACT with zero failed requests and zero leaked
        pages — and the request's journey still renders as ONE
        connected flow across process boundaries in the fleet trace."""
        import json as _json
        import os as _os
        import signal as _signal

        from _remote_stub import make_slow_stub_server
        from paddle_tpu.inference.remote import spawn_replica_host
        from paddle_tpu.inference.router import ReplicaRouter

        server_kw = dict(max_slots=2, max_cache_len=64, page_size=8,
                         num_pages=17, tick_sleep_s=0.01)
        addrs = []
        for _ in range(2):
            proc, addr = spawn_replica_host(
                make_slow_stub_server, server_kw, heartbeat_s=0.05,
                start_server=True)
            procs.append(proc)
            addrs.append(addr)
        reps = [RemoteReplica(addr, call_timeout_s=2.0)
                for addr in addrs]
        router = ReplicaRouter(reps, policy="least_loaded",
                               journeys=True, recorder=True)
        got = []
        try:
            rid = router.submit(PROMPT, max_new_tokens=BUDGET,
                                on_token=lambda r, t: got.extend(
                                    int(x) for x in t))
            _wait(lambda: len(got) >= 6, timeout=120,
                  msg="first streamed tokens from the child")
            with router._lock:
                src_idx = router._routes[rid].idx
            victim = 1 - src_idx
            _os.kill(procs[victim].pid, _signal.SIGKILL)
            procs[victim].join(10)
            moved = router._migrate_live(src_idx)
            assert moved == 0
            assert router._stats["migration_fallbacks"] == 1
            assert router._stats["migrations"] == 0
            out = router.wait(rid, timeout=120)
            np.testing.assert_array_equal(out,
                                          stub_tokens(PROMPT, BUDGET))
            assert got == [int(t) for t in stub_tokens(PROMPT, BUDGET)]
            # zero leaks on the (live) source, measured over the wire
            bal = reps[src_idx].pool_balance()
            assert bal is not None and bal[1] == 0, f"leaked: {bal}"
            # the fallback is attributed on the journey...
            timeline = router.journey(rid)
            assert any(e["phase"] == "migrating"
                       and e.get("fallback") for e in timeline)
            # ...and the journey is ONE connected flow spanning the
            # router pid and the source child pid
            path = tmp_path / "fleet.json"
            router.export_fleet_trace(str(path))
            evs = _json.loads(path.read_text())["traceEvents"]
            flows = [e for e in evs if e.get("cat") == "journey"
                     and e.get("id") == f"r{rid}"]
            assert len(flows) >= 2
            assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
            assert len({e["pid"] for e in flows}) >= 2
        finally:
            router.stop(drain=False, timeout=20, stop_replicas=False)
            for rep in reps:
                rep.close()


# ===================================== seeded chaos storm determinism
@pytest.mark.chaos
@pytest.mark.slow
class TestMigrationChaosStorm:
    """A 25% storm over every ``net.*`` + ``migrate.*`` point the
    migration path crosses, driven SINGLE-THREADED (manual step(),
    socketpair wire) so the fault trace is exact: same seed => same
    trace => same tokens, different seed => different trace."""

    @staticmethod
    def _storm_run(seed):
        fi = FaultInjector(seed=seed) \
            .on(NET_SEND, probability=0.25, error=NetDrop) \
            .on(NET_RECV, probability=0.25, error=NetDrop) \
            .on(NET_PAGE_SEND, probability=0.25, error=NetDrop) \
            .on(MIGRATE_GATHER, probability=0.25) \
            .on(MIGRATE_RESTORE, probability=0.25)
        kw = dict(SERVER_KW)
        src = ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                       **kw)
        tgt = ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                       **kw)
        sa, sb = socket.socketpair()
        a = Connection(sa, fault_injector=fi, peer="src-host")
        b = Connection(sb, peer="tgt-host")
        b._faults = fi
        got = []
        budget = 24
        rid = src.submit(PROMPT, max_new_tokens=budget, seed=5,
                         on_token=lambda r, t: got.extend(
                             int(x) for x in t))

        def step_until(srv, pred, cap=4000):
            for _ in range(cap):
                if pred():
                    return
                srv.step()
            raise AssertionError("stepped past the cap")

        step_until(src, lambda: len(got) >= 6)
        carrier, wait_rid = src, rid
        for _ in range(8):                      # bounded storm retries
            try:
                state, payloads = src.migrate_out(rid)
            except InjectedFault:
                continue                        # gather chaos: slot
            #                                     untouched, try again
            try:
                lost = not a.send({"op": "migrate_in",
                                   "n": len(payloads)})
                for i, p in enumerate(payloads):
                    arr = np.ascontiguousarray(np.stack(p))
                    if not a.send_pages(
                            {"i": i, "shape": list(arr.shape),
                             "dtype": str(arr.dtype)},
                            arr.tobytes()):
                        lost = True
                frames = {}
                header = None
                while True:
                    try:
                        msg = b.recv(timeout=0.1)
                    except TimeoutError:
                        break
                    if "op" in msg:
                        header = msg
                    else:
                        frames[int(msg["i"])] = np.frombuffer(
                            msg["_payload"],
                            dtype=np.dtype(msg["dtype"])) \
                            .reshape(msg["shape"])
                if lost or header is None \
                        or len(frames) != len(payloads):
                    src.migrate_abort(rid)      # frame loss: fallback
                    continue
                new_rid = tgt.migrate_in(
                    state, [frames[i] for i in range(len(payloads))],
                    on_token=lambda r, t: got.extend(
                        int(x) for x in t))
            except (InjectedFault, MigrationError):
                src.migrate_abort(rid)          # restore chaos
                continue
            src.migrate_finish(rid)
            carrier, wait_rid = tgt, new_rid
            break
        step_until(carrier, lambda: len(got) >= budget)
        out = carrier.wait(wait_rid, timeout=5)
        assert src.pool_balance()[1] == 0
        assert tgt.pool_balance()[1] == 0
        a.close()
        b.close()
        return list(fi.trace), [int(t) for t in out], list(got)

    def test_same_seed_same_trace_same_tokens(self):
        t1, out1, got1 = self._storm_run(13)
        t2, out2, got2 = self._storm_run(13)
        t3, _, _ = self._storm_run(14)
        assert t1 == t2                      # identical fault traces
        assert out1 == out2 == got1 == got2  # identical streams
        assert t1 != t3                      # the seed actually steers
        assert out1 == [int(t) for t in stub_tokens(PROMPT, 24)]
        assert len(t1) >= 1                  # the storm actually fired


# ======================================== sharded gather/scatter parity
@pytest.mark.mesh
@pytest.mark.slow
@pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="needs >= 2 forced host devices "
           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
class TestShardedMigration:
    @pytest.fixture(scope="class")
    def llama4(self):
        """llama with 4 kv heads (divisible by mp=2) — real sampling,
        so the restored PRNG chain is exercised for real (the stub's
        closed-form logits cannot distinguish a mis-primed key)."""
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=1,
                          num_heads=8, num_kv_heads=4,
                          intermediate_size=128, max_seq_len=128)
        pt.seed(21)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    @pytest.mark.parametrize("src_mp,tgt_mp", [(2, 1), (1, 2)],
                             ids=["mp2_to_mp1", "mp1_to_mp2"])
    def test_cross_topology_migration_bitexact(self, llama4, src_mp,
                                               tgt_mp):
        """Pages gathered per shard on an mp=2 mesh restore into a
        single-device pool bit-exactly, and vice versa: the wire
        payload is topology-neutral host arrays, so migration crosses
        tensor-parallel layouts without a re-prefill."""
        from jax.sharding import Mesh

        def mesh(n):
            return Mesh(np.array(jax.devices()[:n]), ("mp",)) \
                if n > 1 else None

        kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                  page_size=8, num_pages=24, do_sample=True,
                  temperature=0.8, top_k=20)
        src = ContinuousBatchingServer(llama4, mesh=mesh(src_mp), **kw)
        tgt = ContinuousBatchingServer(llama4, mesh=mesh(tgt_mp), **kw)
        oracle = ContinuousBatchingServer(llama4, **kw)
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 256, (9,)).astype(np.int32)
        budget = 24
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(prompt, max_new_tokens=budget,
                                  seed=31)
            rid = src.submit(prompt, max_new_tokens=budget, seed=31,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, timeout=120,
                  msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            out = tgt.wait(new_rid, timeout=120)
            ref = oracle.wait(rid_o, timeout=120)
            np.testing.assert_array_equal(out, ref)
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop(); oracle.stop()
