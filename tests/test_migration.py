"""Live KV-page migration (ISSUE 18): drains hand off mid-decode
state instead of flushing partials.

Contracts pinned here:

- a mid-decode request migrated source -> target continues BIT-EXACT
  (greedy AND seeded-sampled) against a never-migrated oracle, with
  ZERO re-prefill on the target (``prefill_tokens`` and ``admissions``
  stay 0; a fused target's ``prefill_dispatches`` stays frozen too);
- every failure degrades to requeue-replay, typed and leak-free:
  checksum mismatch, injected ``migrate.gather``/``migrate.restore``
  chaos, a target with no free slot, a SIGKILLed target process — the
  source resumes the paused slot bit-exactly and counts
  ``server_migrations_total{result="fallback"}``;
- the wire protocol ships one sha256-checked binary frame per page and
  the client's ``fetch_tokens`` backfill heals token-push gaps a
  ``net.send`` drop storm tears into the stream (the ``_on_tokens``
  regression);
- a 25% chaos storm over ``net.*`` + ``migrate.*`` replays identically
  under the same seed (single-threaded, step()-driven, so the fault
  trace is exact);
- per-shard gathers/scatters are topology-neutral: pages migrate
  between mp=1 and mp=2 pools bit-exactly (real llama sampling, so
  the restored PRNG chain is genuinely exercised).
"""
import random
import socket
import threading
import time

import numpy as np
import pytest

import jax

from _remote_stub import make_stub_server
from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import OutOfPages
from paddle_tpu.inference.remote import ReplicaHost, RemoteReplica
from paddle_tpu.inference.transport import Connection, NetDelay, NetDrop
from paddle_tpu.reliability import (MIGRATE_GATHER, MIGRATE_RESTORE,
                                    NET_PAGE_SEND, NET_RECV, NET_SEND,
                                    FaultInjector, InjectedFault,
                                    MigrationError)

SERVER_KW = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                 page_size=8, num_pages=17)
PROMPT = (np.arange(1, 12, dtype=np.int32) % 13)
BUDGET = 48          # prompt 11 + 48 <= max_cache_len 64; big enough
#                      that the handoff reliably lands mid-decode


def _loopback_available():
    try:
        s = socket.create_server(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _sink(got, dt=0.003):
    """A throttling stream callback: 3 ms per chunk keeps the decode
    loop slow enough that migrate_out always catches the request
    mid-decode (callbacks fire on the serving thread)."""
    def cb(rid, toks):
        got.extend(int(t) for t in toks)
        time.sleep(dt)
    return cb


def _wait(pred, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timed out on: {msg}"
        time.sleep(0.005)


def _servers(n, **overrides):
    kw = dict(SERVER_KW)
    kw.update(overrides)
    return [ContinuousBatchingServer(StubModel(), **kw)
            for _ in range(n)]


class _Throttle(NetDelay):
    """Every host send dawdles 10 ms: the deferred token-push callbacks
    fire on the serving thread, so this paces the decode loop and the
    wire drills reliably catch the request MID-decode (the StubModel
    otherwise finishes a 48-token budget in the round-trip window)."""
    SECONDS = 0.01


def _throttle_fi():
    return FaultInjector(seed=1).on(NET_SEND, probability=1.0,
                                    error=_Throttle)


class _StormFactory:
    """probability-1.0 ``net.send`` rule for the drop-storm drill:
    every send fires — most resolve to the pacing delay (keeping the
    stream stretched mid-air), a seeded fraction DROP the frame
    outright, capped so the tail of the stream gets through clean and
    the backfill's repair pushes eventually land."""

    def __init__(self, seed, p_drop=0.25, max_drops=6):
        self.rng = random.Random(seed)
        self.p_drop, self.max_drops = p_drop, max_drops
        self.drops = 0

    def __call__(self):
        if self.drops < self.max_drops \
                and self.rng.random() < self.p_drop:
            self.drops += 1
            return NetDrop("storm drop")
        return _Throttle("pacing")


# =================================================== in-process parity
class TestMigrationInProcess:
    # the greedy half is the tier-1 canary; sampled PRNG re-derivation
    # stays covered tier-1 by the abort test below and in full by the
    # slow wire-sampled parity case
    @pytest.mark.parametrize(
        "do_sample", [False, pytest.param(True, marks=pytest.mark.slow)],
        ids=["greedy", "sampled"])
    def test_mid_decode_migration_bitexact_zero_reprefill(self,
                                                          do_sample):
        """The acceptance drill: pause mid-decode, gather, restore on
        a sibling, resume mid-chain — tokens bit-exact vs a
        never-migrated oracle, zero prefill work on the target, zero
        leaked pages on either end, journey + metrics attributed."""
        kw = dict(do_sample=do_sample)
        if do_sample:
            kw.update(temperature=0.7, top_k=8)
        tgt, oracle = _servers(2, **kw)
        src = ContinuousBatchingServer(
            StubModel(), telemetry=True, journeys=True, recorder=True,
            **dict(SERVER_KW, **kw))
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            assert state["seed"] == 5          # resolved seed travels
            assert state["sha256"] and len(state["sha256"]) \
                == len(payloads)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            out = tgt.wait(new_rid, timeout=60)
            ref = oracle.wait(rid_o, timeout=60)
            np.testing.assert_array_equal(out, ref)
            if not do_sample:
                np.testing.assert_array_equal(
                    out, stub_tokens(PROMPT, BUDGET))
            # the stream healed across the handoff: every token once,
            # in order, no re-delivery of the pre-migration prefix
            _wait(lambda: len(got) >= BUDGET, timeout=10,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            # zero re-prefill on the target: no admission, no prompt
            # tokens pushed — the restore scatter is priced as
            # page_migrate bytes, not prefill
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            assert src.stats["migrations"] == 1
            assert tgt.stats["migrated_in"] == 1
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
            # attribution: the journey crossed a "migrating" phase and
            # the source counted {result="ok"} with a latency sample
            timeline = src.journey(rid)
            assert any(e["phase"] == "migrating" for e in timeline)
            snap = src._tele.registry.snapshot()
            assert snap["server_migrations_total"]["samples"][
                ("ok",)] == 1
            assert snap["serving_migration_seconds"]["samples"][()][
                "count"] == 1
        finally:
            src.stop(); tgt.stop(); oracle.stop()

    def test_fused_target_prefill_dispatches_frozen(self):
        """A fused-tick target restores through the same path with its
        prefill dispatch counter EXACTLY frozen (split targets count
        state pushes there; fused has no push op to excuse)."""
        src, oracle = _servers(2)
        (tgt,) = _servers(1, serving_mode="fused",
                          prefill_mode="ragged")
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            before = tgt.stats["prefill_dispatches"]
            state, payloads = src.migrate_out(rid)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            np.testing.assert_array_equal(tgt.wait(new_rid, timeout=60),
                                          oracle.wait(rid_o, timeout=60))
            assert tgt.stats["prefill_dispatches"] == before
            assert tgt.stats["prefill_tokens"] == 0
        finally:
            src.stop(); tgt.stop(); oracle.stop()

    def test_abort_resumes_bitexact_and_counts_fallback(self):
        """migrate_abort re-primes the paused slot (pending token,
        write cursor, PRNG key mid-chain) so the SOURCE finishes the
        stream bit-exactly — the universal fallback every failure
        path below degrades to."""
        src, oracle = _servers(2, do_sample=True, temperature=0.7,
                               top_k=8)
        got = []
        src.start(); oracle.start()
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=9)
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=9,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            assert src.migrate_abort(rid) is True
            assert src.migrate_abort(rid) is False   # idempotent
            np.testing.assert_array_equal(src.wait(rid, timeout=60),
                                          oracle.wait(rid_o, timeout=60))
            assert src.stats["migration_fallbacks"] == 1
            assert src.stats["migrations"] == 0
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); oracle.stop()

    def test_refusals_typed_and_leak_free(self):
        """Non-migratable requests refuse with ``MigrationError`` (a
        named, wire-marshallable class) without touching the slot:
        unknown rids, finished rids, double migrations, dense pools,
        and tampered payloads/geometry at the restore end."""
        src, tgt = _servers(2)
        (dense,) = _servers(1, cache_backend="dense")
        got = []
        src.start(); tgt.start(); dense.start()
        try:
            with pytest.raises(MigrationError):
                src.migrate_out(12345)                 # unknown rid
            with pytest.raises(MigrationError):
                dense.migrate_out(0)                   # no page pool
            done = src.submit(PROMPT, max_new_tokens=4)
            src.wait(done, timeout=60)
            with pytest.raises(MigrationError):
                src.migrate_out(done)                  # finished rid
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            with pytest.raises(MigrationError):
                src.migrate_out(rid)                   # already paused
            # target-side refusals, each before any page sticks:
            bad = dict(state, page_size=4)
            with pytest.raises(MigrationError):
                tgt.migrate_in(bad, payloads)          # geometry
            with pytest.raises(MigrationError):
                tgt.migrate_in(state, payloads[:-1])   # page count
            tampered = [[np.array(a) for a in p] for p in payloads]
            tampered[0][0].flat[0] += 1.0
            with pytest.raises(MigrationError):
                tgt.migrate_in(state, tampered)        # e2e sha256
            assert tgt.pool_balance()[1] == 0          # nothing stuck
            assert tgt.stats["migrated_in"] == 0
            # the source still resumes cleanly after all that
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop(); dense.stop()

    def test_chaos_gather_and_restore_fall_back(self):
        """``migrate.gather`` fires BEFORE the pause (the faulted
        attempt leaves the slot decoding untouched); ``migrate.restore``
        fires before any allocation on the target — both degrade to
        abort/resume with zero leaked pages anywhere."""
        fi_src = FaultInjector(seed=6).on(MIGRATE_GATHER, schedule=[0])
        fi_tgt = FaultInjector(seed=6).on(MIGRATE_RESTORE, schedule=[0])
        kw = dict(SERVER_KW)
        src = ContinuousBatchingServer(StubModel(),
                                       fault_injector=fi_src, **kw)
        tgt = ContinuousBatchingServer(StubModel(),
                                       fault_injector=fi_tgt, **kw)
        got = []
        src.start(); tgt.start()
        try:
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            with pytest.raises(InjectedFault):
                src.migrate_out(rid)                  # gather chaos
            state, payloads = src.migrate_out(rid)    # fault spent
            with pytest.raises(InjectedFault):
                tgt.migrate_in(state, payloads)       # restore chaos
            assert tgt.pool_balance()[1] == 0
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            assert src.stats["migration_fallbacks"] == 1
            assert src.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop()

    def test_target_without_free_slot_refuses_typed(self):
        """A packed target raises ``OutOfPages`` from the normal admit
        path — the router treats it like any restore failure and falls
        back; the source resumes bit-exactly."""
        src, tgt = _servers(2)
        got = []
        src.start(); tgt.start()
        try:
            hold = [tgt.submit(PROMPT, max_new_tokens=BUDGET,
                               on_token=_sink([], dt=0.005))
                    for _ in range(2)]         # both target slots busy
            rid = src.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            with pytest.raises(OutOfPages):
                tgt.migrate_in(state, payloads)
            assert src.migrate_abort(rid) is True
            np.testing.assert_array_equal(
                src.wait(rid, timeout=60),
                stub_tokens(PROMPT, BUDGET))
            for h in hold:
                tgt.wait(h, timeout=60)
            assert src.pool_balance()[1] == 0
            assert tgt.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop()


# ============================================== wire + router + drills
@pytest.mark.net
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestWireMigration:
    @pytest.fixture
    def fleet(self):
        opened = []

        def pair(src_faults=None, **kw):
            src = make_stub_server(num_pages=17, **kw)
            tgt = make_stub_server(num_pages=17, **kw)
            hs = ReplicaHost(src, heartbeat_s=30,
                             fault_injector=src_faults).start()
            ht = ReplicaHost(tgt, heartbeat_s=30).start()
            rs = RemoteReplica(hs.address)
            rt = RemoteReplica(ht.address)
            src.start(); tgt.start()
            opened.extend([(rs, rt), (hs, ht), (src, tgt)])
            return src, tgt, hs, ht, rs, rt

        yield pair
        for rs, rt in opened[0::3]:
            rs.close(); rt.close()
        for hs, ht in opened[1::3]:
            hs.close(); ht.close()
        for src, tgt in opened[2::3]:
            src.stop(); tgt.stop()

    @pytest.mark.parametrize(
        "do_sample",
        [False, pytest.param(True, marks=pytest.mark.slow)],
        ids=["greedy", "sampled"])
    def test_wire_migration_bitexact(self, fleet, do_sample):
        """The tentpole over real sockets: binary page frames out of
        the source host, restored on the target host, the client
        stream re-homed — bit-exact vs a never-migrated oracle with
        zero re-prefill and zero leaks on both processes' pools."""
        kw = dict(do_sample=do_sample)
        if do_sample:
            kw.update(temperature=0.7, top_k=8)
        src, tgt, hs, ht, rs, rt = fleet(src_faults=_throttle_fi(),
                                         **kw)
        oracle = make_stub_server(num_pages=17, **kw)
        oracle.start()
        got = []
        try:
            rid_o = oracle.submit(PROMPT, max_new_tokens=BUDGET, seed=5)
            rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                            on_token=lambda r, t: got.extend(
                                int(x) for x in t))
            _wait(lambda: len(got) >= 6, msg="first streamed tokens")
            state, payloads = rs.migrate_out(rid)
            # client-truth delivery offset rides with the state so the
            # target's mirror starts exactly where this client stopped
            assert state.get("delivered") is not None
            new_rid = rt.migrate_in(
                state, payloads,
                on_token=lambda r, t: got.extend(int(x) for x in t))
            assert rs.migrate_finish(rid) is True
            out = rt.wait(new_rid, timeout=60)
            ref = oracle.wait(rid_o, timeout=60)
            np.testing.assert_array_equal(out, ref)
            if not do_sample:
                np.testing.assert_array_equal(
                    out, stub_tokens(PROMPT, BUDGET))
            _wait(lambda: len(got) >= BUDGET, timeout=10,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            assert src.stats["migrations"] == 1
            assert tgt.stats["migrated_in"] == 1
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            oracle.stop()

    def test_wire_checksum_mismatch_falls_back(self, fleet):
        """A payload corrupted between hosts fails the END-TO-END
        sha256 at restore (typed, over the wire) — the source aborts,
        resumes, and finishes the stream itself; zero leaks."""
        src, tgt, hs, ht, rs, rt = fleet(src_faults=_throttle_fi())
        got = []
        rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                        on_token=lambda r, t: got.extend(
                            int(x) for x in t))
        _wait(lambda: len(got) >= 6, msg="first streamed tokens")
        state, payloads = rs.migrate_out(rid)
        tampered = [np.array(p) for p in payloads]
        tampered[0].flat[0] += 1.0
        with pytest.raises(MigrationError):
            rt.migrate_in(state, tampered)
        assert rs.migrate_abort(rid) is True
        np.testing.assert_array_equal(rs.wait(rid, timeout=60),
                                      stub_tokens(PROMPT, BUDGET))
        assert src.stats["migration_fallbacks"] == 1
        assert tgt.stats["migrated_in"] == 0
        for s in (src, tgt):
            assert s.pool_balance()[1] == 0

    def test_drop_storm_backfill_heals_token_stream(self, fleet):
        """The ``remote._on_tokens`` regression (satellite): a
        ``net.send`` drop storm on the HOST side eats token-push
        frames mid-stream; the client detects each gap and repairs it
        with ``fetch_tokens`` backfill from the host's stash — the
        delivered stream ends COMPLETE and exact, not truncated at the
        first hole."""
        storm = _StormFactory(seed=8)
        fi = FaultInjector(seed=8, enabled=False) \
            .on(NET_SEND, probability=1.0, error=storm)
        src, tgt, hs, ht, rs, rt = fleet(src_faults=fi)
        got = []
        rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                        on_token=lambda r, t: got.extend(
                            int(x) for x in t))
        _wait(lambda: len(got) >= 4, msg="stream started")
        fi.arm()                       # the storm eats mid-stream pushes
        np.testing.assert_array_equal(rs.wait(rid, timeout=60),
                                      stub_tokens(PROMPT, BUDGET))
        _wait(lambda: len(got) >= BUDGET, timeout=15,
              msg="backfill healed the stream")
        assert got == [int(t) for t in stub_tokens(PROMPT, BUDGET)]
        assert storm.drops >= 1        # the storm actually tore frames

    def test_cut_in_callback_window_no_double_delivery(self):
        """The exactly-once seam (ISSUE 20 regression): the server
        fires on_token AFTER releasing its tick lock, so a cut landing
        in that window gathers ``streamed`` ahead of what the wire has
        delivered. ``migrate_out`` must wait for those in-flight pushes
        before snapshotting the client-truth ``delivered`` offset —
        otherwise the target re-streams tokens the source wire is
        about to deliver and the first tokens arrive twice."""
        src = make_stub_server(num_pages=17)
        tgt = make_stub_server(num_pages=17)
        hs = ReplicaHost(src, heartbeat_s=30).start()
        ht = ReplicaHost(tgt, heartbeat_s=30).start()
        rs = RemoteReplica(hs.address)
        rt = RemoteReplica(ht.address)
        tgt.start()
        got = []
        try:
            rid = rs.submit(PROMPT, max_new_tokens=BUDGET, seed=5,
                            on_token=lambda r, t: got.extend(
                                int(x) for x in t))
            # hold the callback flush: tokens land in ``emitted`` (and
            # bump ``streamed``) under the lock while the wire push
            # stays queued — exactly the window a first-token cut hits
            fire = src._fire_callbacks
            src._fire_callbacks = lambda: None
            while not any(st is not None and st.emitted
                          for st in src._slots):
                src.step()
            assert got == []           # nothing crossed the wire yet
            out = {}

            def cut():
                out["state"], out["payloads"] = rs.migrate_out(rid)

            th = threading.Thread(target=cut)
            th.start()
            time.sleep(0.15)           # the cut is inside its catch-up
            src._fire_callbacks = fire  # wait now: release the queued
            fire()                     # pushes
            th.join(timeout=10)
            assert not th.is_alive(), "migrate_out never returned"
            state = out["state"]
            # delivered caught up to server truth: the split point is
            # agreed, so nothing is delivered twice
            assert len(state["delivered"]) == state["streamed"] >= 1
            new_rid = rt.migrate_in(state, out["payloads"],
                                    on_token=lambda r, t: got.extend(
                                        int(x) for x in t))
            rs.migrate_finish(rid)
            np.testing.assert_array_equal(rt.wait(new_rid, timeout=60),
                                          stub_tokens(PROMPT, BUDGET))
            _wait(lambda: len(got) >= BUDGET, timeout=15,
                  msg="stream complete")
            assert got == [int(t) for t in stub_tokens(PROMPT, BUDGET)]
        finally:
            rs.close(); rt.close()
            hs.close(); ht.close()
            src.stop(); tgt.stop()


# ============================================= kill drill (real SIGKILL)
@pytest.mark.net
@pytest.mark.slow
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestMidMigrationKillDrill:
    @pytest.fixture
    def procs(self):
        spawned = []
        yield spawned
        for proc in spawned:
            if proc.is_alive():
                proc.kill()
            proc.join(10)

    def test_sigkill_target_falls_back_zero_leaks_one_flow(
            self, procs, tmp_path):
        """Mid-migration SIGKILL: the target PROCESS dies between the
        source's gather and the restore. The router degrades to
        fallback (``migration_fallbacks`` counts, ``migrations`` does
        not), the source resumes the paused slot and finishes the
        stream BIT-EXACT with zero failed requests and zero leaked
        pages — and the request's journey still renders as ONE
        connected flow across process boundaries in the fleet trace."""
        import json as _json
        import os as _os
        import signal as _signal

        from _remote_stub import make_slow_stub_server
        from paddle_tpu.inference.remote import spawn_replica_host
        from paddle_tpu.inference.router import ReplicaRouter

        server_kw = dict(max_slots=2, max_cache_len=64, page_size=8,
                         num_pages=17, tick_sleep_s=0.01)
        addrs = []
        for _ in range(2):
            proc, addr = spawn_replica_host(
                make_slow_stub_server, server_kw, heartbeat_s=0.05,
                start_server=True)
            procs.append(proc)
            addrs.append(addr)
        reps = [RemoteReplica(addr, call_timeout_s=2.0)
                for addr in addrs]
        router = ReplicaRouter(reps, policy="least_loaded",
                               journeys=True, recorder=True)
        got = []
        try:
            rid = router.submit(PROMPT, max_new_tokens=BUDGET,
                                on_token=lambda r, t: got.extend(
                                    int(x) for x in t))
            _wait(lambda: len(got) >= 6, timeout=120,
                  msg="first streamed tokens from the child")
            with router._lock:
                src_idx = router._routes[rid].idx
            victim = 1 - src_idx
            _os.kill(procs[victim].pid, _signal.SIGKILL)
            procs[victim].join(10)
            moved = router._migrate_live(src_idx)
            assert moved == 0
            assert router._stats["migration_fallbacks"] == 1
            assert router._stats["migrations"] == 0
            out = router.wait(rid, timeout=120)
            np.testing.assert_array_equal(out,
                                          stub_tokens(PROMPT, BUDGET))
            assert got == [int(t) for t in stub_tokens(PROMPT, BUDGET)]
            # zero leaks on the (live) source, measured over the wire
            bal = reps[src_idx].pool_balance()
            assert bal is not None and bal[1] == 0, f"leaked: {bal}"
            # the fallback is attributed on the journey...
            timeline = router.journey(rid)
            assert any(e["phase"] == "migrating"
                       and e.get("fallback") for e in timeline)
            # ...and the journey is ONE connected flow spanning the
            # router pid and the source child pid
            path = tmp_path / "fleet.json"
            router.export_fleet_trace(str(path))
            evs = _json.loads(path.read_text())["traceEvents"]
            flows = [e for e in evs if e.get("cat") == "journey"
                     and e.get("id") == f"r{rid}"]
            assert len(flows) >= 2
            assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
            assert len({e["pid"] for e in flows}) >= 2
        finally:
            router.stop(drain=False, timeout=20, stop_replicas=False)
            for rep in reps:
                rep.close()


# ===================================== seeded chaos storm determinism
@pytest.mark.chaos
@pytest.mark.slow
class TestMigrationChaosStorm:
    """A 25% storm over every ``net.*`` + ``migrate.*`` point the
    migration path crosses, driven SINGLE-THREADED (manual step(),
    socketpair wire) so the fault trace is exact: same seed => same
    trace => same tokens, different seed => different trace."""

    @staticmethod
    def _storm_run(seed):
        fi = FaultInjector(seed=seed) \
            .on(NET_SEND, probability=0.25, error=NetDrop) \
            .on(NET_RECV, probability=0.25, error=NetDrop) \
            .on(NET_PAGE_SEND, probability=0.25, error=NetDrop) \
            .on(MIGRATE_GATHER, probability=0.25) \
            .on(MIGRATE_RESTORE, probability=0.25)
        kw = dict(SERVER_KW)
        src = ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                       **kw)
        tgt = ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                       **kw)
        sa, sb = socket.socketpair()
        a = Connection(sa, fault_injector=fi, peer="src-host")
        b = Connection(sb, peer="tgt-host")
        b._faults = fi
        got = []
        budget = 24
        rid = src.submit(PROMPT, max_new_tokens=budget, seed=5,
                         on_token=lambda r, t: got.extend(
                             int(x) for x in t))

        def step_until(srv, pred, cap=4000):
            for _ in range(cap):
                if pred():
                    return
                srv.step()
            raise AssertionError("stepped past the cap")

        step_until(src, lambda: len(got) >= 6)
        carrier, wait_rid = src, rid
        for _ in range(8):                      # bounded storm retries
            try:
                state, payloads = src.migrate_out(rid)
            except InjectedFault:
                continue                        # gather chaos: slot
            #                                     untouched, try again
            try:
                lost = not a.send({"op": "migrate_in",
                                   "n": len(payloads)})
                for i, p in enumerate(payloads):
                    arr = np.ascontiguousarray(np.stack(p))
                    if not a.send_pages(
                            {"i": i, "shape": list(arr.shape),
                             "dtype": str(arr.dtype)},
                            arr.tobytes()):
                        lost = True
                frames = {}
                header = None
                while True:
                    try:
                        msg = b.recv(timeout=0.1)
                    except TimeoutError:
                        break
                    if "op" in msg:
                        header = msg
                    else:
                        frames[int(msg["i"])] = np.frombuffer(
                            msg["_payload"],
                            dtype=np.dtype(msg["dtype"])) \
                            .reshape(msg["shape"])
                if lost or header is None \
                        or len(frames) != len(payloads):
                    src.migrate_abort(rid)      # frame loss: fallback
                    continue
                new_rid = tgt.migrate_in(
                    state, [frames[i] for i in range(len(payloads))],
                    on_token=lambda r, t: got.extend(
                        int(x) for x in t))
            except (InjectedFault, MigrationError):
                src.migrate_abort(rid)          # restore chaos
                continue
            src.migrate_finish(rid)
            carrier, wait_rid = tgt, new_rid
            break
        step_until(carrier, lambda: len(got) >= budget)
        out = carrier.wait(wait_rid, timeout=5)
        assert src.pool_balance()[1] == 0
        assert tgt.pool_balance()[1] == 0
        a.close()
        b.close()
        return list(fi.trace), [int(t) for t in out], list(got)

    def test_same_seed_same_trace_same_tokens(self):
        t1, out1, got1 = self._storm_run(13)
        t2, out2, got2 = self._storm_run(13)
        t3, _, _ = self._storm_run(14)
        assert t1 == t2                      # identical fault traces
        assert out1 == out2 == got1 == got2  # identical streams
        assert t1 != t3                      # the seed actually steers
        assert out1 == [int(t) for t in stub_tokens(PROMPT, 24)]
        assert len(t1) >= 1                  # the storm actually fired


# ======================================== sharded gather/scatter parity
@pytest.mark.mesh
@pytest.mark.slow
@pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="needs >= 2 forced host devices "
           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
class TestShardedMigration:
    @pytest.fixture(scope="class")
    def llama4(self):
        """llama with 4 kv heads (divisible by mp=2) — real sampling,
        so the restored PRNG chain is exercised for real (the stub's
        closed-form logits cannot distinguish a mis-primed key)."""
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=1,
                          num_heads=8, num_kv_heads=4,
                          intermediate_size=128, max_seq_len=128)
        pt.seed(21)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    @pytest.mark.parametrize("src_mp,tgt_mp", [(2, 1), (1, 2)],
                             ids=["mp2_to_mp1", "mp1_to_mp2"])
    def test_cross_topology_migration_bitexact(self, llama4, src_mp,
                                               tgt_mp):
        """Pages gathered per shard on an mp=2 mesh restore into a
        single-device pool bit-exactly, and vice versa: the wire
        payload is topology-neutral host arrays, so migration crosses
        tensor-parallel layouts without a re-prefill."""
        from jax.sharding import Mesh

        def mesh(n):
            return Mesh(np.array(jax.devices()[:n]), ("mp",)) \
                if n > 1 else None

        kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                  page_size=8, num_pages=24, do_sample=True,
                  temperature=0.8, top_k=20)
        src = ContinuousBatchingServer(llama4, mesh=mesh(src_mp), **kw)
        tgt = ContinuousBatchingServer(llama4, mesh=mesh(tgt_mp), **kw)
        oracle = ContinuousBatchingServer(llama4, **kw)
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 256, (9,)).astype(np.int32)
        budget = 24
        got = []
        src.start(); tgt.start(); oracle.start()
        try:
            rid_o = oracle.submit(prompt, max_new_tokens=budget,
                                  seed=31)
            rid = src.submit(prompt, max_new_tokens=budget, seed=31,
                             on_token=_sink(got))
            _wait(lambda: len(got) >= 6, timeout=120,
                  msg="first streamed tokens")
            state, payloads = src.migrate_out(rid)
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got))
            src.migrate_finish(rid)
            out = tgt.wait(new_rid, timeout=120)
            ref = oracle.wait(rid_o, timeout=120)
            np.testing.assert_array_equal(out, ref)
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop(); oracle.stop()

    @pytest.mark.parametrize("src_mp,tgt_mp", [(2, 1), (1, 2)],
                             ids=["mp2_to_mp1", "mp1_to_mp2"])
    def test_cross_topology_prefill_handoff_bitexact(self, llama4,
                                                     src_mp, tgt_mp):
        """The ISSUE-20 cut of the same drill: migrate a slot whose
        ``emitted`` is still EMPTY (mid-prefill) across tensor-parallel
        layouts — the target finishes the remaining prompt chunks and
        samples the first token from the restored seed, bit-exact vs
        the never-handed-off oracle, with only the unfinished tail
        re-prefilled."""
        from jax.sharding import Mesh

        def mesh(n):
            return Mesh(np.array(jax.devices()[:n]), ("mp",)) \
                if n > 1 else None

        kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                  page_size=8, num_pages=24, do_sample=True,
                  temperature=0.8, top_k=20,
                  prefill_tokens_per_tick=8)
        src = ContinuousBatchingServer(llama4, mesh=mesh(src_mp),
                                       role="prefill", **kw)
        tgt = ContinuousBatchingServer(llama4, mesh=mesh(tgt_mp),
                                       role="decode", **kw)
        oracle = ContinuousBatchingServer(llama4, **kw)
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 256, (20,)).astype(np.int32)
        budget = 16
        got = []
        oracle.start()
        try:
            rid_o = oracle.submit(prompt, max_new_tokens=budget,
                                  seed=31)
            rid = src.submit(prompt, max_new_tokens=budget, seed=31,
                             on_token=_sink(got, dt=0))
            src.step()                   # admit + first chunk: 8 of 20
            state, payloads = src.migrate_out(rid)
            assert state["phase"] == "prefill"
            assert int(state["filled"]) == 8
            new_rid = tgt.migrate_in(state, payloads,
                                     on_token=_sink(got, dt=0))
            src.migrate_finish(rid)
            while tgt._busy_locked():
                tgt.step()
            out = tgt.wait(new_rid, timeout=120)
            ref = oracle.wait(rid_o, timeout=120)
            np.testing.assert_array_equal(out, ref)
            assert got == [int(t) for t in ref]
            assert tgt.stats["prefill_tokens"] == len(prompt) - 8
            assert tgt.stats["admissions"] == 1   # the TARGET activates
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            src.stop(); tgt.stop(); oracle.stop()


# ================================ prefill->decode handoff (ISSUE 20)
HANDOFF_KW = dict(SERVER_KW, prefill_tokens_per_tick=8)
LONG_PROMPT = (np.arange(1, 25, dtype=np.int32) % 13)   # 24 = 3 chunks
HBUDGET = 12         # 24-token prompt + 12 <= max_cache_len 64
SHORT_PROMPT = np.asarray([3, 1, 4], np.int32)


def _step_until_idle(*servers, cap=20000):
    for _ in range(cap):
        busy = False
        for srv in servers:
            if srv._busy_locked():
                srv.step()
                busy = True
        if not busy:
            return
    raise AssertionError("servers never went idle")


def _oracle_tokens(budget=HBUDGET, seed=5, prompt=None, **kw):
    """Single-replica never-handed-off reference stream."""
    oracle = ContinuousBatchingServer(StubModel(),
                                      **dict(HANDOFF_KW, **kw))
    rid = oracle.submit(LONG_PROMPT if prompt is None else prompt,
                        max_new_tokens=budget, seed=seed)
    _step_until_idle(oracle)
    return oracle.wait(rid, timeout=5)


class TestPrefillHandoff:
    """The empty-``emitted`` handoff matrix: migrating a slot that has
    not sampled its first token IS a prefill->decode handoff (the
    PR-18 refusal seam, lifted by ISSUE 20)."""

    @pytest.mark.parametrize("do_sample", [False, True],
                             ids=["greedy", "sampled"])
    def test_empty_emitted_handoff_bitexact(self, do_sample):
        """Mid-prefill migrate_out (emitted == []) restores on a decode
        specialist which finishes the remaining chunks and samples the
        first token from the restored seed — bit-exact vs the oracle,
        only the unfinished tail re-prefilled, zero leaks."""
        kw = dict(do_sample=do_sample)
        if do_sample:
            kw.update(temperature=0.7, top_k=8)
        src = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **dict(HANDOFF_KW, **kw))
        tgt = ContinuousBatchingServer(StubModel(), role="decode",
                                       **dict(HANDOFF_KW, **kw))
        got = []
        rid = src.submit(LONG_PROMPT, max_new_tokens=HBUDGET, seed=5,
                         on_token=_sink(got, dt=0))
        src.step(); src.step()          # admit + chunks 1,2: 16 of 24
        state, payloads = src.migrate_out(rid)
        assert state["phase"] == "prefill"
        assert state["emitted"] == [] or len(state["emitted"]) == 0
        assert int(state["filled"]) == 16
        assert len(payloads) == 2       # 16 written rows = 2 full pages
        new_rid = tgt.migrate_in(state, payloads,
                                 on_token=_sink(got, dt=0))
        src.migrate_finish(rid)
        _step_until_idle(tgt)
        out = tgt.wait(new_rid, timeout=5)
        ref = _oracle_tokens(**kw)
        np.testing.assert_array_equal(out, ref)
        assert got == [int(t) for t in ref]
        # zero RE-prefill: the target only ran the tokens the source
        # had not reached (24 - 16), never the handed-off 16
        assert src.stats["prefill_tokens"] == 16
        assert tgt.stats["prefill_tokens"] == len(LONG_PROMPT) - 16
        assert tgt.stats["admissions"] == 1   # the TARGET activates
        assert src.stats["migrations"] == 1
        assert tgt.stats["migrated_in"] == 1
        for s in (src, tgt):
            assert s.pool_balance()[1] == 0

    def test_staged_pipelined_handoff_bitexact(self):
        """The pipelined protocol end to end, deterministically
        step-driven: partial frames stream completed chunks while the
        source keeps prefilling; the closing pull carries only the
        unshipped tail; the commit launches decode — bit-exact, every
        page shipped exactly once."""
        src = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **HANDOFF_KW)
        tgt = ContinuousBatchingServer(StubModel(), role="decode",
                                       **HANDOFF_KW)
        got = []
        rid = src.submit(LONG_PROMPT, max_new_tokens=HBUDGET, seed=5,
                         on_token=_sink(got, dt=0))
        src.step()                           # chunk 1: 8 of 24 filled
        frag, payloads = src.migrate_out(rid, partial=True)
        assert frag["partial"] and frag["phase"] == "prefill"
        assert frag["base"] == 0 and len(payloads) == 1
        handle = tgt.migrate_in_begin(
            {"rid": int(rid), "ids": LONG_PROMPT,
             "prompt_len": len(LONG_PROMPT), "budget": HBUDGET,
             "seed": 5, "page_size": 8, "phase": "prefill"})
        assert tgt.migrate_in_pages(handle, 0, payloads,
                                    frag["sha256"]) == 1
        src.step()                           # chunk 2: 16 filled
        frag2, payloads2 = src.migrate_out(rid, partial=True)
        assert frag2["base"] == 1 and len(payloads2) == 1
        tgt.migrate_in_pages(handle, 1, payloads2, frag2["sha256"])
        # closing pull: everything from page 2 on (the incomplete
        # third page has nothing written yet -> zero tail payloads)
        state, tail = src.migrate_out(rid, from_page=2)
        assert state["base"] == 2 and tail == []
        new_rid = tgt.migrate_in_commit(handle, state, tail,
                                        on_token=_sink(got, dt=0))
        src.migrate_finish(rid)
        _step_until_idle(tgt)
        out = tgt.wait(new_rid, timeout=5)
        np.testing.assert_array_equal(out, _oracle_tokens())
        assert got == [int(t) for t in out]
        assert tgt.stats["prefill_tokens"] == len(LONG_PROMPT) - 16
        assert src.stats["handoff_pages_out"] == 2
        assert tgt.stats["handoff_pages_in"] == 2
        for s in (src, tgt):
            assert s.pool_balance()[1] == 0

    def test_refusal_matrix_typed(self):
        """Role and protocol refusals are typed ``MigrationError``s
        that leave both ends untouched: a prefill specialist refuses
        decode-phase admissions; a pipelined state (base > 0) refuses
        the one-shot ``migrate_in``; an unknown staging handle
        refuses page frames."""
        src = ContinuousBatchingServer(StubModel(), **HANDOFF_KW)
        pre = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **HANDOFF_KW)
        got = []
        rid = src.submit(PROMPT, max_new_tokens=HBUDGET, seed=5,
                         on_token=_sink(got, dt=0))
        for _ in range(50):                # well into decode
            src.step()
            if len(got) >= 4:
                break
        state, payloads = src.migrate_out(rid)
        assert state["phase"] == "decode"
        with pytest.raises(MigrationError, match="role 'prefill'"):
            pre.migrate_in(state, payloads)
        with pytest.raises(MigrationError, match="migrate_in_begin"):
            ContinuousBatchingServer(StubModel(), **HANDOFF_KW) \
                .migrate_in(dict(state, base=2), payloads)
        with pytest.raises(MigrationError, match="staged"):
            pre.migrate_in_pages(999, 0, payloads)
        assert pre.stats["migrated_in"] == 0
        assert pre.pool_balance()[1] == 0
        # the refused source resumes and finishes bit-exact
        assert src.migrate_abort(rid) is True
        _step_until_idle(src)
        np.testing.assert_array_equal(
            src.wait(rid, timeout=5),
            _oracle_tokens(prompt=PROMPT))
        assert src.pool_balance()[1] == 0

    def test_midprefill_abort_resumes_bitexact(self):
        """migrate_abort on a paused MID-PREFILL slot re-queues it on
        the prefill fifo exactly where it stopped — the source
        finishes the remaining chunks and the stream is bit-exact."""
        src = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **HANDOFF_KW)
        got = []
        rid = src.submit(LONG_PROMPT, max_new_tokens=HBUDGET, seed=5,
                         on_token=_sink(got, dt=0))
        src.step()
        state, _ = src.migrate_out(rid)
        assert state["phase"] == "prefill"
        assert src.migrate_abort(rid) is True
        _step_until_idle(src)
        np.testing.assert_array_equal(src.wait(rid, timeout=5),
                                      _oracle_tokens())
        assert src.stats["prefill_tokens"] == len(LONG_PROMPT)
        assert src.stats["migration_fallbacks"] == 1
        assert src.pool_balance()[1] == 0

    def test_staged_abort_leaks_nothing(self):
        """Aborting an open staging releases the placeholder's pages
        (no prefix-cache donation of garbage rows) and is
        idempotent."""
        tgt = ContinuousBatchingServer(StubModel(), role="decode",
                                       **HANDOFF_KW)
        free0 = tgt.pool_balance()[0]
        handle = tgt.migrate_in_begin(
            {"rid": 1, "ids": LONG_PROMPT,
             "prompt_len": len(LONG_PROMPT), "budget": HBUDGET,
             "seed": 5, "page_size": 8, "phase": "prefill"})
        assert tgt.pool_balance()[0] < free0      # pages reserved
        assert tgt.migrate_in_abort(handle) is True
        assert tgt.migrate_in_abort(handle) is False   # idempotent
        assert tgt.pool_balance()[0] == free0
        assert tgt.pool_balance()[1] == 0

    def _drive_router(self, router, reps, timeout=90):
        """Threaded-pump-aware drive: step serving replicas while the
        router's handoff pump runs in the background."""
        deadline = time.monotonic() + timeout
        idle = 0
        while time.monotonic() < deadline:
            router.poll()
            busy = False
            for rep in reps:
                if rep.health == "dead":
                    continue
                if rep.queue_depth() or rep.in_flight():
                    rep.step()
                    busy = True
            idle = 0 if busy else idle + 1
            if idle >= 3:
                return
            time.sleep(0.0005)
        raise AssertionError("router drive did not converge")

    def test_disaggregated_router_handoff_end_to_end(self):
        """placement="disaggregated" end to end: the long prompt lands
        on the prefill specialist, the pump hands it to the decode
        specialist (zero re-prefill), the journey crosses a "handoff"
        phase, and the short prompt bypasses the specialist
        entirely."""
        from paddle_tpu.inference.router import ReplicaRouter
        pre = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **HANDOFF_KW)
        dec = ContinuousBatchingServer(StubModel(), role="decode",
                                       **HANDOFF_KW)
        router = ReplicaRouter([pre, dec], placement="disaggregated",
                               disagg_prefill_min_tokens=16,
                               journeys=True, recorder=True)
        got = []
        rid = router.submit(LONG_PROMPT, max_new_tokens=HBUDGET,
                            seed=5, on_token=_sink(got))
        self._drive_router(router, [pre, dec])
        out = router.wait(rid, timeout=60)
        np.testing.assert_array_equal(out, _oracle_tokens())
        assert got == [int(t) for t in out]
        assert router.stats["handoffs"] == 1
        assert router.stats["handoff_fallbacks"] == 0
        assert dec.stats["prefill_tokens"] == 0       # zero re-prefill
        assert pre.stats["prefill_tokens"] == len(LONG_PROMPT)
        timeline = router.journey(rid)
        assert any(e["phase"] == "handoff" for e in timeline)
        # short prompts skip the specialist: decode-local, no handoff
        rid2 = router.submit(SHORT_PROMPT, max_new_tokens=4)
        self._drive_router(router, [pre, dec])
        np.testing.assert_array_equal(
            router.wait(rid2, timeout=30),
            _oracle_tokens(budget=4, seed=None, prompt=SHORT_PROMPT))
        assert router.stats["handoffs"] == 1          # unchanged
        assert router.stats["routed"] == [1, 1]       # short went
        #                                               decode-local
        for s in (pre, dec):
            assert s.pool_balance()[1] == 0

    def test_all_specialists_down_degrades_to_hybrid(self):
        """A dead prefill specialist does not strand long prompts:
        phase ordering degrades to any serving replica and the decode
        specialist serves the whole request itself."""
        from paddle_tpu.inference.router import ReplicaRouter
        pre = ContinuousBatchingServer(StubModel(), role="prefill",
                                       **HANDOFF_KW)
        dec = ContinuousBatchingServer(StubModel(), role="decode",
                                       **HANDOFF_KW)
        router = ReplicaRouter([pre, dec], placement="disaggregated",
                               disagg_prefill_min_tokens=16)
        pre.stop(drain=False)
        rid = router.submit(LONG_PROMPT, max_new_tokens=HBUDGET, seed=5)
        self._drive_router(router, [pre, dec])
        np.testing.assert_array_equal(router.wait(rid, timeout=60),
                                      _oracle_tokens())
        assert router.stats["routed"][1] == 1
        assert router.stats["handoffs"] == 0
        assert dec.stats["prefill_tokens"] == len(LONG_PROMPT)


class _PageStorm:
    """Capped ``net.page_send`` drop storm: a seeded 25% of page
    frames vanish mid-wire (up to ``max_drops``), the rest ride a
    pacing delay so the prefill stays stretched while the pump pulls
    partial batches."""

    def __init__(self, seed, p_drop=0.25, max_drops=4):
        self.rng = random.Random(seed)
        self.p_drop, self.max_drops = p_drop, max_drops
        self.drops = 0

    def __call__(self):
        if self.drops < self.max_drops \
                and self.rng.random() < self.p_drop:
            self.drops += 1
            return NetDrop("page storm")
        return _Throttle("pacing")


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestPartialHandoffStorm:
    def test_partial_frames_survive_page_send_storm(self):
        """Chunked partial-handoff frame ordering under a 25%
        ``net.page_send`` storm: dropped frames surface as holes in
        the pulled batch (never exceptions), holes are simply not
        forwarded, and the closing pull re-ships everything above the
        delivered contiguous prefix — the handoff still lands
        bit-exact with every page landing exactly once."""
        from _remote_stub import make_slow_stub_server
        storm = _PageStorm(seed=8)   # seeded to tear frames 1,3,5,6
        fi = FaultInjector(seed=8) \
            .on(NET_PAGE_SEND, probability=1.0, error=storm)
        kw = dict(max_slots=2, max_cache_len=96, page_size=8,
                  num_pages=24, prefill_tokens_per_tick=8)
        src = make_slow_stub_server(tick_sleep_s=0.03, role="prefill",
                                    **kw)
        tgt = make_slow_stub_server(tick_sleep_s=0.0, role="decode",
                                    **kw)
        hs = ReplicaHost(src, heartbeat_s=30,
                         fault_injector=fi).start()
        ht = ReplicaHost(tgt, heartbeat_s=30).start()
        rs, rt = RemoteReplica(hs.address), RemoteReplica(ht.address)
        src.start(); tgt.start()
        prompt = (np.arange(1, 41, dtype=np.int32) % 13)   # 5 pages
        budget = 8
        got = []
        collect = lambda r, t: got.extend(int(x) for x in t)  # noqa: E731
        try:
            assert rs.role == "prefill" and rt.role == "decode"
            rid = rs.submit(prompt, max_new_tokens=budget, seed=5,
                            on_token=collect)
            delivered = set()
            handle = None
            pulled_holes = 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    frag, payloads = rs.migrate_out(rid, partial=True)
                except MigrationError:
                    time.sleep(0.005)
                    continue
                if frag["phase"] != "prefill":
                    break
                if payloads:
                    if handle is None:
                        handle = rt.migrate_in_begin(
                            {"rid": int(rid), "ids": prompt,
                             "prompt_len": len(prompt),
                             "budget": budget, "seed": 5,
                             "page_size": 8, "phase": "prefill"})
                    base0 = int(frag["base"])
                    shas = frag["sha256"]
                    i = 0
                    while i < len(payloads):
                        if payloads[i] is None:
                            pulled_holes += 1
                            i += 1
                            continue
                        j = i
                        while j < len(payloads) \
                                and payloads[j] is not None:
                            j += 1
                        landed = rt.migrate_in_pages(
                            handle, base0 + i, payloads[i:j],
                            shas[i:j])
                        delivered.update(int(p) for p in landed)
                        i = j
                time.sleep(0.005)
            else:
                raise AssertionError("source never reached decode")
            k = 0
            while k in delivered:
                k += 1
            new_rid = None
            for _ in range(6):              # storm-bounded retries
                try:
                    state, tail = rs.migrate_out(rid, from_page=k)
                except MigrationError:
                    time.sleep(0.01)
                    continue
                if any(p is None for p in tail):
                    rs.migrate_abort(rid)
                    continue
                try:
                    if handle is not None:
                        new_rid = rt.migrate_in_commit(
                            handle, state, tail, on_token=collect)
                    else:
                        new_rid = rt.migrate_in(state, tail,
                                                on_token=collect)
                except MigrationError:
                    rs.migrate_abort(rid)
                    continue
                break
            assert new_rid is not None, "handoff never committed"
            rs.migrate_finish(rid)
            out = rt.wait(new_rid, timeout=60)
            ref = stub_tokens(prompt, budget)
            np.testing.assert_array_equal(out, ref)
            _wait(lambda: len(got) >= budget, timeout=15,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            assert storm.drops >= 1         # the storm actually tore
            assert tgt.stats["prefill_tokens"] == 0
            assert tgt.stats["admissions"] == 0
            for s in (src, tgt):
                assert s.pool_balance()[1] == 0
        finally:
            rs.close(); rt.close()
            hs.close(); ht.close()
            src.stop(); tgt.stop()


@pytest.mark.net
@pytest.mark.slow
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestPrefillSpecialistKillDrill:
    @pytest.fixture
    def procs(self):
        spawned = []
        yield spawned
        for proc in spawned:
            if proc.is_alive():
                proc.kill()
            proc.join(10)

    def test_sigkill_prefill_specialist_mid_handoff(self, procs,
                                                    tmp_path):
        """SIGKILL the prefill specialist PROCESS mid-prompt: the
        supervisor evacuates, the prompt requeues on the decode
        specialist via the normal path (degraded hybrid — it prefills
        itself) and finishes BIT-EXACT with zero lost requests, zero
        leaked pages on the surviving end, and the journey rendering
        as one connected flow across pids."""
        import json as _json
        import os as _os
        import signal as _signal

        from _remote_stub import make_slow_stub_server
        from paddle_tpu.inference.remote import spawn_replica_host
        from paddle_tpu.inference.router import ReplicaRouter

        base_kw = dict(max_slots=2, max_cache_len=96, page_size=8,
                       num_pages=24, tick_sleep_s=0.01,
                       prefill_tokens_per_tick=8)
        addrs = []
        for role in ("prefill", "decode"):
            proc, addr = spawn_replica_host(
                make_slow_stub_server, dict(base_kw, role=role),
                heartbeat_s=0.05, start_server=True)
            procs.append(proc)
            addrs.append(addr)
        reps = [RemoteReplica(addr, call_timeout_s=2.0)
                for addr in addrs]
        router = ReplicaRouter(reps, placement="disaggregated",
                               disagg_prefill_min_tokens=16,
                               journeys=True, recorder=True)
        prompt = (np.arange(1, 41, dtype=np.int32) % 13)
        budget = 16
        got = []
        try:
            _wait(lambda: reps[0].role == "prefill"
                  and reps[1].role == "decode", timeout=60,
                  msg="roles ride the heartbeat digests")
            router.start(poll_interval=0.02, start_replicas=False)
            rid = router.submit(prompt, max_new_tokens=budget,
                                on_token=lambda r, t: got.extend(
                                    int(x) for x in t))
            with router._lock:
                assert router._routes[rid].idx == 0   # specialist won
            time.sleep(0.04)             # mid-prompt, pump possibly
            #                              mid-partial-batch
            _os.kill(procs[0].pid, _signal.SIGKILL)
            procs[0].join(10)
            out = router.wait(rid, timeout=120)
            ref = stub_tokens(prompt, budget)
            np.testing.assert_array_equal(out, ref)
            _wait(lambda: len(got) >= budget, timeout=15,
                  msg="stream drained")
            assert got == [int(t) for t in ref]
            # zero leaks on the surviving decode end (any staged
            # placeholder from a mid-flight pump was aborted)
            _wait(lambda: (reps[1].pool_balance() or (0, 1))[1] == 0,
                  timeout=30, msg="decode pool settles to zero live")
            # one connected flow across the router pid and >= 1 child
            path = tmp_path / "fleet.json"
            router.export_fleet_trace(str(path))
            evs = _json.loads(path.read_text())["traceEvents"]
            flows = [e for e in evs if e.get("cat") == "journey"
                     and e.get("id") == f"r{rid}"]
            assert len(flows) >= 2
            assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
            assert len({e["pid"] for e in flows}) >= 2
        finally:
            router.stop(drain=False, timeout=20, stop_replicas=False)
            for rep in reps:
                rep.close()
