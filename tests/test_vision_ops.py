"""vision.ops detection suite (reference python/paddle/vision/ops.py)."""
import numpy as np
import pytest

import paddle_tpu as pt

V = pt.vision.ops


@pytest.fixture()
def boxes():
    return np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                    "float32")


def test_nms_suppresses_overlaps(boxes):
    keep = V.nms(pt.to_tensor(boxes), 0.5,
                 pt.to_tensor(np.array([0.9, 0.8, 0.7], "float32"))).numpy()
    assert keep.tolist() == [0, 2]


def test_nms_category_aware(boxes):
    cats = np.array([0, 1, 0], "int64")
    keep = V.nms(pt.to_tensor(boxes), 0.5,
                 pt.to_tensor(np.array([0.9, 0.8, 0.7], "float32")),
                 category_idxs=pt.to_tensor(cats), categories=[0, 1]).numpy()
    assert sorted(keep.tolist()) == [0, 1, 2]  # overlap is cross-category


def test_roi_align_constant_and_grad():
    feat = np.ones((1, 3, 8, 8), "float32") * 5
    rois = np.array([[1.0, 1.0, 6.0, 6.0]], "float32")
    x = pt.to_tensor(feat, stop_gradient=False)
    out = V.roi_align(x, pt.to_tensor(rois),
                      pt.to_tensor(np.array([1], "int32")), 2)
    np.testing.assert_allclose(out.numpy(), 5.0, rtol=1e-5)
    out.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()
    assert abs(x.grad.numpy().sum() - 12.0) < 0.1  # channels x bins, avg weights sum to 1


def test_roi_pool_ramp_max():
    ramp = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    out = V.roi_pool(pt.to_tensor(ramp),
                     pt.to_tensor(np.array([[0, 0, 8, 8]], "float32")),
                     pt.to_tensor(np.array([1], "int32")), 2)
    assert float(out.numpy().max()) == 63.0


def test_psroi_pool_shape():
    feat = pt.to_tensor(np.random.randn(1, 8, 8, 8).astype("float32"))
    out = V.psroi_pool(feat, pt.to_tensor(np.array([[0, 0, 8, 8]],
                                                   "float32")),
                       pt.to_tensor(np.array([1], "int32")), 2)
    assert out.shape == [1, 2, 2, 2]


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "float32")
    var = np.ones((2, 4), "float32")
    targets = np.array([[1, 1, 9, 9], [6, 6, 14, 14]], "float32")
    enc = V.box_coder(pt.to_tensor(priors), pt.to_tensor(var),
                      pt.to_tensor(targets))
    dec = V.box_coder(pt.to_tensor(priors), pt.to_tensor(var), enc,
                      code_type="decode_center_size")
    np.testing.assert_allclose(dec.numpy(), targets, rtol=1e-4, atol=1e-3)


def test_yolo_box_and_loss():
    x = np.random.randn(1, 3 * 7, 4, 4).astype("float32")
    yb, ys = V.yolo_box(pt.to_tensor(x),
                        pt.to_tensor(np.array([[64, 64]], "int32")),
                        anchors=[10, 13, 16, 30, 33, 23], class_num=2,
                        conf_thresh=0.01)
    assert yb.shape == [1, 48, 4] and ys.shape == [1, 48, 2]
    xin = pt.to_tensor(x * 0.1, stop_gradient=False)
    loss = V.yolo_loss(xin,
                       pt.to_tensor(np.array([[[0.5, 0.5, 0.2, 0.2]]],
                                             "float32")),
                       pt.to_tensor(np.array([[1]], "int64")),
                       anchors=[10, 13, 16, 30, 33, 23],
                       anchor_mask=[0, 1, 2], class_num=2,
                       ignore_thresh=0.5, downsample_ratio=32)
    loss.backward()
    assert np.isfinite(xin.grad.numpy()).all()


def test_fpn_and_proposals(boxes):
    rois = np.array([[0, 0, 16, 16], [0, 0, 100, 100], [0, 0, 300, 300]],
                    "float32")
    outs, restore, _ = V.distribute_fpn_proposals(pt.to_tensor(rois),
                                                  2, 5, 4, 224)
    assert sum(o.shape[0] for o in outs) == 3
    sc = np.random.rand(1, 3, 4, 4).astype("float32")
    deltas = np.random.randn(1, 12, 4, 4).astype("float32") * 0.1
    anchors = np.random.rand(4, 4, 3, 4).astype("float32") * 20
    anchors[..., 2:] += 25
    var = np.ones((4, 4, 3, 4), "float32") * 0.1
    rois2, rsc = V.generate_proposals(
        pt.to_tensor(sc), pt.to_tensor(deltas),
        pt.to_tensor(np.array([[64, 64, 1]], "float32")),
        pt.to_tensor(anchors), pt.to_tensor(var), post_nms_top_n=5)
    assert rois2.shape[1] == 4 and rois2.shape[0] <= 5


def test_image_io_roundtrip(tmp_path):
    from PIL import Image
    arr = (np.random.rand(10, 12, 3) * 255).astype("uint8")
    p = str(tmp_path / "img.jpg")
    Image.fromarray(arr).save(p, quality=95)
    img = V.decode_jpeg(V.read_file(p))
    assert img.shape == [3, 10, 12]


def test_deform_conv_zero_offset_equals_conv():
    from paddle_tpu.nn import functional as F
    dc = V.DeformConv2D(2, 4, 3, padding=1)
    x = pt.to_tensor(np.random.randn(1, 2, 6, 6).astype("float32"))
    off = pt.to_tensor(np.zeros((1, 18, 6, 6), "float32"))
    np.testing.assert_allclose(
        dc(x, off).numpy(),
        F.conv2d(x, dc.weight, dc.bias, padding=1).numpy(),
        rtol=1e-3, atol=1e-4)


def test_prior_box_and_matrix_nms(boxes):
    pb, pv = V.prior_box(pt.to_tensor(np.zeros((1, 3, 4, 4), "float32")),
                         pt.to_tensor(np.zeros((1, 3, 32, 32), "float32")),
                         min_sizes=[8.0], aspect_ratios=[1.0, 2.0],
                         flip=True)
    assert pb.shape[:2] == [4, 4] and pb.shape[3] == 4
    det, idx, num = V.matrix_nms(
        pt.to_tensor(boxes[None]),
        pt.to_tensor(np.random.rand(1, 3, 3).astype("float32")),
        0.1, 0.05, 10, 5, return_index=True)
    assert det.shape[1] == 6


def test_nn_utils_weight_norm_and_clip():
    import paddle_tpu.nn as nn
    lin = nn.Linear(4, 6)
    w0 = lin.weight.numpy().copy()
    nn.utils.weight_norm(lin, "weight", dim=0)
    x = pt.to_tensor(np.random.randn(3, 4).astype("float32"))
    np.testing.assert_allclose(lin(x).numpy(),
                               x.numpy() @ w0 + lin.bias.numpy(),
                               rtol=1e-4, atol=1e-5)
    nn.utils.remove_weight_norm(lin)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                               atol=1e-6)
    p = pt.to_tensor(np.ones(4, "float32"), stop_gradient=False)
    (p * 10).sum().backward()
    nn.utils.clip_grad_norm_([p], max_norm=1.0)
    np.testing.assert_allclose(float(np.linalg.norm(p.grad.numpy())), 1.0,
                               rtol=1e-3)


def test_roi_pool_exact_large_bins():
    ramp = np.arange(256, dtype="float32").reshape(1, 1, 16, 16)
    out = V.roi_pool(pt.to_tensor(ramp),
                     pt.to_tensor(np.array([[0, 0, 16, 16]], "float32")),
                     pt.to_tensor(np.array([1], "int32")), 1)
    assert float(out.numpy().max()) == 255.0


def test_psroi_pool_channel_major():
    C, oh, ow = 8, 2, 2
    feat = np.zeros((1, C, 4, 4), "float32")
    for ch in range(C):
        feat[0, ch] = ch
    ps = V.psroi_pool(pt.to_tensor(feat),
                      pt.to_tensor(np.array([[0, 0, 4, 4]], "float32")),
                      pt.to_tensor(np.array([1], "int32")), 2)
    want = np.zeros((1, C // 4, oh, ow), "float32")
    for c in range(C // 4):
        for i in range(oh):
            for j in range(ow):
                want[0, c, i, j] = (c * oh + i) * ow + j
    np.testing.assert_allclose(ps.numpy(), want)


def test_box_coder_axis1_decode():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "float32")
    var = np.ones((2, 4), "float32")
    tb = np.zeros((3, 2, 4), "float32")   # zero deltas -> identity decode
    dec = V.box_coder(pt.to_tensor(priors), pt.to_tensor(var),
                      pt.to_tensor(tb), code_type="decode_center_size",
                      axis=1)
    for n in range(3):
        np.testing.assert_allclose(dec.numpy()[n], priors, rtol=1e-5)


def test_generate_proposals_score_box_pairing():
    sc = np.zeros((1, 2, 2, 2), "float32")
    sc[0, 1, 0, 1] = 0.9          # best: anchor 1 at cell (0, 1)
    deltas = np.zeros((1, 8, 2, 2), "float32")
    anchors = np.zeros((2, 2, 2, 4), "float32")
    v = 0
    for i in range(2):
        for j in range(2):
            for a in range(2):
                anchors[i, j, a] = [v, v, v + 5, v + 5]
                v += 1
    var = np.ones((2, 2, 2, 4), "float32")
    rois, rsc = V.generate_proposals(
        pt.to_tensor(sc), pt.to_tensor(deltas),
        pt.to_tensor(np.array([[64, 64, 1]], "float32")),
        pt.to_tensor(anchors), pt.to_tensor(var), min_size=0.0,
        post_nms_top_n=1)
    np.testing.assert_allclose(rois.numpy()[0], [3, 3, 8, 8], atol=1e-4)
    assert float(rsc.numpy()[0]) == np.float32(0.9)


def test_deform_conv_groups():
    from paddle_tpu.nn import functional as F
    x = pt.to_tensor(np.random.randn(1, 2, 6, 6).astype("float32"))
    dcw = np.random.randn(4, 2, 3, 3).astype("float32")
    off2 = pt.to_tensor(np.zeros((1, 2 * 2 * 9, 4, 4), "float32"))
    out = V.deform_conv2d(x, off2, pt.to_tensor(dcw), deformable_groups=2)
    np.testing.assert_allclose(
        out.numpy(), F.conv2d(x, pt.to_tensor(dcw)).numpy(),
        rtol=1e-3, atol=1e-4)
    gw = np.random.randn(4, 1, 3, 3).astype("float32")
    off1 = pt.to_tensor(np.zeros((1, 18, 4, 4), "float32"))
    outg = V.deform_conv2d(x, off1, pt.to_tensor(gw), groups=2)
    np.testing.assert_allclose(
        outg.numpy(), F.conv2d(x, pt.to_tensor(gw), groups=2).numpy(),
        rtol=1e-3, atol=1e-4)


def test_decode_jpeg_unchanged_grayscale(tmp_path):
    from PIL import Image
    g = (np.random.rand(6, 7) * 255).astype("uint8")
    p = str(tmp_path / "g.jpg")
    Image.fromarray(g, mode="L").save(p)
    img = V.decode_jpeg(V.read_file(p))
    assert img.shape == [1, 6, 7]


def test_matrix_nms_actually_suppresses():
    boxes = np.array([[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                      [20, 20, 30, 30]], "float32")
    sc = np.zeros((1, 2, 3), "float32")
    sc[0, 1] = [0.9, 0.85, 0.8]
    det, idx, num = V.matrix_nms(pt.to_tensor(boxes[None]),
                                 pt.to_tensor(sc), 0.1, 0.5, 10, 5,
                                 return_index=True)
    assert det.shape[0] == 2   # overlapping duplicate decays out


def test_prior_box_order_flag():
    args = (pt.to_tensor(np.zeros((1, 3, 1, 1), "float32")),
            pt.to_tensor(np.zeros((1, 3, 32, 32), "float32")))
    kw = dict(min_sizes=[8.0], max_sizes=[16.0], aspect_ratios=[1.0, 2.0])
    b_def = V.prior_box(*args, **kw)[0].numpy().reshape(-1, 4)
    b_mm = V.prior_box(*args, min_max_aspect_ratios_order=True,
                       **kw)[0].numpy().reshape(-1, 4)
    w_def = (b_def[:, 2] - b_def[:, 0]) * 32
    w_mm = (b_mm[:, 2] - b_mm[:, 0]) * 32
    maxw = (8 * 16) ** 0.5
    assert abs(w_def[-1] - maxw) < 1e-2     # default: max box last
    assert abs(w_mm[1] - maxw) < 1e-2       # mm order: max box second


def test_yolo_box_zeroes_scores_and_iou_aware():
    x = np.random.randn(1, 21, 2, 2).astype("float32") * 0.1 - 5.0
    _, ys = V.yolo_box(pt.to_tensor(x),
                       pt.to_tensor(np.array([[64, 64]], "int32")),
                       anchors=[10, 13, 16, 30, 33, 23], class_num=2,
                       conf_thresh=0.5)
    assert float(np.abs(ys.numpy()).sum()) == 0.0
    xiou = np.random.randn(1, 24, 2, 2).astype("float32")
    yb, _ = V.yolo_box(pt.to_tensor(xiou),
                       pt.to_tensor(np.array([[64, 64]], "int32")),
                       anchors=[10, 13, 16, 30, 33, 23], class_num=2,
                       conf_thresh=0.01, iou_aware=True)
    assert yb.shape == [1, 12, 4]


def test_roi_align_adaptive_grid_matches_reference():
    # sampling_ratio=-1: grid adapts per ROI (ceil(bin)) like the phi /
    # torchvision kernels; fixed 2x2 diverges on big ROIs (ADVICE r3).
    rng = np.random.default_rng(3)
    feat = rng.standard_normal((1, 2, 16, 16)).astype("float32")
    box = np.array([[1.0, 1.0, 13.0, 13.0]], "float32")  # 12x12 -> 2x2 bins

    def ref_roi_align(f, b, out, aligned=True):
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = b * 1.0 - off
        rw, rh = max(x2 - x1, 1e-3), max(y2 - y1, 1e-3)
        bh, bw = rh / out, rw / out
        sy, sx = int(np.ceil(rh / out)), int(np.ceil(rw / out))
        H, W = f.shape[-2:]

        def bilin(c, y, x):
            y0, x0 = int(np.clip(np.floor(y), 0, H - 1)), int(np.clip(np.floor(x), 0, W - 1))
            y1_, x1_ = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
            wy, wx = np.clip(y - y0, 0, 1), np.clip(x - x0, 0, 1)
            return (f[c, y0, x0] * (1 - wy) * (1 - wx) + f[c, y0, x1_] * (1 - wy) * wx
                    + f[c, y1_, x0] * wy * (1 - wx) + f[c, y1_, x1_] * wy * wx)

        o = np.zeros((f.shape[0], out, out), "float64")
        for c in range(f.shape[0]):
            for i in range(out):
                for j in range(out):
                    acc = 0.0
                    for si in range(sy):
                        for sj in range(sx):
                            y = y1 + (i + (si + 0.5) / sy) * bh
                            x = x1 + (j + (sj + 0.5) / sx) * bw
                            acc += bilin(c, y, x)
                    o[c, i, j] = acc / (sy * sx)
        return o

    out = V.roi_align(pt.to_tensor(feat), pt.to_tensor(box),
                      pt.to_tensor(np.array([1], "int32")), 2,
                      sampling_ratio=-1, aligned=True).numpy()
    expect = ref_roi_align(feat[0], box[0], 2)
    np.testing.assert_allclose(out[0], expect, rtol=1e-4, atol=1e-5)


def test_roi_align_traceable_over_boxes():
    # code-review r4: default sampling_ratio must stay jit-traceable over
    # boxes (falls back to the fixed 2x2 grid under tracing)
    import jax
    feat = np.ones((1, 1, 8, 8), "float32")
    bn = pt.to_tensor(np.array([1], "int32"))

    from paddle_tpu.core.tensor import unwrap

    def f(b):
        return unwrap(V.roi_align(pt.to_tensor(feat), pt.to_tensor(b),
                                  bn, 2))

    out = jax.jit(f)(np.array([[1.0, 1.0, 6.0, 6.0]], "float32"))
    np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-5)
