"""Heter-PS analog: giant host/SSD embedding tables with per-batch row
streaming through a jitted TPU step (VERDICT r4 missing #6; reference
paddle/fluid/framework/fleet/heter_ps/ GPU-PS design).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.heter_embedding import HeterEmbedding


def _step_fn(dim):
    @jax.jit
    def step(w, rows, inv, labels):
        def loss_fn(w, rows):
            x = HeterEmbedding.embed(rows, inv, labels.shape)  # [B,S,D]
            pred = x @ w                                       # [B,S]
            return jnp.mean((pred.squeeze(-1) - labels) ** 2)

        (loss, (gw, g_rows)) = jax.value_and_grad(
            lambda w, r: loss_fn(w, r), argnums=(0, 1))(w, rows)
        return loss, w - 0.1 * gw, g_rows

    return step


def test_streamed_rows_match_dense_table_training():
    """3 steps of SGD through the fetch/step/apply triangle == the same
    training on a DENSE jnp table (the oracle), with a vocab far larger
    than anything materialized."""
    V, D, B, S = 1 << 30, 8, 4, 6       # 2^30 vocab: only touched rows exist
    emb = HeterEmbedding(V, D, lr=0.05, optimizer="sgd",
                         initializer="uniform", seed=3)
    rng = np.random.RandomState(0)
    # oracle: dense table over a REMAPPED small id space
    all_ids = rng.choice(1 << 20, size=32, replace=False).astype(np.int64)
    id2small = {int(i): k for k, i in enumerate(all_ids)}
    dense = jnp.asarray(np.stack(
        [np.asarray(emb.table.pull([i])[0]) for i in all_ids]))
    w = jnp.asarray(rng.randn(D, 1).astype(np.float32))
    w2 = w
    step = _step_fn(D)

    @jax.jit
    def dense_step(tab, w, ids_small, labels):
        def loss_fn(tab, w):
            x = tab[ids_small]
            pred = (x @ w).squeeze(-1)
            return jnp.mean((pred - labels) ** 2)

        loss, (gt, gw) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            tab, w)
        return loss, tab - 0.05 * gt, w - 0.1 * gw

    for it in range(3):
        ids = rng.choice(all_ids, size=(B, S))          # duplicates likely
        labels = rng.randn(B, S).astype(np.float32)
        rows, inv, ids_u = emb.fetch(ids)
        loss, w, g_rows = step(w, rows, jnp.asarray(inv),
                               jnp.asarray(labels))
        emb.apply_grad_rows(ids_u, g_rows)

        ids_small = jnp.asarray(
            np.vectorize(id2small.get)(ids).astype(np.int32))
        loss2, dense, w2 = dense_step(dense, w2, ids_small,
                                      jnp.asarray(labels))
        np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)

    # every touched row matches the dense oracle after training
    got = emb.table.pull(all_ids)
    np.testing.assert_allclose(got, np.asarray(dense), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(w), np.asarray(w2), rtol=1e-5)
    # the table only ever materialized the touched rows, not 2^30
    assert emb.num_touched_rows == len(all_ids)


def test_duplicate_ids_sum_their_grads():
    """embed()'s gather makes duplicate-id grads SUM into one row — the
    sparse-grad contract of the reference push_sparse."""
    emb = HeterEmbedding(1000, 4, lr=1.0, optimizer="sgd",
                         initializer="zeros")
    ids = np.array([[7, 7, 7, 9]])
    rows, inv, ids_u = emb.fetch(ids)

    def f(r):
        x = HeterEmbedding.embed(r, inv, (1, 4))
        return x.sum()

    g = jax.grad(f)(rows)
    # id 7 appears 3x -> grad 3.0 per component; id 9 once -> 1.0
    np.testing.assert_allclose(np.asarray(g[list(ids_u).index(7)]), 3.0)
    np.testing.assert_allclose(np.asarray(g[list(ids_u).index(9)]), 1.0)


def test_adagrad_rows_and_state_roundtrip(tmp_path):
    emb = HeterEmbedding(10_000, 4, lr=0.5, optimizer="adagrad",
                         initializer="zeros")
    ids = np.array([1, 2, 2, 3])
    rows, inv, ids_u = emb.fetch(ids)
    g = np.ones((len(ids_u), 4), np.float32)
    emb.apply_grad_rows(ids_u, g)
    emb.apply_grad_rows(ids_u, g)
    # adagrad: second step smaller than first (acc grows)
    r = emb.table.pull(ids_u)
    first = 0.5 * 1.0 / (1.0 + 1e-6)
    second = 0.5 * 1.0 / (np.sqrt(2.0) + 1e-6)
    np.testing.assert_allclose(r, -(first + second), rtol=1e-5)
    # state roundtrip restores rows AND accumulators
    st = emb.state()
    emb2 = HeterEmbedding(10_000, 4, lr=0.5, optimizer="adagrad",
                          initializer="zeros")
    emb2.load_state(st)
    emb2.apply_grad_rows(ids_u, g)
    third = 0.5 * 1.0 / (np.sqrt(3.0) + 1e-6)
    np.testing.assert_allclose(emb2.table.pull(ids_u),
                               -(first + second + third), rtol=1e-5)


def test_ssd_spill_backing(tmp_path):
    """The SSD table composes: rows spill to disk past cache_rows and
    stream back on fetch (reference heter_ps SSD cache level)."""
    emb = HeterEmbedding(1 << 24, 4, lr=0.1, optimizer="sgd",
                         ssd_path=str(tmp_path / "ssd"), cache_rows=8,
                         initializer="uniform", seed=1)
    ids = np.arange(64)
    rows, inv, ids_u = emb.fetch(ids)
    emb.apply_grad_rows(ids_u, np.ones((64, 4), np.float32))
    before = emb.table.pull(np.arange(8))
    # touch 64 rows with an 8-row cache: most spilled to disk; re-fetch
    # round-trips through the spill
    rows2, _inv2, _ = emb.fetch(np.arange(8))
    np.testing.assert_allclose(np.asarray(rows2), before, rtol=1e-6)
    emb.table.close()
