"""paddle.distribution parity: KL closed forms vs Monte-Carlo.

(Reference: python/paddle/distribution/kl.py registered pairs.)
"""
import numpy as np
import pytest

@pytest.mark.slow
def test_kl_divergence_closed_forms_vs_monte_carlo():
    """New KL pairs (Beta/Dirichlet/Exponential/Gamma/Laplace/Poisson/
    Gumbel) agree with Monte-Carlo estimates. (slow: large-sample
    Monte-Carlo over 7 pairs; the closed-form transform/family checks
    stay tier-1.)"""
    from paddle_tpu.distribution import (Beta, Dirichlet, Exponential,
                                         Gamma, Gumbel, Laplace, Poisson,
                                         kl_divergence)
    import paddle_tpu as pt
    pt.seed(0)
    pairs = [
        (Beta(2.0, 3.0), Beta(3.0, 2.0)),
        (Exponential(2.0), Exponential(0.7)),
        (Gamma(2.0, 1.5), Gamma(3.0, 1.0)),
        (Laplace(0.0, 1.0), Laplace(1.0, 2.0)),
        (Poisson(3.0), Poisson(5.0)),
        (Gumbel(0.0, 1.0), Gumbel(0.5, 1.5)),
        (Dirichlet(np.array([2.0, 3.0, 4.0])),
         Dirichlet(np.array([1.0, 1.0, 1.0]))),
    ]
    for p, q in pairs:
        kl = float(np.asarray(kl_divergence(p, q).numpy()).squeeze())
        s = p.sample((60000,)).numpy()
        est = float((p.log_prob(s).numpy() - q.log_prob(s).numpy()).mean())
        assert abs(kl - est) < max(0.08, 0.08 * abs(kl)), (
            type(p).__name__, kl, est)



def test_transform_family():
    """distribution.transform: roundtrips + analytic log-det vs autodiff
    (reference python/paddle/distribution/transform.py)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    T = pt.distribution.transform
    x = np.random.randn(5).astype("float32")
    cases = [(T.AffineTransform(2.0, 3.0), x),
             (T.ExpTransform(), x),
             (T.SigmoidTransform(), x),
             (T.TanhTransform(), x * 0.5),
             (T.PowerTransform(2.0), np.abs(x) + 0.5)]
    for t, dom in cases:
        y = t.forward(pt.to_tensor(dom))
        np.testing.assert_allclose(t.inverse(y).numpy(), dom, rtol=1e-4,
                                   atol=1e-5)
        g = jax.vmap(jax.grad(lambda v: t._forward(v)))(jnp.asarray(dom))
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(pt.to_tensor(dom)).numpy(),
            np.log(np.abs(np.asarray(g))), rtol=1e-4, atol=1e-4)
    ch = T.ChainTransform([T.AffineTransform(0.0, 2.0), T.ExpTransform()])
    np.testing.assert_allclose(
        ch.forward(pt.to_tensor(x)).numpy(), np.exp(2 * x), rtol=1e-5)
    sb = T.StickBreakingTransform()
    u = np.random.randn(4).astype("float32")
    y = np.asarray(sb.forward(pt.to_tensor(u)).numpy())
    assert abs(y.sum() - 1) < 1e-5 and (y > 0).all()
    np.testing.assert_allclose(sb.inverse(pt.to_tensor(y)).numpy(), u,
                               rtol=1e-3, atol=1e-4)
    J = jax.jacfwd(lambda v: sb._forward(v)[:-1])(jnp.asarray(u))
    np.testing.assert_allclose(
        float(sb.forward_log_det_jacobian(pt.to_tensor(u)).numpy()),
        np.log(abs(np.linalg.det(np.asarray(J)))), rtol=1e-4)


def test_transformed_distribution_lognormal():
    from scipy.stats import lognorm

    import paddle_tpu as pt
    from paddle_tpu.distribution import Normal, TransformedDistribution
    T = pt.distribution.transform
    td = TransformedDistribution(Normal(0.0, 1.0), [T.ExpTransform()])
    np.testing.assert_allclose(
        float(np.asarray(td.log_prob(2.0).numpy()).squeeze()),
        lognorm.logpdf(2.0, 1.0), rtol=1e-4)
