"""paddle.distribution parity: KL closed forms vs Monte-Carlo.

(Reference: python/paddle/distribution/kl.py registered pairs.)
"""
import numpy as np

def test_kl_divergence_closed_forms_vs_monte_carlo():
    """New KL pairs (Beta/Dirichlet/Exponential/Gamma/Laplace/Poisson/
    Gumbel) agree with Monte-Carlo estimates."""
    from paddle_tpu.distribution import (Beta, Dirichlet, Exponential,
                                         Gamma, Gumbel, Laplace, Poisson,
                                         kl_divergence)
    import paddle_tpu as pt
    pt.seed(0)
    pairs = [
        (Beta(2.0, 3.0), Beta(3.0, 2.0)),
        (Exponential(2.0), Exponential(0.7)),
        (Gamma(2.0, 1.5), Gamma(3.0, 1.0)),
        (Laplace(0.0, 1.0), Laplace(1.0, 2.0)),
        (Poisson(3.0), Poisson(5.0)),
        (Gumbel(0.0, 1.0), Gumbel(0.5, 1.5)),
        (Dirichlet(np.array([2.0, 3.0, 4.0])),
         Dirichlet(np.array([1.0, 1.0, 1.0]))),
    ]
    for p, q in pairs:
        kl = float(np.asarray(kl_divergence(p, q).numpy()).squeeze())
        s = p.sample((60000,)).numpy()
        est = float((p.log_prob(s).numpy() - q.log_prob(s).numpy()).mean())
        assert abs(kl - est) < max(0.08, 0.08 * abs(kl)), (
            type(p).__name__, kl, est)

