"""Chaos suite: deterministic fault injection against the serving
stack (reliability.FaultInjector wired into prefill, decode tick, page
alloc, and token callbacks).

Contracts under 10-30% injected failure rates:
- the server RECOVERS: breaker closed, later requests succeed;
- every wait() resolves to a result or a TYPED error (no wedged
  waiters, no raw thread death);
- the paged pool never leaks: free + pinned + cached == usable pool
  once drained, across every failure path;
- same seed => identical injection trace AND identical final state.

Everything runs on the StubModel double with zero-delay retry policies
— no sleeps, so the whole suite is tier-1 fast."""
import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.reliability import (CallbackError, CircuitBreaker,
                                    FaultInjector, ReliabilityError,
                                    RetryPolicy, faults)

pytestmark = pytest.mark.chaos


def _prompts(n, rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    return [rng.integers(0, 16, (int(k),)).astype(np.int32)
            for k in rng.integers(2, 9, (n,))]


def _chaos_injector(seed, p_prefill=0.25, p_tick=0.2, p_alloc=0.15):
    return (FaultInjector(seed=seed)
            .on(faults.PREFILL, probability=p_prefill)
            .on(faults.DECODE_TICK, probability=p_tick)
            .on(faults.PAGE_ALLOC, probability=p_alloc))


def _chaos_server(fi, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("retry_policy", RetryPolicy(base_delay_s=0.0,
                                              jitter=0.0))
    # high threshold: these tests exercise per-request failure + retry;
    # breaker-open recovery has its own test below
    kw.setdefault("breaker", CircuitBreaker(failure_threshold=10_000))
    return ContinuousBatchingServer(StubModel(), fault_injector=fi, **kw)


def _drive(srv, max_ticks=5000):
    """Single-threaded supervisor stand-in: retry every failed tick.
    Deterministic (no thread scheduling), used where the test must
    replay exactly; the threaded tests use start()/wait()."""
    ticks = 0
    while True:
        with srv._lock:
            busy = srv._busy_locked()       # incl. mid-prefill slots
        if not busy:
            return
        try:
            srv.step()
        except CallbackError:
            pass                       # per-request; requests already failed
        except Exception:
            pass                       # transient tick fault: retry
        ticks += 1
        assert ticks < max_ticks, "chaos drive did not converge"


def _final_state(srv, fi):
    """(trace, results, failure types, pool balance) for determinism
    comparisons."""
    results = {r: tuple(int(x) for x in v)
               for r, v in srv._results.items()}
    fails = {r: type(e).__name__ for r, e in srv.failures.items()}
    return fi.trace, results, fails, srv.pool_balance()


class TestChaos:
    def test_threaded_chaos_recovers_no_leaks(self):
        """Acceptance: faults in prefill/decode/page-alloc at 10-30%,
        server recovers, every wait() resolves typed, pool balanced."""
        fi = _chaos_injector(seed=1234)
        srv = _chaos_server(fi).start()
        prompts = _prompts(14)
        rids = [srv.submit(p, max_new_tokens=5) for p in prompts]
        ok, failed = {}, {}
        for rid in rids:
            try:
                ok[rid] = srv.wait(rid, timeout=120)
            except ReliabilityError as e:
                failed[rid] = e
        assert len(ok) + len(failed) == len(rids)   # nobody wedged
        for rid, p in zip(rids, prompts):
            if rid in ok:                # survivors are bit-exact
                np.testing.assert_array_equal(ok[rid], stub_tokens(p, 5))
        assert fi.fired() > 0, "chaos never fired; raise rates"
        # recovery: chaos off, the same server keeps serving
        fi.disarm()
        p = _prompts(1, rng_seed=99)[0]
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.wait(rid, timeout=60),
                                      stub_tokens(p, 4))
        assert srv.health == "healthy"
        srv.stop()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0, f"leaked {live} pages"
        assert free + pinned + cached == srv._kv.num_pages - 1

    def test_chaos_with_prefix_pinning_no_leaks(self):
        """Injected admission failures must roll back cleanly even when
        slots share refcounted prefix pages."""
        fi = _chaos_injector(seed=77, p_tick=0.1)
        srv = _chaos_server(fi, max_cache_len=64)
        fi.disarm()
        prefix = np.arange(8, dtype=np.int32) % 16
        srv.register_prefix(prefix)
        fi.arm()
        prompts = [np.concatenate([prefix, t]) for t in _prompts(8)]
        rids = [srv.submit(p, max_new_tokens=4) for p in prompts]
        _drive(srv)
        outs = srv._results
        for rid, p in zip(rids, prompts):
            if rid in outs:
                np.testing.assert_array_equal(outs[rid],
                                              stub_tokens(p, 4))
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and pinned == 1         # only the prefix pin
        assert free + pinned + cached == srv._kv.num_pages - 1

    def test_same_seed_identical_trace_and_state(self):
        """Satellite: two chaos runs with the same seed produce
        identical injection traces and identical final server state
        (results, failure types, free-page count)."""
        def run_once():
            fi = _chaos_injector(seed=4242)
            srv = _chaos_server(fi)
            for p in _prompts(10, rng_seed=3):
                srv.submit(p, max_new_tokens=5)
            _drive(srv)
            return _final_state(srv, fi)

        trace_a, res_a, fail_a, pool_a = run_once()
        trace_b, res_b, fail_b, pool_b = run_once()
        assert trace_a == trace_b
        assert res_a == res_b
        assert fail_a == fail_b
        assert pool_a == pool_b
        assert trace_a, "deterministic run injected nothing"

    def test_injector_reset_replays_one_server_script(self):
        """reset() rewinds the PRNG streams: the same injector replays
        the same script against a fresh server."""
        fi = _chaos_injector(seed=9, p_alloc=0.0)

        def run():
            srv = _chaos_server(fi)
            for p in _prompts(6, rng_seed=5):
                srv.submit(p, max_new_tokens=4)
            _drive(srv)
            return list(fi.trace), srv.pool_balance()

        first = run()
        fi.reset()
        assert run() == first

    def test_callback_chaos_fails_streams_not_server(self):
        """ON_TOKEN faults: poisoned streams fail individually, clean
        requests stream to completion, pool stays balanced."""
        fi = FaultInjector(seed=21).on(faults.ON_TOKEN, probability=0.3)
        srv = _chaos_server(fi).start()
        prompts = _prompts(8, rng_seed=7)
        chunks = {i: [] for i in range(len(prompts))}
        rids = [srv.submit(p, max_new_tokens=4,
                           on_token=lambda r, t, i=i: chunks[i].append(t))
                for i, p in enumerate(prompts)]
        done = failed = 0
        for i, rid in enumerate(rids):
            try:
                out = srv.wait(rid, timeout=120)
                done += 1
                np.testing.assert_array_equal(out,
                                              stub_tokens(prompts[i], 4))
            except ReliabilityError:
                failed += 1
        assert done + failed == len(rids)
        assert srv.health == "healthy"           # engine never degraded
        srv.stop()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0

    def test_breaker_storm_then_full_recovery(self):
        """A sustained decode-fault storm opens the breaker (typed
        errors for everyone in flight); once the storm passes and the
        cooldown elapses, the SAME server serves again — acceptance
        'breaker closed, subsequent requests succeed'."""
        from paddle_tpu.telemetry import FakeClock
        fcb = FakeClock()
        fi = FaultInjector(seed=0).on(faults.DECODE_TICK,
                                      probability=1.0)
        srv = _chaos_server(
            fi, breaker=CircuitBreaker(failure_threshold=4,
                                       reset_after_s=5.0,
                                       clock=fcb)).start()
        rids = [srv.submit(p, max_new_tokens=4) for p in _prompts(5)]
        errs = []
        for rid in rids:
            with pytest.raises(ReliabilityError) as ei:
                srv.wait(rid, timeout=120)
            errs.append(ei.value)
        assert srv.health == "degraded"
        fi.disarm()                       # storm over
        fcb.advance(6.0)                  # cooldown elapses
        p = _prompts(1, rng_seed=11)[0]
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.wait(rid, timeout=60),
                                      stub_tokens(p, 4))
        assert srv.health == "healthy"
        srv.stop()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0
