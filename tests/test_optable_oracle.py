"""Oracle tests driven by the declarative op table (the ops.yaml-analogue
single source of truth — paddle_tpu/ops/optable.py). One parameterized
test per table row; plus API-surface and inplace-variant checks.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.optable import TABLE, coverage_names


def _run_case(case):
    op = getattr(pt, case.name, None)
    assert op is not None, f"op {case.name} missing from namespace"
    np_inputs = [gen() for gen in case.inputs.values()]
    tensors = [pt.to_tensor(v) for v in np_inputs]
    if case.call is not None:
        out = case.call(op, tensors, case.attrs)
    else:
        out = op(*tensors, **case.attrs)
    expected = case.ref(*np_inputs) if case.inputs else case.ref()

    def leaves(x):
        if isinstance(x, (tuple, list)):
            return [l for e in x for l in leaves(e)]
        return [x]

    got, want = leaves(out), leaves(expected)
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        g = np.asarray(g.numpy() if hasattr(g, "numpy") else g)
        w = np.asarray(w)
        if w.dtype == bool or np.issubdtype(w.dtype, np.integer):
            np.testing.assert_array_equal(g.astype(w.dtype), w)
        elif np.issubdtype(w.dtype, np.complexfloating):
            np.testing.assert_allclose(g.astype(np.complex128),
                                       w.astype(np.complex128),
                                       atol=case.atol, rtol=case.rtol)
        else:
            np.testing.assert_allclose(g.astype(np.float64),
                                       w.astype(np.float64),
                                       atol=case.atol, rtol=case.rtol)


@pytest.mark.parametrize("case", TABLE, ids=[c.case_id for c in TABLE])
def test_optable_oracle(case):
    _run_case(case)


def test_case_count_meets_floor():
    # VERDICT round-3 target: >=300 oracle cases driven by the table
    # (plus the legacy suite in test_ops_oracle.py)
    assert len(TABLE) >= 300, len(TABLE)


def test_every_table_op_in_namespace():
    missing = [n for n in coverage_names() if not hasattr(pt, n)]
    assert not missing, missing


class TestInplaceVariants:
    def test_add_(self):
        x = pt.to_tensor(np.array([1.0, 2.0], np.float32))
        y = x.add_(pt.to_tensor(np.array([10.0, 20.0], np.float32)))
        assert y is x
        np.testing.assert_allclose(x.numpy(), [11.0, 22.0])

    def test_clip_scale_chain(self):
        x = pt.to_tensor(np.array([-5.0, 0.5, 5.0], np.float32))
        x.clip_(min=-1.0, max=1.0).scale_(scale=2.0)
        np.testing.assert_allclose(x.numpy(), [-2.0, 1.0, 2.0])

    def test_cast_changes_dtype(self):
        x = pt.to_tensor(np.array([1.7], np.float32))
        x.cast_("int32")
        assert "int32" in str(x.dtype)

    def test_zero_fill(self):
        x = pt.to_tensor(np.ones((2, 2), np.float32))
        x.zero_()
        np.testing.assert_allclose(x.numpy(), np.zeros((2, 2)))
        x.fill_(3.5)
        np.testing.assert_allclose(x.numpy(), np.full((2, 2), 3.5))

    def test_zero_detaches_tape(self):
        # review regression: zeroing a computed tensor must NOT backprop
        # through the stale producer
        a = pt.to_tensor(np.array([2.0], np.float32),
                         stop_gradient=False)
        b = pt.to_tensor(np.array([3.0], np.float32),
                         stop_gradient=False)
        y = a * b
        y.zero_()
        out = y + a  # keep a path to `a` so backward() has a graph
        out.sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), [1.0])
        assert b.grad is None

    def test_exp_sqrt_(self):
        x = pt.to_tensor(np.array([4.0], np.float32))
        x.sqrt_()
        np.testing.assert_allclose(x.numpy(), [2.0])
        x.exp_()
        np.testing.assert_allclose(x.numpy(), [np.exp(2.0)], rtol=1e-6)

    def test_inplace_participates_in_autograd(self):
        # review regression: relu_ after multiply must keep the relu
        # derivative on the tape (not backprop through a*b alone)
        a = pt.to_tensor(np.array([-2.0, 3.0], np.float32),
                         stop_gradient=False)
        b = pt.to_tensor(np.array([5.0, 7.0], np.float32),
                         stop_gradient=False)
        y = a * b
        y.relu_()
        y.sum().backward()
        # d/da relu(a*b) = b * (a*b > 0)
        np.testing.assert_allclose(a.grad.numpy(), [0.0, 7.0])


class TestReviewRegressions:
    def test_cummax_indices(self):
        x = pt.to_tensor(np.array([3.0, 1.0, 5.0, 5.0], np.float32))
        vals, idx = pt.cummax(x, axis=0)
        np.testing.assert_allclose(vals.numpy(), [3.0, 3.0, 5.0, 5.0])
        np.testing.assert_array_equal(idx.numpy(), [0, 0, 2, 2])

    def test_cummin_indices_2d(self):
        x = np.array([[2.0, 1.0], [0.5, 3.0], [0.5, 0.0]], np.float32)
        vals, idx = pt.cummin(pt.to_tensor(x), axis=0)
        np.testing.assert_allclose(vals.numpy(),
                                   [[2.0, 1.0], [0.5, 1.0], [0.5, 0.0]])
        np.testing.assert_array_equal(idx.numpy(),
                                      [[0, 0], [1, 0], [1, 2]])

    def test_vector_norm_keepdim_all_axes(self):
        x = pt.to_tensor(np.ones((2, 3), np.float32))
        out = pt.vector_norm(x, keepdim=True)
        assert tuple(out.numpy().shape) == (1, 1)

    def test_scaler_step_without_update_keeps_unscaling(self):
        net = pt.nn.Linear(2, 1)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        scaler = pt.amp.GradScaler(init_loss_scaling=8.0,
                                   use_dynamic_loss_scaling=False)
        x = pt.to_tensor(np.ones((4, 2), np.float32))
        for _ in range(3):
            loss = net(x).mean()
            scaler.scale(loss).backward()
            scaler.unscale_(opt)
            g = net.weight.grad.numpy().copy()
            scaler.step(opt)   # must not re-unscale, must not skip next
            opt.clear_grad()
        # third-iteration grad must be exactly unscaled (1.0): the
        # skip-unscale bug would leave 8.0, double-unscale would give
        # 0.125
        np.testing.assert_allclose(np.abs(g), 1.0, rtol=1e-5)
