"""Multi-process collective e2e: 2 REAL processes through the launcher's
env protocol, jax.distributed bring-up, and a cross-process collective
(reference pattern: test_parallel_dygraph_dataparallel.py
start_local_trainers + collective_allreduce_api over 2 trainers)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_multiprocess_supported():
    """The installed XLA CPU backend may reject cross-process programs
    outright ("Multiprocess computations aren't implemented on the CPU
    backend") — probe the version once instead of failing the e2e."""
    import jax
    ver = tuple(int(x) for x in jax.__version__.split(".")[:3])
    return ver >= (0, 5, 0)


@pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="XLA CPU backend of this JAX (<0.5) cannot run multiprocess "
           "computations; e2e needs a newer runtime or real chips")
def test_two_process_collective(tmp_path):
    worker = os.path.join(REPO, "tests", "dist_collective_worker.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    env.update({"PROBE_DIR": str(tmp_path), "PYTHONUNBUFFERED": "1"})
    cmd = [sys.executable, "-m", "paddle_tpu.parallel.launch.main",
           "--nproc_per_node", "2", "--master", "127.0.0.1:29883",
           "--log_dir", str(tmp_path / "log"), worker]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=300)
    logs = ""
    for i in range(2):
        p = tmp_path / "log" / f"workerlog.{i}"
        if p.exists():
            logs += f"--- worker {i} ---\n" + p.read_text()[-1500:]
    assert r.returncode == 0, (r.stdout, r.stderr, logs)
    res = [json.load(open(tmp_path / f"rank{i}.json")) for i in range(2)]
    assert all(x["world"] == 2 for x in res)
    # sum over both processes' shards: 4*1 + 4*2
    assert all(x["sum"] == 12.0 for x in res)
