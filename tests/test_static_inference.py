"""Static-graph API + inference engine tests.

Mirrors the reference's static-mode unit tests (Program/Executor feed-fetch,
append_backward, minimize training, save/load_inference_model) and the
paddle_infer Predictor API surface.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.static as st


def fresh_programs():
    return st.Program(), st.Program()


def test_program_build_and_run():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [4, 3], "float32")
        y = pt.add(pt.multiply(x, x), x)
        z = pt.mean(y)
    assert main.num_ops == 3
    assert x.shape == [4, 3]
    exe = st.Executor()
    xv = np.random.rand(4, 3).astype("float32")
    (zv,) = exe.run(main, feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(zv, (xv * xv + xv).mean(), rtol=1e-6)


def test_tensor_methods_record():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [2, 5], "float32")
        y = (x + 1.0) * 2.0
        s = y.sum()
    exe = st.Executor()
    xv = np.ones((2, 5), np.float32)
    (sv,) = exe.run(main, feed={"x": xv}, fetch_list=[s])
    assert float(sv) == pytest.approx(40.0)


def test_executor_cache_reuse():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [2, 2], "float32")
        y = pt.exp(x)
    exe = st.Executor()
    exe.run(main, feed={"x": np.zeros((2, 2), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == 1
    exe.run(main, feed={"x": np.ones((2, 2), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == 1  # same signature → cached
    exe.run(main, feed={"x": np.ones((3, 2), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == 2  # new shape → new entry


def test_static_nn_fc_train_minimize():
    main, startup = fresh_programs()
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 4).astype("float32")
    w_true = rng.rand(4, 1).astype("float32")
    ys = xs @ w_true
    with st.program_guard(main, startup):
        x = st.data("x", [16, 4], "float32")
        label = st.data("label", [16, 1], "float32")
        pred = st.nn.fc(x, 1)
        loss = pt.mean(pt.square(pred - label))
        opt = pt.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = st.Executor()
    exe.run(startup)  # materialize params
    losses = []
    for _ in range(200):
        (lv,) = exe.run(main, feed={"x": xs, "label": ys},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05


def test_append_backward_grad_fetch():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [3, 2], "float32")
        w = st.create_parameter([2, 2], "float32")
        y = pt.matmul(x, w)
        loss = pt.sum(y)
        grads = st.append_backward(loss)
    exe = st.Executor()
    exe.run(startup)
    xv = np.random.rand(3, 2).astype("float32")
    gname = grads[0][1].name
    (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gname])
    # d(sum(x@w))/dw = x^T @ ones
    np.testing.assert_allclose(gv, xv.T @ np.ones((3, 2)), rtol=1e-5)


def test_eager_layer_under_program_guard():
    """A dygraph nn.Layer works inside program_guard: its concrete params
    are interned as persistable scope vars (paddle 2.x dual-mode parity)."""
    main, startup = fresh_programs()
    layer = pt.nn.Linear(6, 3)
    with st.program_guard(main, startup):
        x = st.data("x", [2, 6], "float32")
        out = layer(x)
        loss = pt.mean(out)
        opt = pt.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    assert len(main._param_names) == 2
    exe = st.Executor()
    before = layer.weight.numpy().copy()
    exe.run(main, feed={"x": np.ones((2, 6), np.float32)},
            fetch_list=["mean_0"] if "mean_0" in main.global_block.vars
            else [loss])
    after = layer.weight.numpy()
    assert not np.allclose(before, after)  # write-back reached eager param


def test_program_clone_for_test():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [2, 2], "float32")
        y = pt.relu(x)
        loss = pt.mean(y)
        pt.optimizer.SGD(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert test_prog._train_spec is None
    assert main._train_spec is not None


def test_gradients_api():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [4], "float32")
        y = pt.sum(pt.square(x))
        (gx,) = st.gradients(y, x)
    exe = st.Executor()
    xv = np.arange(4, dtype=np.float32)
    (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 2 * xv, rtol=1e-6)


def test_save_load_inference_model_predictor(tmp_path):
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [1, 4], "float32")
        out = st.nn.fc(x, 2, activation="relu")
    exe = st.Executor()
    exe.run(startup)
    prefix = str(tmp_path / "model" / "infer")
    st.save_inference_model(prefix, [x], [out], exe)

    # direct load
    prog, feeds, fetches = st.load_inference_model(prefix)
    xv = np.random.rand(1, 4).astype("float32")
    (ov,) = prog(xv)

    # paddle_infer-style Predictor
    from paddle_tpu import inference as paddle_infer
    cfg = paddle_infer.Config(prefix)
    pred = paddle_infer.create_predictor(cfg)
    assert pred.get_input_names() == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(xv)
    pred.run()
    out_np = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out_np, np.asarray(ov), rtol=1e-5)
    assert (out_np >= 0).all()


def test_jit_save_export_layer(tmp_path):
    layer = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                             pt.nn.Linear(16, 4))
    xv = np.random.rand(2, 8).astype("float32")
    ref = layer(pt.to_tensor(xv)).numpy()
    prefix = str(tmp_path / "seq")
    pt.jit.save(layer, prefix,
                input_spec=[st.InputSpec([2, 8], "float32", "x")])
    loaded = pt.jit.load(prefix)
    out = loaded(xv)
    flat = out[0] if isinstance(out, (list, tuple)) else out
    np.testing.assert_allclose(np.asarray(flat), ref, rtol=1e-5)


def test_jit_save_dynamic_batch(tmp_path):
    """Dynamic (-1) batch dim exports symbolically: one archive serves any
    batch size (reference: -1 feed dims in save_inference_model)."""
    layer = pt.nn.Linear(8, 3)
    prefix = str(tmp_path / "dyn")
    pt.jit.save(layer, prefix,
                input_spec=[st.InputSpec([-1, 8], "float32", "x")])
    loaded = pt.jit.load(prefix)
    for bs in (1, 4, 7):
        xv = np.random.rand(bs, 8).astype("float32")
        out = loaded(xv)
        out = out[0] if isinstance(out, (list, tuple)) else out
        ref = layer(pt.to_tensor(xv)).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)


def test_export_restores_sublayer_training(tmp_path):
    layer = pt.nn.Sequential(pt.nn.Linear(4, 4), pt.nn.Dropout(0.5))
    layer.train()
    pt.jit.save(layer, str(tmp_path / "m"),
                input_spec=[st.InputSpec([2, 4], "float32", "x")])
    assert layer.training
    assert all(m.training for _, m in layer.named_sublayers())


def test_opt_state_survives_fetch_and_shape_change():
    """Adam moments must not reset when the fetch list or batch size
    changes between runs of the same program."""
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [8, 4], "float32")
        w = st.create_parameter([4, 1], "float32")
        loss = pt.mean(pt.square(pt.matmul(x, w)))
        opt = pt.optimizer.Adam(learning_rate=0.01)
        opt.minimize(loss)
    exe = st.Executor()
    exe.run(startup)
    xv = np.random.rand(8, 4).astype("float32")
    exe.run(main, feed={"x": xv}, fetch_list=[loss])
    st0 = exe._opt_states[id(main)][1]
    exe.run(main, feed={"x": xv}, fetch_list=[loss, "x"])  # new fetch sig
    assert exe._opt_states[id(main)][1] == st0 + 1  # state continued


def test_minimize_parameter_list_freezes_others():
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [4, 2], "float32")
        w = st.create_parameter([2, 2], "float32", name="w_train")
        b = st.create_parameter([2], "float32", name="b_frozen",
                                is_bias=True)
        loss = pt.mean(pt.square(pt.matmul(x, w) + b + 1.0))
        pt.optimizer.SGD(0.1).minimize(loss, parameter_list=["w_train"])
    exe = st.Executor()
    exe.run(startup)
    b_before = np.asarray(st.global_scope()._vars["b_frozen"]).copy()
    w_before = np.asarray(st.global_scope()._vars["w_train"]).copy()
    exe.run(main, feed={"x": np.ones((4, 2), np.float32)},
            fetch_list=[loss])
    np.testing.assert_allclose(
        np.asarray(st.global_scope()._vars["b_frozen"]), b_before)
    assert not np.allclose(
        np.asarray(st.global_scope()._vars["w_train"]), w_before)


def test_static_save_load_params(tmp_path):
    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [2, 3], "float32")
        out = st.nn.fc(x, 2)
    exe = st.Executor()
    exe.run(startup)
    pname = main._param_names[0]
    orig = np.asarray(st.global_scope()._vars[pname]).copy()
    prefix = str(tmp_path / "ckpt")
    st.save(main, prefix)
    st.global_scope()._vars[pname] = np.zeros_like(orig)
    st.load(main, prefix)
    np.testing.assert_allclose(
        np.asarray(st.global_scope()._vars[pname]), orig)


def test_predictor_config_knobs_functional(tmp_path):
    """VERDICT r3 #9: Config switches must act or raise, never sit inert."""
    import pytest

    main, startup = fresh_programs()
    with st.program_guard(main, startup):
        x = st.data("x", [1, 4], "float32")
        out = st.nn.fc(x, 2, activation="relu")
    exe = st.Executor()
    exe.run(startup)
    prefix = str(tmp_path / "m" / "infer")
    st.save_inference_model(prefix, [x], [out], exe)

    from paddle_tpu import inference as paddle_infer
    xv = np.random.rand(1, 4).astype("float32")

    # memory_optim -> donated compiled call, same numbers
    cfg0 = paddle_infer.Config(prefix)
    ref = paddle_infer.create_predictor(cfg0).run([xv])[0]
    cfg = paddle_infer.Config(prefix)
    cfg.enable_memory_optim()
    cfg.enable_profile()
    pred = paddle_infer.create_predictor(cfg)
    got = pred.run([xv.copy()])[0]
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    assert cfg.profile_stats()["runs"] == 1
    assert cfg.profile_stats()["total_ms"] > 0
    assert cfg.summary()["memory_optim"] is True

    # ir_optim cannot be switched off on XLA: raises, not ignores
    with pytest.raises(NotImplementedError):
        paddle_infer.Config(prefix).switch_ir_optim(False)
    paddle_infer.Config(prefix).switch_ir_optim(True)   # default: fine


# --------------------------------------------------- batched scheduler

class TestBatchScheduler:
    def test_groups_requests_into_one_run(self):
        """10 single-row requests within the linger window -> far fewer
        runner calls than requests; every future gets ITS slice."""
        from paddle_tpu.inference import BatchScheduler
        calls = []

        def runner(stacked):
            calls.append(stacked[0].shape[0])
            return [stacked[0] * 2.0, stacked[0].sum(-1, keepdims=True)]

        sched = BatchScheduler(runner, max_batch_size=8, max_delay_ms=60)
        xs = [np.full((1, 4), float(i), np.float32) for i in range(10)]
        futs = [sched.submit(x) for x in xs]
        outs = [f.result(timeout=20) for f in futs]
        sched.close()
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o[0], xs[i] * 2.0)
            np.testing.assert_allclose(o[1], xs[i].sum(-1, keepdims=True))
        assert sched.batches_run < 10, calls
        assert sum(calls) == 10     # every row served exactly once

    def test_mismatched_shapes_batch_separately(self):
        from paddle_tpu.inference import BatchScheduler
        shapes = []

        def runner(stacked):
            shapes.append(stacked[0].shape)
            return [stacked[0] + 1.0]

        sched = BatchScheduler(runner, max_batch_size=8, max_delay_ms=30)
        f1 = sched.submit(np.zeros((1, 3), np.float32))
        f2 = sched.submit(np.zeros((1, 5), np.float32))
        r1 = f1.result(timeout=20)[0]
        r2 = f2.result(timeout=20)[0]
        sched.close()
        assert r1.shape == (1, 3) and r2.shape == (1, 5)
        assert all(s[1:] in ((3,), (5,)) for s in shapes)
        assert len(shapes) == 2, "different shapes must not mix"

    def test_runner_error_propagates(self):
        from paddle_tpu.inference import BatchScheduler

        def runner(stacked):
            raise RuntimeError("boom")

        sched = BatchScheduler(runner, max_batch_size=4, max_delay_ms=5)
        f = sched.submit(np.zeros((1, 2), np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            f.result(timeout=20)
        sched.close()

    def test_scheduler_over_real_predictor(self, tmp_path):
        """End-to-end: jit.save a layer, create_predictor, serve
        batched requests through the scheduler — one compiled program,
        many requests."""
        from paddle_tpu import inference

        layer = pt.nn.Linear(4, 3)
        prefix = str(tmp_path / "m")
        pt.jit.save(layer, prefix,
                    input_spec=[st.InputSpec([-1, 4], "float32", "x")])
        cfg = inference.Config(prefix)
        pred = inference.create_predictor(cfg)
        sched = inference.BatchScheduler(pred, max_batch_size=4,
                                         max_delay_ms=40)
        xs = [np.full((1, 4), float(i), np.float32) for i in range(6)]
        futs = [sched.submit(x) for x in xs]
        outs = [f.result(timeout=60) for f in futs]
        sched.close()
        for x, o in zip(xs, outs):
            want = layer(pt.to_tensor(x)).numpy()
            np.testing.assert_allclose(o[0], want, rtol=1e-5,
                                       atol=1e-6)


def test_predictor_concurrent_runs_are_isolated():
    """The reference AnalysisPredictor advertises multi-stream serving
    (analysis_predictor.h:95); the TPU-native analog is one compiled
    XLA program safely shared across caller threads."""
    from concurrent.futures import ThreadPoolExecutor
    from paddle_tpu import inference

    layer = pt.nn.Linear(4, 3)
    prefix = str(__import__("tempfile").mkdtemp()) + "/m"
    pt.jit.save(layer, prefix,
                input_spec=[st.InputSpec([-1, 4], "float32", "x")])
    pred = inference.create_predictor(inference.Config(prefix))

    def call(i):
        x = np.full((2, 4), float(i), np.float32)
        return i, pred.run([x])[0]

    with ThreadPoolExecutor(max_workers=4) as ex:
        results = list(ex.map(call, range(32)))
    for i, out in results:
        want = layer(pt.to_tensor(
            np.full((2, 4), float(i), np.float32))).numpy()
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
