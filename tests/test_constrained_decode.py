"""Constrained (FSM) decoding: the output provably matches the automaton
— enumerated phrases, parity alternation, and per-request grammar swaps
without recompiles."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference import fsm_generate, phrases_to_fsm


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(101)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


class TestConstrainedDecode:
    def test_phrase_choice_and_eos_tail(self):
        """Output must be exactly one of the registered phrases + eos."""
        model = _model()
        V, EOS = 256, 7
        phrases = [[10, 20, 30], [10, 25], [40, 41, 42, 43]]
        mask, nxt = phrases_to_fsm(phrases, V, EOS)
        ids = np.arange(4, dtype=np.int32)[None]
        out = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                             max_cache_len=32, fsm=(mask, nxt),
                             eos_token_id=EOS).numpy()[0, 4:].tolist()
        matched = False
        for ph in phrases:
            cand = ph + [EOS] * (6 - len(ph))
            if out == cand:
                matched = True
        assert matched, f"{out} is not a registered phrase + eos tail"

    def test_parity_alternation_automaton(self):
        """2-state FSM: even-id tokens from state 0, odd from state 1."""
        model = _model()
        V = 256
        tokens = np.arange(V)
        mask = np.zeros((2, V), bool)
        mask[0, tokens % 2 == 0] = True
        mask[1, tokens % 2 == 1] = True
        nxt = np.zeros((2, V), np.int32)
        nxt[0] = 1
        nxt[1] = 0
        ids = np.arange(3, dtype=np.int32)[None]
        out = model.generate(pt.to_tensor(ids), max_new_tokens=8,
                             max_cache_len=32,
                             fsm=(mask, nxt)).numpy()[0, 3:]
        assert (out % 2 == np.arange(8) % 2).all(), out

    def test_grammar_swap_without_recompile(self):
        """The automaton is a runtime argument: a second call with a
        different grammar must obey IT (regression: masks must not bake
        into the compiled program as constants)."""
        model = _model()
        V = 256
        only_5 = np.zeros((1, V), bool)
        only_5[0, 5] = True
        only_9 = np.zeros((1, V), bool)
        only_9[0, 9] = True
        nxt = np.zeros((1, V), np.int32)
        ids = np.arange(3, dtype=np.int32)[None]
        a = model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           max_cache_len=32,
                           fsm=(only_5, nxt)).numpy()[0, 3:]
        b = model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           max_cache_len=32,
                           fsm=(only_9, nxt)).numpy()[0, 3:]
        assert (a == 5).all() and (b == 9).all(), (a, b)

    def test_constrained_sampling_stays_in_grammar(self):
        model = _model()
        V = 256
        allowed = np.zeros((1, V), bool)
        allowed[0, [3, 4, 5]] = True
        nxt = np.zeros((1, V), np.int32)
        ids = np.arange(3, dtype=np.int32)[None]
        out = model.generate(pt.to_tensor(ids), max_new_tokens=10,
                             max_cache_len=32, do_sample=True,
                             temperature=5.0, seed=1,
                             fsm=(allowed, nxt)).numpy()[0, 3:]
        assert set(out.tolist()) <= {3, 4, 5}, out

    def test_beam_fsm_exclusive(self):
        model = _model()
        mask = np.ones((1, 256), bool)
        nxt = np.zeros((1, 256), np.int32)
        with pytest.raises(ValueError, match="not beam search"):
            model.generate(pt.to_tensor(np.zeros((1, 2), np.int32)),
                           max_new_tokens=2, num_beams=2,
                           fsm=(mask, nxt))
