"""Static-graph c_* collective ops executed under shard_map
(reference: paddle/fluid/operators/collective/ op suite +
collective/collective_allreduce_api.py test pattern)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.parallel as dist
import paddle_tpu.static as static
from paddle_tpu.static import collective as C
from paddle_tpu.parallel.mesh import P


def test_c_allreduce_and_concat():
    mesh = dist.init_mesh(mp=4)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[2, 4], dtype="float32")
        s = C.c_allreduce_sum(x, axis_name="mp")
        g = C.c_concat(x, axis_name="mp")

    xg = np.arange(32, dtype=np.float32).reshape(2, 16)
    out = C.run_program_sharded(prog, mesh, {"x": xg}, [s, g],
                                {"x": P(None, "mp")})
    # allreduce over mp of per-rank 4-col slices
    ref_sum = xg.reshape(2, 4, 4).sum(1)
    np.testing.assert_allclose(np.asarray(out[0]), ref_sum)
    np.testing.assert_allclose(np.asarray(out[1]), xg)


def test_c_allgather_without_mesh_fails_loud(monkeypatch):
    # review regression: a missing mesh must not record an un-gathered
    # output shape (silent nranks=1)
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.utils.enforce import InvalidArgumentError
    monkeypatch.setattr(mesh_mod, "_GLOBAL_MESH", None)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[2, 4], dtype="float32")
        with pytest.raises(InvalidArgumentError, match="nranks"):
            C.c_allgather(x, axis_name="mp")


def test_c_broadcast():
    mesh = dist.init_mesh(mp=4)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[3], dtype="float32")
        b = C.c_broadcast(x, root=2, axis_name="mp")

    xg = np.arange(12, dtype=np.float32)
    out = C.run_program_sharded(prog, mesh, {"x": xg}, [b],
                                {"x": P("mp")})
    np.testing.assert_allclose(np.asarray(out[0]), xg[6:9])


def test_c_softmax_with_cross_entropy_matches_dense():
    mesh = dist.init_mesh(mp=4)
    V, B = 16, 4
    rng = np.random.RandomState(0)
    logits = rng.randn(B, V).astype(np.float32)
    labels = rng.randint(0, V, size=(B,)).astype(np.int64)

    prog = static.Program()
    with static.program_guard(prog):
        lg = static.data("lg", shape=[B, V // 4], dtype="float32")
        lb = static.data("lb", shape=[B], dtype="int64")
        loss = C.c_softmax_with_cross_entropy(lg, lb, axis_name="mp")

    out = C.run_program_sharded(prog, mesh,
                                {"lg": logits, "lb": labels}, [loss],
                                {"lg": P(None, "mp"), "lb": P()})
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[:, 0]
    ref = lse - logits[np.arange(B), labels]
    np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=1e-5,
                               atol=1e-5)
