"""Behavioral tests for the round-3 parity tail (the api-parity test only
asserts existence; these assert semantics)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


class TestIncubateOptimizers:
    def test_lookahead_pulls_slow_weights(self):
        pt.seed(0)
        lin = nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        la = pt.incubate.LookAhead(opt, alpha=0.5, k=2)
        x = pt.to_tensor(np.random.randn(8, 4).astype("float32"))
        w0 = lin.weight.numpy().copy()
        for _ in range(4):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
        assert not np.allclose(lin.weight.numpy(), w0)
        sd = la.state_dict()
        assert sd["lookahead_step"] == 4

    def test_model_average_apply_restore(self):
        lin = nn.Linear(3, 3)
        ma = pt.incubate.ModelAverage(parameters=lin.parameters())
        ma.step()
        cur = lin.weight.numpy().copy()
        import jax.numpy as jnp
        lin.weight._replace_value(jnp.zeros_like(lin.weight._value))
        with ma.apply():
            np.testing.assert_allclose(lin.weight.numpy(), cur, rtol=1e-6)
        np.testing.assert_allclose(lin.weight.numpy(), 0.0)


class TestDistributedTail:
    def test_spawn_runs_workers(self, tmp_path):
        import paddle_tpu.parallel as dist
        marker = str(tmp_path / "w")

        procs = dist.spawn(_spawn_target, args=(marker,), nprocs=2)
        import os
        assert os.path.exists(marker + "0") and os.path.exists(marker + "1")

    def test_data_generator_protocol(self):
        import paddle_tpu.parallel as dist

        class Gen(dist.fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def reader():
                    toks = [int(t) for t in line.split()]
                    yield [("ids", toks), ("label", [toks[0] % 2])]
                return reader

        g = Gen()
        out = g.run_from_memory(["1 2 3", "4 5 6"])
        assert len(out) == 2 and out[0][0][0] == "ids"

    def test_entries_and_datasets(self, tmp_path):
        import paddle_tpu.parallel as dist
        with pytest.raises(ValueError):
            dist.CountFilterEntry(-1)
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(1.5)
        p = tmp_path / "d.txt"
        p.write_text("1 2\n3 4\n5 6\n")
        ds = dist.InMemoryDataset()
        ds.init(batch_size=2)
        ds.set_filelist([str(p)])
        ds.load_into_memory()
        ds.local_shuffle()
        assert sum(b.shape[0] for b in ds) == 3

    def test_fleet_util_shard(self):
        import paddle_tpu.parallel as dist
        u = dist.fleet.UtilBase()
        files = [f"f{i}" for i in range(5)]
        assert u.get_file_shard(files) == files  # world of 1


class TestSeq2Seq:
    def test_beam_search_prefers_high_prob_tokens(self):
        """A cell whose logits always favour token 3 must decode 3s."""

        class Fixed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.dummy = nn.Linear(1, 1)

            def __call__(self, emb, states):
                import jax.numpy as jnp

                from paddle_tpu.core.tensor import wrap
                b = emb.shape[0]
                logits = jnp.tile(
                    jnp.array([[0., 0., 0., 5., 0., 0.]], jnp.float32),
                    (b, 1))
                return wrap(logits), states

        dec = nn.BeamSearchDecoder(Fixed(), start_token=0, end_token=5,
                                   beam_size=2,
                                   embedding_fn=nn.Embedding(6, 1))
        ids, lens = nn.dynamic_decode(
            dec, inits=pt.to_tensor(np.zeros((1, 1), "float32")),
            max_step_num=4)
        assert (ids.numpy()[0, 0] == 3).all()


class TestStaticTail:
    def test_fc_program_with_serialization(self):
        import paddle_tpu.static as static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8])
            h = static.nn.fc(x, 5)
        exe = static.Executor()
        static.run_startup()
        (hv,) = exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                        fetch_list=[h])
        assert hv.shape == (4, 5)
        blob = static.serialize_program([x], [h], program=main)
        meta = static.deserialize_program(blob)
        assert meta["feeds"] == ["x"]

    def test_accuracy_and_auc(self):
        import paddle_tpu.static as static
        acc = static.accuracy(
            pt.to_tensor(np.eye(4, 5, dtype="float32")),
            pt.to_tensor(np.array([[0], [1], [2], [4]], "int64")))
        assert 0.7 < float(acc.numpy()) <= 1.0
        a, _, _ = static.auc(
            pt.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8],
                                   [0.3, 0.7], [0.6, 0.4]], "float32")),
            pt.to_tensor(np.array([0, 1, 1, 0], "int64")))
        assert float(a.numpy()) == 1.0

    def test_sequence_ops(self):
        import paddle_tpu.static.nn as snn
        x = pt.to_tensor(np.arange(24, dtype="float32").reshape(2, 3, 4))
        assert snn.sequence_pool(x, "max").shape == [2, 4]
        assert snn.sequence_first_step(x).shape == [2, 4]
        rev = snn.sequence_reverse(x)
        np.testing.assert_allclose(rev.numpy()[:, 0], x.numpy()[:, -1])
        enum = snn.sequence_enumerate(
            pt.to_tensor(np.array([[1, 2, 3]], "int64")), 2)
        assert enum.shape == [1, 3, 2]


class TestAudioIO:
    def test_wav_roundtrip(self, tmp_path):
        sr = 8000
        sig = (0.5 * np.sin(np.linspace(0, 100, sr))).astype(
            "float32")[None]
        p = str(tmp_path / "t.wav")
        pt.audio.save(p, pt.to_tensor(sig), sr)
        meta = pt.audio.info(p)
        assert meta.sample_rate == sr and meta.num_channels == 1
        wav, sr2 = pt.audio.load(p)
        assert sr2 == sr
        np.testing.assert_allclose(wav.numpy(), sig, atol=2e-4)


class TestVisionTransformTail:
    def test_functional_vs_identity_invariants(self):
        import paddle_tpu.vision.transforms as T
        img = (np.random.rand(16, 16, 3) * 255).astype("float32")
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=1.0)
        ident = T.perspective(img, [(0, 0), (15, 0), (15, 15), (0, 15)],
                              [(0, 0), (15, 0), (15, 15), (0, 15)])
        np.testing.assert_allclose(ident, img, atol=1e-2)
        shifted = T.affine(img, translate=(2, 0))
        np.testing.assert_allclose(shifted[:, 3, 0], img[:, 1, 0],
                                   rtol=1e-4)
        e = T.erase(img, 2, 3, 4, 5, 0.0)
        assert e[2:6, 3:8].sum() == 0


def _spawn_target(marker):
    import os
    with open(marker + os.environ["PADDLE_TRAINER_ID"], "w") as f:
        f.write("ok")


class TestTopLevelModules:
    def test_hub_local_protocol(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def toy(scale=2):\n    'scaler'\n    return lambda x: x * scale\n")
        d = str(tmp_path)
        assert "toy" in pt.hub.list(d)
        assert "scaler" in pt.hub.help(d, "toy")
        assert pt.hub.load(d, "toy", scale=3)(2) == 6
        with pytest.raises(RuntimeError):
            pt.hub.load("owner/repo", "toy", source="github")

    def test_reader_decorators(self):
        import paddle_tpu.reader as reader
        r = lambda: iter(range(10))
        assert list(reader.firstn(r, 3)()) == [0, 1, 2]
        assert sorted(reader.shuffle(r, 4)()) == list(range(10))
        assert list(reader.map_readers(lambda a, b: a + b, r, r)())[:3] \
            == [0, 2, 4]
        assert len(list(reader.buffered(r, 2)())) == 10
        assert list(reader.chain(r, r)()) == list(range(10)) * 2

    def test_callbacks_namespace_and_wandb_fallback(self, tmp_path):
        cb = pt.callbacks.WandbCallback(dir=str(tmp_path))
        cb.on_train_begin()
        cb.on_epoch_end(0, {"loss": 1.25})
        import json
        rec = json.loads((tmp_path / "wandb_fallback.jsonl").read_text())
        assert rec["loss"] == 1.25 and rec["epoch"] == 0

    def test_cost_model(self):
        import paddle_tpu.static as static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3])
            _ = x * 2.0
        costs = pt.cost_model.CostModel().profile_measure(main)
        assert costs


class TestReaderRobustness:
    def test_buffered_surfaces_reader_errors(self):
        import paddle_tpu.reader as reader

        def bad():
            yield 1
            raise IOError("boom")

        with pytest.raises(IOError):
            list(reader.buffered(bad, 2)())

    def test_compose_alignment_check(self):
        import paddle_tpu.reader as reader
        with pytest.raises(reader.ComposeNotAligned):
            list(reader.compose(lambda: iter(range(3)),
                                lambda: iter(range(5)))())


class TestIncubateFused:
    def test_fused_multi_transformer_modes_and_cache(self):
        import paddle_tpu.incubate.nn.functional as IF
        D, L, H = 16, 2, 4
        mk = lambda *s: pt.to_tensor(
            np.random.randn(*s).astype("float32") * 0.05)
        args = dict(
            ln_scales=[mk(D) + 1.0 for _ in range(L)],
            ln_biases=[mk(D) for _ in range(L)],
            qkv_weights=[mk(D, 3 * D) for _ in range(L)],
            qkv_biases=[mk(3 * D) for _ in range(L)],
            linear_weights=[mk(D, D) for _ in range(L)],
            linear_biases=[mk(D) for _ in range(L)],
            ffn_ln_scales=[mk(D) + 1.0 for _ in range(L)],
            ffn_ln_biases=[mk(D) for _ in range(L)],
            ffn1_weights=[mk(D, 4 * D) for _ in range(L)],
            ffn1_biases=[mk(4 * D) for _ in range(L)],
            ffn2_weights=[mk(4 * D, D) for _ in range(L)],
            ffn2_biases=[mk(D) for _ in range(L)],
            trans_qkvw=False, num_heads=H)
        x = pt.to_tensor(np.random.randn(1, 6, D).astype("float32"))
        out = IF.fused_multi_transformer(x, **args)
        out_post = IF.fused_multi_transformer(x, pre_layer_norm=False,
                                              **args)
        assert out.shape == [1, 6, D]
        assert not np.allclose(out.numpy(), out_post.numpy())
        with pytest.raises(ValueError):
            IF.fused_multi_transformer(x, **{**args, "num_heads": None})
        empty = [pt.to_tensor(np.zeros((2, 1, H, 0, D // H), "float32"))
                 for _ in range(L)]
        prefill, caches = IF.fused_multi_transformer(x, cache_kvs=empty,
                                                     **args)
        step = pt.to_tensor(np.random.randn(1, 1, D).astype("float32"))
        dec, caches2 = IF.fused_multi_transformer(step, cache_kvs=caches,
                                                  **args)
        assert dec.shape == [1, 1, D] and caches2[0].shape[3] == 7

    def test_fused_ec_moe_routes_and_trains(self):
        from paddle_tpu.incubate.nn import FusedEcMoe
        moe = FusedEcMoe(16, 32, 4)
        z = pt.to_tensor(np.random.randn(2, 8, 16).astype("float32"),
                         stop_gradient=False)
        out = moe(z)
        out.sum().backward()
        assert np.isfinite(moe.w1.grad.numpy()).all()
        logits = pt.to_tensor(np.random.randn(2, 8, 4).astype("float32"))
        out2 = moe(z, gate_logits=logits)
        assert not np.allclose(out.numpy(), out2.numpy())

    def test_fused_linear_and_bias_dropout_ln(self):
        from paddle_tpu.incubate.nn import (
            FusedBiasDropoutResidualLayerNorm, FusedLinear)
        fl = FusedLinear(8, 16)
        x = pt.to_tensor(np.random.randn(2, 8).astype("float32"))
        assert fl(x).shape == [2, 16]
        bdr = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
        y = pt.to_tensor(np.random.randn(2, 4, 8).astype("float32"))
        assert bdr(y, y).shape == [2, 4, 8]


class TestStaticCacheDecode:
    def test_static_cache_matches_growing_cache(self):
        """time_step path (reference fused_multi_transformer_op time_step
        input): fixed-shape cache + dynamic_update_slice must produce the
        same tokens as the growing-concat path."""
        import paddle_tpu.incubate.nn.functional as IF
        rng = np.random.default_rng(0)
        D, L, H, T_MAX = 16, 2, 4, 12
        mk = lambda *s: pt.to_tensor(
            rng.standard_normal(s).astype("float32") * 0.05)
        args = dict(
            ln_scales=[mk(D) + 1.0 for _ in range(L)],
            ln_biases=[mk(D) for _ in range(L)],
            qkv_weights=[mk(D, 3 * D) for _ in range(L)],
            qkv_biases=[mk(3 * D) for _ in range(L)],
            linear_weights=[mk(D, D) for _ in range(L)],
            linear_biases=[mk(D) for _ in range(L)],
            ffn_ln_scales=[mk(D) + 1.0 for _ in range(L)],
            ffn_ln_biases=[mk(D) for _ in range(L)],
            ffn1_weights=[mk(D, 4 * D) for _ in range(L)],
            ffn1_biases=[mk(4 * D) for _ in range(L)],
            ffn2_weights=[mk(4 * D, D) for _ in range(L)],
            ffn2_biases=[mk(D) for _ in range(L)],
            trans_qkvw=False, num_heads=H)
        x = pt.to_tensor(rng.standard_normal((1, 4, D)).astype("float32"))
        steps = [pt.to_tensor(rng.standard_normal((1, 1, D))
                              .astype("float32")) for _ in range(3)]

        # growing-concat reference
        empty = [pt.to_tensor(np.zeros((2, 1, H, 0, D // H), "float32"))
                 for _ in range(L)]
        ref_out, caches = IF.fused_multi_transformer(
            x, cache_kvs=empty, **args)
        ref_tokens = []
        for s in steps:
            o, caches = IF.fused_multi_transformer(s, cache_kvs=caches,
                                                   **args)
            ref_tokens.append(o.numpy())

        # static-cache path: prefill at t=0, decode at t=4,5,6
        fixed = [pt.to_tensor(np.zeros((2, 1, H, T_MAX, D // H),
                                       "float32")) for _ in range(L)]
        out0, fixed = IF.fused_multi_transformer(
            x, cache_kvs=fixed, time_step=0, **args)
        np.testing.assert_allclose(out0.numpy(), ref_out.numpy(),
                                   rtol=1e-4, atol=1e-5)
        for t, (s, want) in enumerate(zip(steps, ref_tokens)):
            o, fixed = IF.fused_multi_transformer(
                s, cache_kvs=fixed, time_step=4 + t, **args)
            assert fixed[0].shape[3] == T_MAX, "cache must stay fixed-size"
            np.testing.assert_allclose(o.numpy(), want, rtol=1e-4,
                                       atol=1e-5)


def test_static_cache_decode_honors_attn_mask():
    """code-review r4: the time_step path must combine a caller-supplied
    attn_mask (e.g. left-padding) with the validity mask."""
    import paddle_tpu.incubate.nn.functional as IF
    rng = np.random.default_rng(4)
    D, L, H, T_MAX = 16, 1, 4, 8
    mk = lambda *s: pt.to_tensor(
        rng.standard_normal(s).astype("float32") * 0.05)
    args = dict(
        ln_scales=[mk(D) + 1.0], ln_biases=[mk(D)],
        qkv_weights=[mk(D, 3 * D)], qkv_biases=[mk(3 * D)],
        linear_weights=[mk(D, D)], linear_biases=[mk(D)],
        ffn_ln_scales=[mk(D) + 1.0], ffn_ln_biases=[mk(D)],
        ffn1_weights=[mk(D, 4 * D)], ffn1_biases=[mk(4 * D)],
        ffn2_weights=[mk(4 * D, D)], ffn2_biases=[mk(D)],
        trans_qkvw=False, num_heads=H)
    x = pt.to_tensor(rng.standard_normal((1, 1, D)).astype("float32"))
    fixed = [pt.to_tensor(np.zeros((2, 1, H, T_MAX, D // H), "float32"))]
    # pretend positions 0-2 are left-padding: mask them out
    pad_mask = np.zeros((1, 1, 1, T_MAX), "float32")
    pad_mask[..., :3] = -1e9
    o_masked, _ = IF.fused_multi_transformer(
        x, cache_kvs=[c for c in fixed], time_step=4,
        attn_mask=pt.to_tensor(pad_mask), **args)
    o_plain, _ = IF.fused_multi_transformer(
        x, cache_kvs=[c for c in fixed], time_step=4, **args)
    # cache holds zeros; with a nonzero current token the masked and
    # unmasked attention normalize over different support -> different out
    assert not np.allclose(o_masked.numpy(), o_plain.numpy())
