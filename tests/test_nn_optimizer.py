"""nn.Layer / functional / optimizer tests (numpy-oracle style)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_layer_forward_backward():
    layer = nn.Linear(4, 3)
    x = pt.to_tensor(np.random.randn(2, 4).astype(np.float32))
    out = layer(x)
    assert out.shape == [2, 3]
    expected = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)
    loss = out.sum()
    loss.backward()
    assert layer.weight.grad is not None
    np.testing.assert_allclose(layer.bias.grad.numpy(), [2.0, 2.0, 2.0],
                               rtol=1e-6)


def test_layer_containers_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = model.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    params = model.parameters()
    assert len(params) == 4
    # roundtrip
    sd2 = {k: pt.to_tensor(v.numpy() * 0 + 1.0) for k, v in sd.items()}
    model.set_state_dict(sd2)
    np.testing.assert_allclose(model[0].weight.numpy(),
                               np.ones((4, 8), np.float32))


def test_layernorm_matches_numpy():
    x = np.random.randn(2, 5, 8).astype(np.float32)
    ln = nn.LayerNorm(8)
    out = ln(pt.to_tensor(x)).numpy()
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    np.testing.assert_allclose(out, (x - mean) / np.sqrt(var + 1e-5),
                               rtol=1e-4, atol=1e-5)


def test_rmsnorm_matches_numpy():
    x = np.random.randn(2, 6, 16).astype(np.float32)
    layer = nn.RMSNorm(16)
    out = layer(pt.to_tensor(x)).numpy()
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    # grad flows
    y = layer(pt.to_tensor(x, stop_gradient=False))
    y.sum().backward()
    assert layer.weight.grad is not None


def test_embedding_and_grad():
    emb = nn.Embedding(10, 4)
    idx = pt.to_tensor(np.array([1, 3, 1]), dtype="int32")
    out = emb(idx)
    assert out.shape == [3, 4]
    out.sum().backward()
    g = emb.weight.grad.numpy()
    assert g[1].sum() == pytest.approx(8.0)  # row 1 used twice
    assert g[3].sum() == pytest.approx(4.0)
    assert g[0].sum() == 0.0


def test_conv2d_matches_scipy_like():
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    conv = nn.Conv2D(3, 5, 3, padding=1)
    out = conv(pt.to_tensor(x))
    assert out.shape == [1, 5, 8, 8]
    out.sum().backward()
    assert conv.weight.grad is not None


def test_dropout_train_eval():
    x = pt.ops.ones([1000])
    drop = nn.Dropout(0.5)
    y = drop(x)
    frac = (y.numpy() == 0).mean()
    assert 0.3 < frac < 0.7
    drop.eval()
    np.testing.assert_array_equal(drop(x).numpy(), x.numpy())


def test_cross_entropy_matches_numpy():
    logits = np.random.randn(4, 7).astype(np.float32)
    labels = np.array([0, 3, 6, 2])
    out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels)).numpy()
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_sgd_converges_linear_regression():
    np.random.seed(0)
    w_true = np.array([[2.0], [-3.0]], np.float32)
    x = np.random.randn(64, 2).astype(np.float32)
    y = x @ w_true
    model = nn.Linear(2, 1)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    for _ in range(200):
        pred = model(pt.to_tensor(x))
        loss = F.mse_loss(pred, pt.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(model.weight.numpy(), w_true, atol=0.05)


def test_adamw_step_and_state():
    model = nn.Linear(3, 3)
    opt = pt.optimizer.AdamW(learning_rate=0.01,
                             parameters=model.parameters(),
                             weight_decay=0.01)
    w0 = model.weight.numpy().copy()
    out = model(pt.to_tensor(np.ones((2, 3), np.float32)))
    out.sum().backward()
    opt.step()
    assert not np.allclose(model.weight.numpy(), w0)
    sd = opt.state_dict()
    assert sd["step"] == 1 and "state" in sd


def test_grad_clip_global_norm():
    model = nn.Linear(4, 4)
    clip = nn.ClipGradByGlobalNorm(0.001)
    opt = pt.optimizer.SGD(learning_rate=1.0, parameters=model.parameters(),
                           grad_clip=clip)
    out = model(pt.to_tensor(np.ones((2, 4), np.float32) * 100))
    (out * 1000).sum().backward()
    w0 = model.weight.numpy().copy()
    opt.step()
    delta = np.abs(model.weight.numpy() - w0)
    # update magnitude bounded by lr * clip_norm
    assert np.sqrt((delta ** 2).sum()) <= 0.0011


def test_lr_scheduler_with_optimizer():
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                      gamma=0.5)
    model = nn.Linear(2, 2)
    opt = pt.optimizer.SGD(learning_rate=sched,
                           parameters=model.parameters())
    assert opt.get_lr() == pytest.approx(0.1)
    sched.step()
    sched.step()
    assert opt.get_lr() == pytest.approx(0.05)


def test_amp_autocast_bf16():
    with pt.amp.auto_cast(dtype="bfloat16"):
        a = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
        b = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
        out = a @ b
        assert out.dtype == pt.bfloat16
        s = pt.ops.softmax(out)  # blacklisted -> fp32
        assert s.dtype == pt.float32


def test_grad_scaler_fp16_semantics():
    model = nn.Linear(2, 2)
    scaler = pt.amp.GradScaler(init_loss_scaling=1024.0)
    out = model(pt.to_tensor(np.ones((1, 2), np.float32)))
    loss = out.sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    opt = pt.optimizer.SGD(learning_rate=0.0, parameters=model.parameters())
    scaler.step(opt)
    scaler.update()
    # after unscale_, grads are back at true scale
    np.testing.assert_allclose(model.bias.grad.numpy(), [1.0, 1.0], rtol=1e-5)


def test_functional_call_and_jit_step():
    import jax
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    params = model.raw_params()
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 1).astype(np.float32)

    from paddle_tpu.jit import functional_call

    def loss_fn(ps):
        pred = functional_call(model, ps, pt.to_tensor(x))
        import jax.numpy as jnp
        return jnp.mean((pred - y) ** 2)

    grads = jax.grad(loss_fn)(params)
    assert set(grads) == set(params)
    # eager grads must match functional grads
    pred = model(pt.to_tensor(x))
    loss = F.mse_loss(pred, pt.to_tensor(y))
    loss.backward()
    eager_g = model[0].weight.grad.numpy()
    np.testing.assert_allclose(grads["0.weight"], eager_g, rtol=1e-4,
                               atol=1e-6)


def test_train_step_fn_end_to_end():
    model = nn.Sequential(nn.Linear(4, 16), nn.GELU(), nn.Linear(16, 1))
    opt = pt.optimizer.AdamW(learning_rate=0.01,
                             parameters=model.parameters())
    import jax.numpy as jnp

    def loss_fn(pred, label):
        return jnp.mean((pred - label) ** 2)

    step = pt.jit.train_step_fn(model, loss_fn, opt)
    params = model.raw_params()
    init_fn, _ = opt.functional()
    state = init_fn(params)
    x = np.random.randn(32, 4).astype(np.float32)
    y = (x.sum(-1, keepdims=True) * 0.5).astype(np.float32)
    losses = []
    for i in range(60):
        loss, params, state = step(params, state,
                                   {"inputs": (x,), "labels": (y,)}, i + 1)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1
