"""Optimistic admission with bit-exact preemption (ISSUE 8).

Contracts under KV-pool pressure with ``admission="optimistic"``:

- admission reserves prompt + headroom only; decode grows page-by-page
  (``PagedKVCache.grow_slot``) and preempts victims when the pool runs
  dry — lowest priority class, then fewest tokens generated,
  deterministic ties, the grower itself included;
- preempted requests park, re-admit, and REPLAY bit-exactly (resolved
  seed + prefix-cache-assisted recompute): outputs are identical to an
  unpressured full-extent run, greedy AND seeded-sampled, and
  streaming callbacks never re-send a delivered chunk;
- ``PreemptedError`` never escapes to a waiter, no page ever leaks,
  ``pool_balance()`` returns to baseline once drained;
- deadlines keep their promise while parked (partial result, pages
  stay freed, no decode resumed) and ``stop(drain=True)`` finishes
  parked requests before shutdown.

Everything runs on the StubModel double (closed-form oracle, no
transformer compiles) — tier-1 fast."""
import threading

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import (
    ContinuousBatchingServer, PoolBalance, PreemptionPolicy)
from paddle_tpu.reliability import (CircuitBreaker, FaultInjector,
                                    PreemptedError, ReliabilityError,
                                    RetryPolicy, faults)
from paddle_tpu.telemetry import FakeClock


def _prompts(n, rng_seed=3, lo=4, hi=12):
    rng = np.random.default_rng(rng_seed)
    return [rng.integers(0, 16, (int(k),)).astype(np.int32)
            for k in rng.integers(lo, hi, (n,))]


def _server(admission="optimistic", num_pages=9, max_slots=4, fi=None,
            **kw):
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("retry_policy", RetryPolicy(base_delay_s=0.0,
                                              jitter=0.0))
    kw.setdefault("breaker", CircuitBreaker(failure_threshold=10_000))
    return ContinuousBatchingServer(
        StubModel(), max_slots=max_slots, cache_backend="paged",
        num_pages=num_pages, admission=admission, fault_injector=fi,
        **kw)


def _drive(srv, max_ticks=20_000):
    """Single-threaded supervisor stand-in: retry every failed tick
    (injected kv.grow / server.preempt faults surface as tick errors
    the supervised loop would back off and retry)."""
    ticks = 0
    while True:
        with srv._lock:
            busy = srv._busy_locked()
        if not busy:
            return
        try:
            srv.step()
        except Exception:
            pass
        ticks += 1
        assert ticks < max_ticks, "drive did not converge"


def _pressured_run(admission, num_pages, do_sample=False, fi=None,
                   budget=28, n=10, seeds=None, on_token=None):
    srv = _server(admission, num_pages=num_pages, do_sample=do_sample,
                  fi=fi, seed=5)
    prompts = _prompts(n)
    rids = []
    for i, p in enumerate(prompts):
        kw = {"seed": seeds[i]} if seeds is not None else {}
        if on_token is not None:
            kw["on_token"] = on_token
        rids.append(srv.submit(p, max_new_tokens=budget, **kw))
    _drive(srv)
    return srv, prompts, rids, dict(srv._results)


class TestOptimisticAdmission:
    def test_greedy_bit_exact_under_pressure(self):
        """Tentpole acceptance: a pool 2.5x too small for the fleet's
        full extents still completes EVERY request bit-exactly (vs the
        oracle AND vs an unpressured reserve run), with real
        preemptions, and returns the pool to baseline."""
        srv, prompts, rids, outs = _pressured_run("optimistic", 9)
        srv2, _, rids2, outs2 = _pressured_run("reserve", 49)
        for rid, rid2, p in zip(rids, rids2, prompts):
            np.testing.assert_array_equal(outs[rid], stub_tokens(p, 28))
            np.testing.assert_array_equal(outs[rid], outs2[rid2])
        assert srv.stats["preemptions"] > 0, "pool never pressured"
        assert srv.stats["preempt_resumed"] == srv.stats["preemptions"]
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0
        assert bal[0] + bal[2] + bal[3] == srv._kv.num_pages - 1

    def test_sampled_bit_exact_under_pressure(self):
        """Seeded-sampled parity: the replayed chain restarts from the
        same resolved seed, so a preempted request draws the identical
        tokens an unpressured run draws."""
        seeds = list(range(100, 110))
        srv, _, rids, outs = _pressured_run("optimistic", 9,
                                            do_sample=True, seeds=seeds)
        _, _, rids2, outs2 = _pressured_run("reserve", 49,
                                            do_sample=True, seeds=seeds)
        assert srv.stats["preemptions"] > 0
        for rid, rid2 in zip(rids, rids2):
            np.testing.assert_array_equal(outs[rid], outs2[rid2])

    def test_streaming_never_resends_across_preemption(self):
        """on_token across preemption: the concatenated stream equals
        the final result exactly — the replay below the old offset is
        suppressed, the tail streams once."""
        chunks = {}

        def on_token(rid, toks):
            chunks.setdefault(rid, []).append(np.asarray(toks))

        srv, prompts, rids, outs = _pressured_run(
            "optimistic", 9, on_token=on_token)
        assert srv.stats["preemptions"] > 0
        for rid, p in zip(rids, prompts):
            got = np.concatenate(chunks[rid])
            np.testing.assert_array_equal(got, outs[rid])
            np.testing.assert_array_equal(got, stub_tokens(p, 28))

    def test_grow_and_headroom_counters(self):
        srv, _, _, _ = _pressured_run("optimistic", 9)
        assert srv.stats["grow_pages"] > 0
        assert srv.stats["headroom_pages"] > 0
        assert srv._kv.grown_total == srv.stats["grow_pages"]
        assert srv._kv.telemetry_stats()["grown_total"] \
            == srv.stats["grow_pages"]
        # reserve mode never grows and reserves no headroom
        srv2, _, _, _ = _pressured_run("reserve", 49)
        assert srv2.stats["grow_pages"] == 0
        assert srv2.stats["headroom_pages"] == 0
        assert srv2.stats["preemptions"] == 0

    def test_pool_balance_keeps_4_tuple_with_attrs(self):
        srv = _server(num_pages=9)
        bal = srv.pool_balance()
        assert isinstance(bal, PoolBalance)
        free, live, pinned, cached = bal          # 4-way unpack intact
        assert (free, live, pinned, cached) == (8, 0, 0, 0)
        assert bal.preempted == 0 and bal.preemptions == 0

    def test_optimistic_dense_raises_with_roadmap_pointer(self):
        with pytest.raises(NotImplementedError, match="ROADMAP"):
            ContinuousBatchingServer(StubModel(), max_slots=2,
                                     max_cache_len=32,
                                     admission="optimistic")

    def test_config_guards(self):
        with pytest.raises(ValueError, match="admission"):
            _server(admission="eager")
        with pytest.raises(ValueError, match="headroom_pages"):
            _server(headroom_pages=-1)

    def test_submit_still_bounds_full_extent(self):
        """Optimistic admission keeps the per-request feasibility
        check: a request whose FULL extent cannot fit the pool on its
        own must fail at submit (the preemption leader could never
        finish it)."""
        srv = _server(num_pages=4)        # 3 usable pages = 24 tokens
        with pytest.raises(ValueError, match="pages"):
            srv.submit(np.arange(8, dtype=np.int32) % 16,
                       max_new_tokens=24)

    def test_victim_order_priority_then_fewest_tokens(self):
        """Every pick obeys the victim order: the chosen slot is in
        the LOWEST priority class present, and within that class has
        the fewest tokens generated (ties to the youngest rid) — so a
        low-priority request is always sacrificed before a
        high-priority one whenever both are resident."""
        picks = []       # (victim_key, all candidate keys) per pick

        class Recording(PreemptionPolicy):
            def pick(self, grower, candidates):
                v = super().pick(grower, candidates)
                if v is not None:
                    by_slot = dict(candidates)
                    picks.append(
                        (self.key(v, by_slot[v]),
                         [self.key(s, st) for s, st in candidates],
                         by_slot[v].priority,
                         {st.priority for _, st in candidates}))
                return v

        srv = _server(num_pages=9, max_slots=2,
                      preemption_policy=Recording())
        prompts = _prompts(4, rng_seed=9)
        # stage a low-priority resident first, then the high class
        low = [srv.submit(prompts[0], max_new_tokens=28, priority=0)]
        srv.step()
        low.append(srv.submit(prompts[1], max_new_tokens=28,
                              priority=0))
        high = [srv.submit(p, max_new_tokens=28, priority=1)
                for p in prompts[2:]]
        _drive(srv)
        assert picks, "no preemption happened; shrink the pool"
        mixed = 0
        for vkey, cand_keys, vpri, cand_pris in picks:
            assert vkey == min(cand_keys)        # the policy's order
            assert vpri == min(cand_pris)        # lowest class first
            if len(cand_pris) > 1:
                mixed += 1
        assert mixed > 0, "never picked among mixed priority classes"
        for rid, p in zip(low + high, prompts):
            np.testing.assert_array_equal(srv._results[rid],
                                          stub_tokens(p, 28))

    def test_resumed_victim_keeps_pre_preemption_seniority(self):
        """ISSUE 8 regression: a resumed slot early in its replay must
        rank by its TRUE partial (the work it already did once), not
        the raw replay progress — otherwise every squeeze re-picks the
        same just-resumed request and throws its replay away again."""
        from paddle_tpu.inference.continuous_batching import _Slot
        policy = PreemptionPolicy()
        resumed = _Slot(0, np.arange(4, dtype=np.int32), 4, 48)
        resumed.emitted = [1, 2]               # replay barely started
        resumed.replayed = tuple(range(40))    # 40 tokens done pre-park
        fresh = _Slot(1, np.arange(4, dtype=np.int32), 4, 48)
        fresh.emitted = list(range(10))
        # the fresh request (10 tokens of work) loses to the resumed
        # one's 40-token seniority
        assert policy.pick(0, [(0, resumed), (1, fresh)]) == 1
        # priority class still dominates seniority
        fresh.priority = 1
        assert policy.pick(0, [(0, resumed), (1, fresh)]) == 0

    def test_priority_aware_admission_order(self):
        """Admission prefers higher priority classes; same class keeps
        submit order (priority-aware FIFO)."""
        srv = _server(num_pages=17, max_slots=1)
        prompts = _prompts(3, rng_seed=11)
        r_low = srv.submit(prompts[0], max_new_tokens=6, priority=0)
        r_mid = srv.submit(prompts[1], max_new_tokens=6, priority=1)
        r_high = srv.submit(prompts[2], max_new_tokens=6, priority=2)
        _drive(srv)
        # dict order == completion order (one slot serializes them)
        assert list(srv._results) == [r_high, r_mid, r_low]

    def test_grower_parks_itself_when_least_valuable(self):
        """When the growing slot ranks below every other live slot it
        preempts ITSELF (PreemptedError stays internal) — nobody more
        valuable is evicted, and the request still completes."""
        srv = _server(num_pages=9, max_slots=2)
        prompts = _prompts(2, rng_seed=13)
        r_low = srv.submit(prompts[0], max_new_tokens=28, priority=0)
        r_high = srv.submit(prompts[1], max_new_tokens=28, priority=1)
        _drive(srv)
        assert srv.stats["preemptions"] > 0
        np.testing.assert_array_equal(srv._results[r_low],
                                      stub_tokens(prompts[0], 28))
        np.testing.assert_array_equal(srv._results[r_high],
                                      stub_tokens(prompts[1], 28))
        assert not srv.failures


class TestPreemptedLifecycle:
    def _park_one(self, clock=None, deadline_s=None):
        """A server with one request PARKED on the preempted queue and
        one still decoding; returns (server, {rid: prompt}, parked
        rid). Decodes a few ticks for a real partial, then preempts
        through the production teardown (an organically-triggered
        victim is usually re-admitted within the same tick, which is
        exactly what these lifecycle tests must interrupt)."""
        srv = _server(num_pages=17, max_slots=2, clock=clock)
        prompts = _prompts(2, rng_seed=13)
        kw = {} if deadline_s is None else {"deadline_s": deadline_s}
        victim = srv.submit(prompts[0], max_new_tokens=28, **kw)
        other = srv.submit(prompts[1], max_new_tokens=28)
        for _ in range(5):
            srv.step()
        with srv._lock:
            slot = next(s for s in range(srv.max_slots)
                        if srv._slots[s] is not None
                        and srv._slots[s].rid == victim)
            srv._preempt_slot_locked(slot)
            assert srv._preempted and srv._preempted[0].rid == victim
        return srv, dict(zip((victim, other), prompts)), victim

    def test_deadline_expiry_while_parked(self):
        """ISSUE 8 satellite: a request whose deadline passes while it
        sits on the preempted queue resolves like mid-decode expiry —
        its pre-preemption partial is the result, its pages stay
        donated/freed, and decode is NEVER resumed for it."""
        fc = FakeClock()
        srv, by_rid, parked = self._park_one(clock=fc, deadline_s=60.0)
        resumed_before = srv.stats["preempt_resumed"]
        parked_partial = list(srv._preempted[0].emitted)
        fc.advance(61.0)
        _drive(srv)
        # the parked request expired with its partial recorded...
        np.testing.assert_array_equal(srv._results[parked],
                                      parked_partial)
        assert len(parked_partial) < 28          # genuinely partial
        # ...decode never resumed for it, and the survivor finished
        assert srv.stats["preempt_resumed"] == resumed_before
        other = next(r for r in by_rid if r != parked)
        np.testing.assert_array_equal(srv._results[other],
                                      stub_tokens(by_rid[other], 28))
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0
        assert bal[0] + bal[2] + bal[3] == srv._kv.num_pages - 1

    def test_cancel_while_parked_records_partial(self):
        srv, by_rid, parked = self._park_one()
        parked_partial = list(srv._preempted[0].emitted)
        assert srv.cancel(parked)
        np.testing.assert_array_equal(srv._results[parked],
                                      parked_partial)
        _drive(srv)
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0

    def test_stop_drain_finishes_parked_requests(self):
        """ISSUE 8 satellite: ``stop(drain=True)`` counts parked
        requests as pending work — the drain re-admits and completes
        them before the thread exits."""
        srv = _server(num_pages=9, max_slots=2).start()
        prompts = _prompts(4, rng_seed=13)
        rids = [srv.submit(p, max_new_tokens=28) for p in prompts]
        srv.stop(drain=True, timeout=120.0)
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(srv._results[rid],
                                          stub_tokens(p, 28))
        assert srv.stats["preemptions"] > 0, "drain saw no pressure"
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0

    def test_hard_stop_flushes_parked_partial(self):
        srv, by_rid, parked = self._park_one()
        parked_partial = list(srv._preempted[0].emitted)
        srv.stop(drain=False)                 # no thread: just flushes
        np.testing.assert_array_equal(srv._results[parked],
                                      parked_partial)
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0

    def test_evacuate_flush_partials_covers_parked(self):
        """A dead replica's parked preempted requests flush their
        partials to waiters exactly like mid-decode slots (they are
        not replayable elsewhere without double-streaming)."""
        srv, by_rid, parked = self._park_one()
        parked_partial = list(srv._preempted[0].emitted)
        harvested = srv.evacuate(flush_partials=True)
        assert all(item.rid != parked for item in harvested)
        np.testing.assert_array_equal(srv._results[parked],
                                      parked_partial)
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0


@pytest.mark.chaos
class TestPreemptionChaos:
    def test_grow_fault_storm_bit_exact_no_leaks(self):
        """ISSUE 8 acceptance: a 30% ``kv.grow`` fault storm over an
        undersized pool — every submitted request COMPLETES (nothing
        fails, nothing wedges, zero ``PreemptedError`` escapes), the
        outputs are bit-identical to an unpressured full-extent run
        (greedy and seeded-sampled), zero pages leak, and
        ``pool_balance()`` returns to baseline."""
        for do_sample in (False, True):
            seeds = list(range(200, 210))
            fi = (FaultInjector(seed=77)
                  .on(faults.KV_GROW, probability=0.3)
                  .on(faults.SERVER_PREEMPT, probability=0.2))
            srv = _server("optimistic", num_pages=9, fi=fi,
                          do_sample=do_sample).start()
            prompts = _prompts(10)
            rids = [srv.submit(p, max_new_tokens=28, seed=seeds[i])
                    for i, p in enumerate(prompts)]
            outs, escapes = {}, []
            for rid in rids:
                try:
                    outs[rid] = srv.wait(rid, timeout=240)
                except ReliabilityError as e:     # typed at least...
                    if isinstance(e, PreemptedError):
                        escapes.append(e)         # ...but NEVER this
            assert not escapes, f"PreemptedError escaped: {escapes}"
            assert len(outs) == len(rids), "a request failed or wedged"
            assert fi.fired() > 0
            assert srv.stats["preemptions"] > 0
            srv.stop()
            # unpressured reference run, same seeds
            ref = _server("reserve", num_pages=49,
                          do_sample=do_sample)
            rref = [ref.submit(p, max_new_tokens=28, seed=seeds[i])
                    for i, p in enumerate(prompts)]
            ref_outs = ref.run()
            for rid, rid2 in zip(rids, rref):
                np.testing.assert_array_equal(outs[rid], ref_outs[rid2])
            bal = srv.pool_balance()
            assert bal[1] == 0, f"leaked {bal[1]} pages"
            assert bal.preempted == 0
            assert bal[0] + bal[2] + bal[3] == srv._kv.num_pages - 1

    def test_same_seed_identical_trace_and_state(self):
        """Determinism: same chaos seed, same submissions => identical
        injection trace, results, and final pool balance — preemption
        decisions included (deterministic victim ties)."""
        def run_once():
            fi = (FaultInjector(seed=4242)
                  .on(faults.KV_GROW, probability=0.25)
                  .on(faults.SERVER_PREEMPT, probability=0.15))
            srv = _server("optimistic", num_pages=9, fi=fi)
            for p in _prompts(8, rng_seed=21):
                srv.submit(p, max_new_tokens=24)
            _drive(srv)
            results = {r: tuple(int(x) for x in v)
                       for r, v in srv._results.items()}
            return (list(fi.trace), results, tuple(srv.pool_balance()),
                    srv.stats["preemptions"])

        a, b = run_once(), run_once()
        assert a == b
        assert a[0], "deterministic run injected nothing"
        assert a[3] > 0, "deterministic run never preempted"

    def test_mixed_alloc_evict_grow_storm_converges(self):
        """kv.grow faults compose with the existing alloc/evict chaos:
        admission deferrals, aborted reclaim sweeps, and preemption all
        interleave — still zero failed requests, zero leaks."""
        fi = (FaultInjector(seed=9)
              .on(faults.KV_GROW, probability=0.2)
              .on(faults.PAGE_ALLOC, probability=0.1)
              .on(faults.PREFIX_EVICT, probability=0.2))
        srv = _server("optimistic", num_pages=9, fi=fi)
        prompts = _prompts(8, rng_seed=31)
        rids = [srv.submit(p, max_new_tokens=20) for p in prompts]
        _drive(srv)
        assert fi.fired() > 0
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(srv._results[rid],
                                          stub_tokens(p, 20))
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0
        assert bal[0] + bal[2] + bal[3] == srv._kv.num_pages - 1


# ----------------------------------------------------------------- bench
@pytest.mark.slow
@pytest.mark.bench
class TestPreemptionBenchSmoke:
    def test_preemption_bench_asserts_concurrency_win(self):
        """Smoke-run benchmarks/preemption_bench.py at toy scale: it
        must complete, verify outputs bit-exact, and its own >= 1.5x
        effective-concurrency assert must hold."""
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks"))
        import preemption_bench
        out = preemption_bench.main(["--requests", "12", "--slots", "4",
                                     "--pool-pages", "10"])
        assert out["ratio"] >= 1.5
        by = {m["mode"]: m for m in out["modes"]}
        assert by["optimistic"]["preemptions"] >= 0
        assert by["reserve"]["grow_pages"] == 0
