"""paddle.fft / paddle.signal / paddle.regularizer tests (numpy oracle)."""
import numpy as np
import pytest

import paddle_tpu as pt


class TestFFT:
    def test_fft_ifft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(8).astype(np.float32)
        X = pt.fft.fft(pt.to_tensor(x))
        np.testing.assert_allclose(np.asarray(X._value),
                                   np.fft.fft(x), rtol=1e-4, atol=1e-4)
        back = pt.fft.ifft(X)
        np.testing.assert_allclose(np.asarray(back._value).real, x,
                                   rtol=1e-4, atol=1e-5)

    def test_rfft_irfft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(16).astype(np.float32)
        X = pt.fft.rfft(pt.to_tensor(x))
        np.testing.assert_allclose(np.asarray(X._value), np.fft.rfft(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            pt.fft.irfft(X).numpy(), x, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("norm", ["backward", "forward", "ortho"])
    def test_norms(self, norm):
        x = np.arange(8, dtype=np.float32)
        got = np.asarray(pt.fft.fft(pt.to_tensor(x), norm=norm)._value)
        ref = np.fft.fft(x, norm=None if norm == "backward" else norm)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_fft2_and_fftn(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(pt.fft.fft2(pt.to_tensor(x))._value),
            np.fft.fft2(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(pt.fft.rfftn(pt.to_tensor(x))._value),
            np.fft.rfftn(x), rtol=1e-4, atol=1e-4)

    def test_hfft_ihfft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(9).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(pt.fft.hfft(pt.to_tensor(x))._value),
            np.fft.hfft(x), rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(pt.fft.ihfft(pt.to_tensor(x))._value),
            np.fft.ihfft(x), rtol=1e-4, atol=1e-4)

    def test_freq_shift(self):
        np.testing.assert_allclose(pt.fft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5), rtol=1e-6)
        np.testing.assert_allclose(pt.fft.rfftfreq(8).numpy(),
                                   np.fft.rfftfreq(8), rtol=1e-6)
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(
            pt.fft.fftshift(pt.to_tensor(x)).numpy(), np.fft.fftshift(x))
        np.testing.assert_allclose(
            pt.fft.ifftshift(pt.to_tensor(x)).numpy(),
            np.fft.ifftshift(x))

    def test_invalid_norm_raises(self):
        with pytest.raises(ValueError, match="invalid norm"):
            pt.fft.fft(pt.to_tensor(np.ones(4, np.float32)), norm="bad")


class TestSignal:
    def test_frame(self):
        x = np.arange(10, dtype=np.float32)
        f = pt.signal.frame(pt.to_tensor(x), 4, 2).numpy()
        assert f.shape == (4, 4)  # [frame_len, n_frames]
        np.testing.assert_allclose(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[:, 1], [2, 3, 4, 5])

    def test_overlap_add_inverts_frame_nonoverlap(self):
        x = np.arange(12, dtype=np.float32)
        f = pt.signal.frame(pt.to_tensor(x), 4, 4)
        back = pt.signal.overlap_add(f, 4).numpy()
        np.testing.assert_allclose(back, x)

    def test_overlap_add_sums_overlaps(self):
        frames = np.ones((3, 2), np.float32)  # [frame_len, n_frames]
        out = pt.signal.overlap_add(pt.to_tensor(frames), 1).numpy()
        np.testing.assert_allclose(out, [1, 2, 2, 1])

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 512).astype(np.float32)
        from paddle_tpu.audio.functional import get_window
        win = get_window("hann", 128)
        spec = pt.signal.stft(pt.to_tensor(x), n_fft=128, hop_length=32,
                              window=win)
        assert spec.shape == [2, 65, 1 + 512 // 32]
        back = pt.signal.istft(spec, n_fft=128, hop_length=32, window=win,
                               length=512)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-3)

    def test_stft_matches_numpy(self):
        x = np.sin(np.arange(256, dtype=np.float32))
        spec = pt.signal.stft(pt.to_tensor(x), n_fft=64, hop_length=64,
                              center=False).numpy()
        ref0 = np.fft.rfft(x[:64])
        np.testing.assert_allclose(spec[:, 0], ref0, rtol=1e-3, atol=1e-3)


def test_regularizer():
    from paddle_tpu.regularizer import L1Decay, L2Decay
    import jax.numpy as jnp
    p = jnp.asarray([1.0, -2.0])
    g = jnp.zeros(2)
    np.testing.assert_allclose(np.asarray(L2Decay(0.1)(p, g)),
                               [0.1, -0.2], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(L1Decay(0.5)(p, g)),
                               [0.5, -0.5], rtol=1e-6)


class TestGradFlow:
    def test_fft_grad_flows_through_tape(self):
        rng = np.random.RandomState(0)
        x = pt.to_tensor(rng.randn(8).astype(np.float32),
                         stop_gradient=False)
        y = pt.fft.rfft(x)
        import jax.numpy as jnp
        mag = pt.ops.OPS["sum"](
            pt.to_tensor(0.0) + y.abs() if hasattr(y, "abs") else y)
        # simpler: real-valued reduction via dispatch
        from paddle_tpu.core.tensor import dispatch
        loss = dispatch(lambda v: jnp.sum(jnp.abs(v) ** 2), y,
                        name="energy")
        loss.backward()
        assert x.grad is not None
        # Parseval: d/dx sum|rfft(x)|^2 = 2*n*x for real input (approx;
        # one-sided spectrum halves interior bins -> just check nonzero)
        assert np.abs(x.grad.numpy()).sum() > 0

    def test_frame_grad_flows(self):
        x = pt.to_tensor(np.arange(10, dtype=np.float32),
                         stop_gradient=False)
        f = pt.signal.frame(x, 4, 2)
        pt.ops.OPS["sum"](f).backward()
        assert x.grad is not None
        # each sample participates in the #frames covering it
        assert x.grad.numpy().max() == 2.0  # hop 2, len 4 -> overlap 2

    def test_hfftn_matches_1d_hfft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(9).astype(np.float32) + 1j * rng.randn(9).astype(
            np.float32)
        import jax.numpy as jnp
        for norm in ("backward", "forward", "ortho"):
            got = np.asarray(pt.fft.hfftn(
                pt.to_tensor(np.asarray(x)), axes=(0,), norm=norm)._value)
            ref = np.fft.hfft(x, norm=None if norm == "backward" else norm)
            np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)

    def test_ihfftn_matches_1d_ihfft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(10).astype(np.float32)
        for norm in ("backward", "forward", "ortho"):
            got = np.asarray(pt.fft.ihfftn(
                pt.to_tensor(x), axes=(0,), norm=norm)._value)
            ref = np.fft.ihfft(x, norm=None if norm == "backward" else norm)
            np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

