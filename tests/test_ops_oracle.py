"""Broad numpy-oracle op coverage through the OpTest harness.

Reference pattern: python/paddle/fluid/tests/unittests/test_activation_op.py,
test_elementwise_*_op.py, test_reduce_op.py, test_concat_op.py, … — each op
checked against a numpy oracle in both execution modes, float grads checked
by finite differences on a representative subset.
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as pt
from op_test import OpTest

rng = np.random.RandomState(42)


def make_case(op, inputs, ref, attrs=None, atol=1e-5, rtol=1e-5):
    case = OpTest()
    case.atol, case.rtol = atol, rtol

    def setup():
        case.op = op
        case.inputs = dict(inputs)
        case.attrs = dict(attrs or {})
        vals = [np.asarray(v) for v in case.inputs.values()]
        case.outputs = ref(*vals)

    case.setup = setup
    return case


def x24():
    return rng.uniform(-2, 2, (4, 6)).astype(np.float32)


def xpos():
    return rng.uniform(0.3, 3, (4, 6)).astype(np.float32)


def xunit():
    return rng.uniform(-0.9, 0.9, (4, 6)).astype(np.float32)


UNARY = [
    ("abs", pt.abs, x24, np.abs),
    ("exp", pt.exp, x24, np.exp),
    ("log", pt.log, xpos, np.log),
    ("log2", pt.log2, xpos, np.log2),
    ("log10", pt.log10, xpos, np.log10),
    ("log1p", pt.log1p, xpos, np.log1p),
    ("sqrt", pt.sqrt, xpos, np.sqrt),
    ("rsqrt", pt.rsqrt, xpos, lambda v: 1 / np.sqrt(v)),
    ("square", pt.square, x24, np.square),
    ("sin", pt.sin, x24, np.sin),
    ("cos", pt.cos, x24, np.cos),
    ("tan", pt.tan, xunit, np.tan),
    ("asin", pt.asin, xunit, np.arcsin),
    ("acos", pt.acos, xunit, np.arccos),
    ("atan", pt.atan, x24, np.arctan),
    ("sinh", pt.sinh, x24, np.sinh),
    ("cosh", pt.cosh, x24, np.cosh),
    ("tanh", pt.tanh, x24, np.tanh),
    ("asinh", pt.asinh, x24, np.arcsinh),
    ("acosh", pt.acosh, lambda: rng.uniform(1.1, 3, (4, 6)).astype(np.float32),
     np.arccosh),
    ("atanh", pt.atanh, xunit, np.arctanh),
    ("ceil", pt.ceil, x24, np.ceil),
    ("floor", pt.floor, x24, np.floor),
    ("round", pt.round, x24, np.round),
    ("trunc", pt.trunc, x24, np.trunc),
    ("sign", pt.sign, x24, np.sign),
    ("neg", pt.neg, x24, np.negative),
    ("reciprocal", pt.reciprocal, xpos, np.reciprocal),
    ("sigmoid", pt.sigmoid, x24, lambda v: 1 / (1 + np.exp(-v))),
    ("erf", pt.erf, x24, sps.erf),
    ("expm1", pt.expm1, x24, np.expm1),
    ("lgamma", pt.lgamma, xpos, sps.gammaln),
    ("digamma", pt.digamma, xpos, sps.digamma),
    ("frac", pt.frac, x24, lambda v: v - np.trunc(v)),
    ("relu", pt.relu, x24, lambda v: np.maximum(v, 0)),
    ("logit", pt.logit, lambda: rng.uniform(0.1, 0.9, (4, 6)).astype(np.float32),
     sps.logit),
]


@pytest.mark.parametrize("name,op,gen,ref", UNARY, ids=[u[0] for u in UNARY])
def test_unary_oracle(name, op, gen, ref):
    make_case(op, {"x": gen()}, ref, atol=2e-5, rtol=2e-5).check_output()


BINARY = [
    ("add", pt.add, np.add),
    ("subtract", pt.subtract, np.subtract),
    ("multiply", pt.multiply, np.multiply),
    ("divide", pt.divide, np.divide),
    ("maximum", pt.maximum, np.maximum),
    ("minimum", pt.minimum, np.minimum),
    ("pow", pt.pow, lambda a, b: np.power(np.abs(a) + 0.5, b)),
    ("atan2", pt.atan2, np.arctan2),
    ("fmax", pt.fmax, np.fmax),
    ("fmin", pt.fmin, np.fmin),
    ("hypot", pt.hypot, np.hypot),
    ("logaddexp", pt.logaddexp, np.logaddexp),
    ("heaviside", pt.heaviside, np.heaviside),
    ("copysign", pt.copysign, np.copysign),
]


@pytest.mark.parametrize("name,op,ref", BINARY, ids=[b[0] for b in BINARY])
def test_binary_oracle(name, op, ref):
    a, b = x24(), x24()
    if name == "pow":
        a2 = np.abs(a) + 0.5
        make_case(op, {"x": a2, "y": b},
                  lambda x, y: np.power(x, y)).check_output(atol=1e-4,
                                                            rtol=1e-4)
        return
    if name == "divide":
        b = np.where(np.abs(b) < 0.3, 0.7, b).astype(np.float32)
    make_case(op, {"x": a, "y": b}, ref).check_output()


def test_binary_broadcast():
    a = x24()
    b = rng.uniform(-1, 1, (6,)).astype(np.float32)
    make_case(pt.add, {"x": a, "y": b}, np.add).check_output()
    make_case(pt.multiply, {"x": a.reshape(4, 6, 1),
                            "y": b.reshape(1, 6)[:, :, None]},
              np.multiply).check_output()


REDUCE = [
    ("sum", pt.sum, np.sum),
    ("mean", pt.mean, np.mean),
    ("max", pt.max, np.max),
    ("min", pt.min, np.min),
    ("prod", pt.prod, np.prod),
]


@pytest.mark.parametrize("name,op,ref", REDUCE, ids=[r[0] for r in REDUCE])
@pytest.mark.parametrize("axis", [None, 0, 1, -1])
def test_reduce_oracle(name, op, ref, axis):
    x = xpos() * 0.5  # keep prod well-conditioned
    attrs = {} if axis is None else {"axis": axis}
    make_case(op, {"x": x},
              lambda v: ref(v) if axis is None else ref(v, axis=axis),
              attrs=attrs, atol=1e-4, rtol=1e-4).check_output()


def test_reduce_keepdim_variance_std():
    x = x24()
    make_case(pt.var, {"x": x}, lambda v: np.var(v, ddof=0) if True else 0,
              attrs={"unbiased": False}).check_output(atol=1e-4)
    make_case(pt.std, {"x": x},
              lambda v: np.std(v, axis=1, ddof=1, keepdims=True),
              attrs={"axis": 1, "keepdim": True}).check_output(atol=1e-4)
    make_case(pt.logsumexp, {"x": x}, lambda v: sps.logsumexp(v, axis=-1),
              attrs={"axis": -1}).check_output(atol=1e-4)


MANIP = [
    ("reshape", pt.reshape, {"shape": [6, 4]},
     lambda v: v.reshape(6, 4)),
    ("transpose", pt.transpose, {"perm": [1, 0]}, lambda v: v.T),
    ("flip", pt.flip, {"axis": 0}, lambda v: np.flip(v, 0)),
    ("roll", pt.roll, {"shifts": 2, "axis": 1}, lambda v: np.roll(v, 2, 1)),
    ("tile", pt.tile, {"repeat_times": [2, 1]}, lambda v: np.tile(v, (2, 1))),
    ("squeeze", pt.squeeze, {}, lambda v: v.squeeze()),
    ("cumsum", pt.cumsum, {"axis": 1}, lambda v: np.cumsum(v, 1)),
    ("cumprod", pt.cumprod, {"dim": 1}, lambda v: np.cumprod(v, 1)),
    ("tril", pt.tril, {}, np.tril),
    ("triu", pt.triu, {}, np.triu),
]


@pytest.mark.parametrize("name,op,attrs,ref", MANIP,
                         ids=[m[0] for m in MANIP])
def test_manip_oracle(name, op, attrs, ref):
    x = x24() if name != "squeeze" else x24().reshape(4, 1, 6)
    make_case(op, {"x": x}, ref, attrs=attrs).check_output()


def test_concat_stack_split():
    a, b = x24(), x24()
    out = pt.concat([pt.to_tensor(a), pt.to_tensor(b)], axis=0)
    np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
    out = pt.stack([pt.to_tensor(a), pt.to_tensor(b)], axis=1)
    np.testing.assert_allclose(out.numpy(), np.stack([a, b], 1))
    parts = pt.split(pt.to_tensor(a), 2, axis=1)
    np.testing.assert_allclose(parts[0].numpy(), a[:, :3])
    np.testing.assert_allclose(parts[1].numpy(), a[:, 3:])


def test_indexing_ops():
    x = x24()
    idx = np.array([2, 0, 3], dtype=np.int64)
    make_case(pt.index_select, {"x": x, "index": idx},
              lambda v, i: v[i], attrs={"axis": 0}).check_output()
    make_case(pt.gather, {"x": x, "index": idx},
              lambda v, i: v[i]).check_output()
    t = pt.take_along_axis(pt.to_tensor(x),
                           pt.to_tensor(np.argsort(x, 1)), 1)
    np.testing.assert_allclose(t.numpy(), np.sort(x, 1), atol=1e-6)


LINALG = [
    ("matmul", pt.matmul, lambda a, b: a @ b),
    ("inner", pt.inner, np.inner),
    ("outer", pt.outer, lambda a, b: np.outer(a, b)),
]


def test_linalg_oracle():
    a = rng.uniform(-1, 1, (3, 4)).astype(np.float32)
    b = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
    make_case(pt.matmul, {"x": a, "y": b},
              lambda x, y: x @ y).check_output(atol=1e-4)
    v = rng.uniform(-1, 1, (4,)).astype(np.float32)
    make_case(pt.mv, {"x": a, "vec": v}, lambda x, w: x @ w)\
        .check_output(atol=1e-4)
    sq = rng.uniform(-1, 1, (3, 3)).astype(np.float32) + 3 * np.eye(
        3, dtype=np.float32)
    make_case(pt.inverse, {"x": sq}, np.linalg.inv).check_output(atol=1e-3,
                                                                 rtol=1e-3)
    make_case(pt.det, {"x": sq}, np.linalg.det).check_output(atol=1e-3,
                                                             rtol=1e-3)
    make_case(pt.trace, {"x": sq}, np.trace).check_output(atol=1e-4)


# ------------------------------------------------------------------ grads

GRAD_CASES = [
    ("tanh", pt.tanh, x24, {}),
    ("exp", pt.exp, xunit, {}),
    ("log", pt.log, xpos, {}),
    ("sqrt", pt.sqrt, xpos, {}),
    ("sigmoid", pt.sigmoid, x24, {}),
    ("square", pt.square, x24, {}),
    ("mean", pt.mean, x24, {"axis": 1}),
    ("sum", pt.sum, x24, {"axis": 0}),
    ("softmax", pt.softmax, x24, {"axis": -1}),
    ("reshape", pt.reshape, x24, {"shape": [6]}),
    ("transpose", pt.transpose, x24, {"perm": [1, 0]}),
]


@pytest.mark.parametrize("name,op,gen,attrs", GRAD_CASES,
                         ids=[g[0] for g in GRAD_CASES])
def test_grad_finite_difference(name, op, gen, attrs):
    x = gen()[:2, :3]  # small: finite difference loops every element
    case = make_case(op, {"x": x}, lambda v: v)  # oracle unused by check_grad
    case.attrs = attrs

    def setup():
        case.op = op
        case.inputs = {"x": x}
        case.attrs = attrs
        case.outputs = x

    case.setup = setup
    case.check_grad()


def test_grad_binary_matmul():
    a = rng.uniform(-1, 1, (2, 3)).astype(np.float32)
    b = rng.uniform(-1, 1, (3, 2)).astype(np.float32)
    case = make_case(pt.matmul, {"x": a, "y": b}, lambda x, y: x @ y)
    case.check_grad()
    case2 = make_case(pt.multiply, {"x": a, "y": a + 1}, np.multiply)
    case2.check_grad()
    case3 = make_case(pt.divide, {"x": a, "y": np.abs(b.T) + 1}, np.divide)
    case3.check_grad()
