"""Tiered KV cache (ISSUE 17): host-RAM spill under the prefix cache.

Three layers of coverage:

- ``HostTier`` unit tests: checksummed put/get round trips, corrupt
  payload = miss-plus-counter, byte accounting, budget validation, and
  the ``tier.spill`` / ``tier.restore`` fault points changing no state.
- ``PrefixCache`` + tier against a bare ``PagedKVCache``: eviction
  demotes bottom-up (device-leaf first), host nodes stay lookup-able
  with their sketch fingerprints, spill-fault falls back to a clean
  drop, donation adopts host nodes without a restore read, and the
  host byte budget evicts LRU leaves for real at the bottom.
- Server-level tests on the StubModel double and a real llama:
  spill -> restore round trips are BIT-EXACT (restored page contents
  asserted, plus greedy and seeded-sampled token parity vs a
  never-evicted oracle, including restore -> preempt -> replay), a
  corrupted host buffer is a miss plus ``kv_host_restore_corrupt_total``
  (never a failure), spill/restore are priced via the cost catalog but
  never counted as tick dispatches, and a chaos storm at 30% on the
  tier points leaves zero pages leaked in EITHER tier with same-seed
  identical traces. An mp=2 mesh restore (per-shard gather/scatter)
  closes the sharded-pool satellite.
"""
import numpy as np
import pytest

import jax

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import PagedKVCache
from paddle_tpu.inference.kv_tier import HostTier
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.reliability import (CallbackError, CircuitBreaker,
                                    FaultInjector, InjectedFault,
                                    RetryPolicy, faults)
from paddle_tpu.telemetry import (CostCatalog, MetricRegistry,
                                  ServerTelemetry)

PG = 4
PAGE_NBYTES = 64          # stub pool: K+V rows of one page, float32


def _arrs(x=1.0):
    return [np.full((1, PG, 1, 2), x, np.float32),
            np.full((1, PG, 1, 2), x + 0.5, np.float32)]


def _tiered_cache(num_pages=17, budget=None, tier_injector=None):
    kv = PagedKVCache(num_pages=num_pages, page_size=PG, max_slots=4,
                      pages_per_slot=8)
    tier = HostTier(budget_bytes=budget, fault_injector=tier_injector)
    cache = PrefixCache(kv, host_tier=tier,
                        spill=lambda page: _arrs(float(page)))
    return cache, kv, tier


def _donate(cache, kv, ids):
    ids = np.asarray(ids, np.int32)
    pages = kv.alloc(-(-len(ids) // PG))
    cache.donate(ids, pages, len(ids))
    return pages


# --------------------------------------------------------------------------
# HostTier unit contracts
# --------------------------------------------------------------------------
class TestHostTierUnit:
    def test_put_get_round_trip_and_accounting(self):
        tier = HostTier()
        entry = tier.put(_arrs())
        assert tier.entries == 1
        assert tier.bytes_used == entry.nbytes == PAGE_NBYTES
        assert tier.spilled_pages_total == 1
        back = tier.get(entry)
        for a, b in zip(back, _arrs()):
            np.testing.assert_array_equal(a, b)
        assert tier.restored_pages_total == 1
        tier.discard(entry)
        assert tier.entries == 0 and tier.bytes_used == 0
        assert tier.evicted_pages_total == 0     # promotion, not eviction

    def test_corrupt_payload_is_miss_plus_counter(self):
        tier = HostTier()
        entry = tier.put(_arrs())
        entry.payload[0][0, 0, 0, 0] += 1.0      # flip a buffer byte
        assert tier.get(entry) is None
        assert tier.restore_corrupt_total == 1
        assert tier.restored_pages_total == 0

    def test_budget_validation_and_over_budget(self):
        with pytest.raises(ValueError):
            HostTier(budget_bytes=-1)
        tier = HostTier(budget_bytes=PAGE_NBYTES)
        e1 = tier.put(_arrs())
        assert not tier.over_budget()
        tier.put(_arrs(2.0))
        assert tier.over_budget()
        tier.discard(e1, evicted=True)
        assert not tier.over_budget()
        assert tier.evicted_pages_total == 1
        assert HostTier(budget_bytes=None).over_budget() is False

    def test_spill_fault_raises_before_any_state_change(self):
        fi = FaultInjector(seed=3).on(faults.TIER_SPILL, probability=1.0)
        tier = HostTier(fault_injector=fi)
        with pytest.raises(InjectedFault):
            tier.put(_arrs())
        assert tier.entries == 0 and tier.bytes_used == 0
        assert tier.spilled_pages_total == 0

    def test_restore_fault_raises_before_the_read(self):
        fi = FaultInjector(seed=3).on(faults.TIER_RESTORE, probability=1.0)
        fi.disarm()
        tier = HostTier(fault_injector=fi)
        entry = tier.put(_arrs())
        fi.arm()
        with pytest.raises(InjectedFault):
            tier.get(entry)
        assert tier.restored_pages_total == 0
        assert tier.entries == 1                 # run stays spilled


# --------------------------------------------------------------------------
# PrefixCache over the tier: unified radix tree, demotion, budget
# --------------------------------------------------------------------------
class TestTieredRadixTree:
    def test_evict_demotes_leaf_first_and_lookup_stays_unified(self):
        cache, kv, tier = _tiered_cache()
        ids = np.arange(12, dtype=np.int32)      # 3 full pages
        _donate(cache, kv, ids)
        free0 = kv.free_pages()
        assert cache.evict(2) == 2
        # demotion, not drop: device pages freed, nodes kept as host
        assert kv.free_pages() == free0 + 2
        assert cache.cached_pages == 1 and cache.host_pages == 2
        assert tier.entries == 2 and tier.spilled_pages_total == 2
        assert cache.evicted_pages_total == 0    # nothing truly dropped
        m = cache.lookup(ids, 12)
        assert m.tokens == 12 and len(m.nodes) == 3
        assert m.hot_len() == 1                  # hot prefix / host suffix
        assert m.nodes[0].page is not None
        assert all(n.page is None and n.host is not None
                   for n in m.nodes[1:])
        # spilled runs keep their sketch fingerprints (router affinity
        # covers the host tier for free)
        cache.flush_sketch()
        assert {n.fp for n in m.nodes} <= set(cache.sketch())
        assert cache.stats()["host_pages"] == 2

    def test_node_run_stops_at_first_host_node(self):
        cache, kv, tier = _tiered_cache()
        ids = np.arange(12, dtype=np.int32)
        _donate(cache, kv, ids)
        cache.evict(2)
        run = cache.node_run(ids)
        assert len(run) == 1 and run[0].page is not None

    def test_spill_fault_falls_back_to_clean_drop(self):
        fi = FaultInjector(seed=5).on(faults.TIER_SPILL, probability=1.0)
        cache, kv, tier = _tiered_cache(tier_injector=fi)
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        free0 = kv.free_pages()
        assert cache.evict(1) == 1
        # the device page is freed either way; the tier saw no state
        assert kv.free_pages() == free0 + 1
        assert cache.host_pages == 0 and tier.entries == 0
        assert cache.cached_pages == 1
        assert cache.evicted_pages_total == 1

    def test_drop_subtree_releases_both_tiers(self):
        cache, kv, tier = _tiered_cache()
        ids = np.arange(12, dtype=np.int32)
        _donate(cache, kv, ids)
        cache.evict(2)
        m = cache.lookup(ids, 12)
        released = cache.drop_subtree(m.nodes[0])
        assert released == 1                     # the one hot page
        assert cache.cached_pages == 0 and cache.host_pages == 0
        assert tier.entries == 0 and tier.bytes_used == 0
        assert tier.evicted_pages_total == 2
        assert kv.used_pages() == 0
        assert cache.lookup(ids, 12) is None
        cache.flush_sketch()
        assert not cache.sketch()

    def test_host_budget_evicts_lru_leaves_for_real(self):
        cache, kv, tier = _tiered_cache(budget=PAGE_NBYTES)
        ids = np.arange(12, dtype=np.int32)
        _donate(cache, kv, ids)
        cache.evict(3)
        # three demotions, then the budget forgets the two LRU leaves
        assert tier.spilled_pages_total == 3
        assert tier.entries == 1 and tier.bytes_used == PAGE_NBYTES
        assert tier.evicted_pages_total == 2
        assert cache.host_pages == 1
        m = cache.lookup(ids, 12)
        assert len(m.nodes) == 1 and m.nodes[0].host is not None

    def test_donate_adopts_host_nodes_without_a_restore_read(self):
        cache, kv, tier = _tiered_cache()
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        cache.evict(2)
        assert cache.host_pages == 2
        _donate(cache, kv, ids)                  # a slot recomputed it
        assert cache.host_pages == 0 and cache.cached_pages == 2
        assert tier.entries == 0
        assert tier.restored_pages_total == 0    # free promotion
        assert cache.dedup_pages_total == 0
        assert kv.used_pages() == 2


# --------------------------------------------------------------------------
# Server level: spill/restore round trips on the Stub double
# --------------------------------------------------------------------------
def _tier_srv(**kw):
    kw.setdefault("max_slots", 1)
    kw.setdefault("max_cache_len", 32)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 6)                # 5 usable: tight
    kw.setdefault("host_tier", HostTier())
    return ContinuousBatchingServer(StubModel(), **kw)


A8 = np.arange(8, dtype=np.int32)
B8 = (np.arange(8, dtype=np.int32) + 8) % 16
C8 = np.asarray([5, 5, 5, 5, 9, 9, 9, 9], np.int32)


def _spill_A(srv):
    """Serve A, then fill the pool with B and C so A's pages demote."""
    for p in (A8, B8, C8):
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 4))


def _ext_A(n=2):
    """A multi-turn prompt EXTENDING A's stored history (prompt +
    generated prefix + the new turn) — an identical prompt can match at
    most T-1 tokens, so only an extension reaches the host suffix."""
    return np.concatenate([A8, stub_tokens(A8, 4)[:n],
                           np.asarray([1, 2], np.int32)])


class TestHostTierServer:
    def test_spill_restore_round_trip_bit_exact(self):
        tele = ServerTelemetry(registry=MetricRegistry())
        srv = _tier_srv(telemetry=tele)
        tier = srv.host_tier
        _spill_A(srv)
        assert tier.spilled_pages_total == 2     # A's prompt pages demoted
        assert srv._prefix.host_pages == 2
        # the returning session's next turn restores through the
        # normal admit path and the tokens match the never-evicted
        # oracle exactly
        ext = _ext_A()
        rid = srv.submit(ext, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid],
                                      stub_tokens(ext, 4))
        assert tier.restored_pages_total == 2
        assert srv.stats["prefix_auto_hit_tokens"] >= 8
        # restored PAGE CONTENTS: the stub prefill writes token values
        # into cache rows, so the shared pages must hold A's tokens —
        # proof the payload round-tripped bit-exact, not just the ids
        m = srv._prefix.lookup(ext, 8)
        assert m is not None and m.hot_len() == len(m.nodes) == 2
        pool_k = np.asarray(srv._caches["pool"]["k"])
        for i, nd in enumerate(m.nodes):
            np.testing.assert_array_equal(
                pool_k[0, nd.page, :, 0, 0],
                ext[i * 4:(i + 1) * 4].astype(np.float32))
        # balance + telemetry: host residency visible everywhere
        bal = srv.pool_balance()
        assert bal.host == srv._prefix.host_pages == tier.entries
        assert bal.host_bytes == tier.bytes_used
        free, live, pinned, cached = bal
        assert live == 0
        assert free + pinned + cached == srv._kv.num_pages - 1
        reg = tele.registry
        assert reg.get("kv_host_spilled_pages_total").value \
            == tier.spilled_pages_total
        assert reg.get("kv_host_restored_pages_total").value == 2
        assert reg.get("kv_pool_pages").labels(state="host").value \
            == srv._prefix.host_pages
        assert reg.get("serving_restore_seconds").count >= 1
        occ = srv._kv.occupancy(host_tier=srv._host)
        assert occ["host_tier"]["entries"] == tier.entries

    def test_corrupt_host_buffer_is_miss_plus_counter_never_failure(self):
        tele = ServerTelemetry(registry=MetricRegistry())
        srv = _tier_srv(telemetry=tele)
        tier = srv.host_tier
        _spill_A(srv)
        full = np.concatenate([A8, stub_tokens(A8, 4)])
        m = srv._prefix.lookup(full, 12)
        assert m.hot_len() == 0
        entry = m.nodes[0].host
        rotten = [a.copy() for a in entry.payload]
        rotten[0][0, 0, 0, 0] += 1.0                    # rot the buffer
        entry.payload = tuple(rotten)
        ext = _ext_A()
        rid = srv.submit(ext, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid],
                                      stub_tokens(ext, 4))
        assert tier.restore_corrupt_total == 1
        assert tele.registry.get("kv_host_restore_corrupt_total").value \
            == 1
        # the corrupt run (and its all-host subtree) left both tiers
        assert srv._prefix.lookup(full, 12) is None \
            or srv._prefix.lookup(full, 12).nodes[0].host is None
        bal = srv.pool_balance()
        assert bal.host == tier.entries

    def test_host_tier_bytes_kwarg_bounds_the_tier(self):
        srv = _tier_srv(host_tier=None, host_tier_bytes=PAGE_NBYTES)
        tier = srv.host_tier
        assert isinstance(tier, HostTier)
        assert tier.budget_bytes == PAGE_NBYTES
        _spill_A(srv)
        # two demotions but only one page of budget: the LRU host
        # leaf fell off the bottom of the hierarchy for real
        assert tier.spilled_pages_total == 2
        assert tier.entries == 1
        assert tier.bytes_used <= PAGE_NBYTES
        assert tier.evicted_pages_total == 1
        assert srv.pool_balance().host == 1

    def test_disabled_tier_is_structurally_free(self):
        srv = _tier_srv(host_tier=HostTier(enabled=False))
        assert srv._host is None
        assert srv._prefix._tier is None
        _spill_A(srv)
        assert srv.host_tier.spilled_pages_total == 0
        assert srv._prefix.host_pages == 0
        assert srv.pool_balance().host == 0
        # and the default server has no tier at all
        assert ContinuousBatchingServer(
            StubModel(), max_slots=1, max_cache_len=32,
            cache_backend="paged", page_size=4).host_tier is None

    def test_dense_backend_rejects_the_tier(self):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatchingServer(StubModel(), max_slots=1,
                                     max_cache_len=32, host_tier=True)

    def test_spill_restore_priced_but_never_tick_dispatches(self):
        """Satellite 1: ``page_spill``/``page_restore`` ride the cost
        catalog as 2x-bytes-moved transfers and NEVER count against
        ``serving_tick_dispatches`` / ``server_dispatches_total``."""
        tele = ServerTelemetry(registry=MetricRegistry())
        cat = CostCatalog(registry=tele.registry)
        srv = _tier_srv(telemetry=tele, costs=cat)
        tier = srv.host_tier
        _spill_A(srv)
        rid = srv.submit(_ext_A(), max_new_tokens=4)
        srv.run()[rid]
        cat.flush_tick()
        tot = cat.totals()
        row = PAGE_NBYTES // PG                  # K+V bytes per token row
        assert tot["page_spill"]["hbm_bytes"] \
            == 2 * tier.spilled_pages_total * PG * row
        assert tot["page_restore"]["hbm_bytes"] \
            == 2 * tier.restored_pages_total * PG * row
        assert tot["page_spill"]["flops"] == 0.0
        assert tot["page_restore"]["flops"] == 0.0
        disp = tele.registry.get("server_dispatches_total")._children
        assert not any("page_spill" in str(k) or "page_restore" in str(k)
                       for k in disp)

    def test_postmortem_freezes_host_counts(self):
        srv = _tier_srv(recorder=True)
        _spill_A(srv)
        srv.kill(timeout=5.0)
        pm = srv.postmortems()[-1]
        assert pm["pool_balance"]["host"] == srv._prefix.host_pages
        assert pm["pool_balance"]["host_bytes"] \
            == srv.host_tier.bytes_used
        assert pm["block_table"]["host_tier"]["entries"] \
            == srv.host_tier.entries


# --------------------------------------------------------------------------
# Chaos: 30% storms over tier.spill / tier.restore
# --------------------------------------------------------------------------
@pytest.mark.chaos
class TestTierChaos:
    def _injector(self, seed):
        return (FaultInjector(seed=seed)
                .on(faults.PREFILL, probability=0.15)
                .on(faults.DECODE_TICK, probability=0.1)
                .on(faults.PAGE_ALLOC, probability=0.1)
                .on(faults.PREFIX_EVICT, probability=0.2)
                .on(faults.PREFIX_DONATE, probability=0.2)
                .on(faults.TIER_SPILL, probability=0.3)
                .on(faults.TIER_RESTORE, probability=0.3))

    def _srv(self, fi, **kw):
        kw.setdefault("max_slots", 2)
        kw.setdefault("max_cache_len", 32)
        kw.setdefault("cache_backend", "paged")
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 8)            # 7 usable: pressure
        kw.setdefault("host_tier",
                      HostTier(budget_bytes=8 * PAGE_NBYTES))
        kw.setdefault("retry_policy", RetryPolicy(base_delay_s=0.0,
                                                  jitter=0.0))
        kw.setdefault("breaker", CircuitBreaker(failure_threshold=10_000))
        return ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                        **kw)

    def _drive(self, srv, max_ticks=5000):
        ticks = 0
        while True:
            with srv._lock:
                busy = srv._busy_locked()
            if not busy:
                return
            try:
                srv.step()
            except CallbackError:
                pass
            except Exception:
                pass
            ticks += 1
            assert ticks < max_ticks, "chaos drive did not converge"

    def _workload(self, seed=5, n=12):
        """DISTINCT per-user prompts (a shared system prefix dedups
        into two pages and the pool never runs short): each one
        donates its own page run, so the storm actually evicts."""
        rng = np.random.default_rng(seed)
        return [rng.integers(0, 16, (int(k),)).astype(np.int32)
                for k in rng.integers(8, 14, (n,))]

    def _run_storm(self, fi, srv):
        """Two phases: fill the tree under pressure, then come back
        with EXTENDING multi-turn prompts so restores are attempted."""
        prompts = self._workload()
        rids = [srv.submit(p, max_new_tokens=4) for p in prompts]
        self._drive(srv)
        exts = []
        for p in prompts[:8]:
            full = np.concatenate([p, stub_tokens(p, 4)])
            exts.append(np.concatenate(
                [full[:len(p) + 2],
                 np.asarray([int(p[0]) % 16, 3], np.int32)]))
        rids += [srv.submit(e, max_new_tokens=4) for e in exts]
        self._drive(srv)
        return prompts + exts, rids

    def test_tier_storm_zero_leaks_in_both_tiers(self):
        fi = self._injector(seed=606)
        srv = self._srv(fi)
        tier = srv.host_tier
        prompts, rids = self._run_storm(fi, srv)
        outs = srv._results
        served = 0
        for rid, p in zip(rids, prompts):
            if rid in outs:
                served += 1
                np.testing.assert_array_equal(outs[rid],
                                              stub_tokens(p, 4))
        assert served > 0
        assert fi.fired(faults.TIER_SPILL) > 0, "spill chaos idle"
        assert fi.fired(faults.TIER_RESTORE) \
            + tier.restored_pages_total > 0, "restore path idle"
        # device pool balanced: host nodes hold NO device page, so the
        # 4-tuple still sums to the usable pool
        bal = srv.pool_balance()
        free, live, pinned, cached = bal
        assert live == 0, f"leaked {live} device pages"
        assert free + pinned + cached == srv._kv.num_pages - 1
        # host tier balanced: tree view == tier accounting, budget held
        assert bal.host == srv._prefix.host_pages == tier.entries
        assert bal.host_bytes == tier.bytes_used \
            == tier.entries * PAGE_NBYTES
        assert tier.bytes_used <= tier.budget_bytes
        assert tier.evicted_pages_total > 0, "host LRU bottom idle"

    def test_same_seed_identical_trace_and_tier_state(self):
        def run_once():
            fi = self._injector(seed=4242)
            srv = self._srv(fi)
            self._run_storm(fi, srv)
            results = {r: tuple(int(x) for x in v)
                       for r, v in srv._results.items()}
            fails = {r: type(e).__name__
                     for r, e in srv.failures.items()}
            return (fi.trace, results, fails, srv.pool_balance(),
                    srv._prefix.stats(), srv.host_tier.stats())

        a, b = run_once(), run_once()
        assert a == b
        assert any(pt in (faults.TIER_SPILL, faults.TIER_RESTORE)
                   for pt, _ in a[0]), "deterministic run hit no tier"


# --------------------------------------------------------------------------
# Real-model parity: a restored run is bit-exact with a never-evicted one
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def llama():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _llama_kw(**kw):
    base = dict(max_slots=1, max_cache_len=64, cache_backend="paged",
                page_size=8)
    base.update(kw)
    return base


def _llama_session(oracle, tiered, prompts, ext_turn, n_new, seeds=None):
    """Drive the SAME multi-turn session through a never-evicted oracle
    and a tight tiered server: prompts serve in order (spilling the
    first one's history on the tiered side), then the first session
    returns with ``ext_turn`` new tokens appended to its full history.
    Every request must be bit-identical across the pair."""
    seeds = seeds or [None] * (len(prompts) + 1)
    hist = None
    for i, p in enumerate(prompts):
        ra = oracle.submit(p, max_new_tokens=n_new, seed=seeds[i])
        rb = tiered.submit(p, max_new_tokens=n_new, seed=seeds[i])
        oa, ob = oracle.run()[ra], tiered.run()[rb]
        np.testing.assert_array_equal(oa, ob)
        if i == 0:
            hist = np.concatenate([p, np.asarray(oa, np.int32)])
    ext = np.concatenate([hist, ext_turn])
    ra = oracle.submit(ext, max_new_tokens=n_new, seed=seeds[-1])
    rb = tiered.submit(ext, max_new_tokens=n_new, seed=seeds[-1])
    np.testing.assert_array_equal(oracle.run()[ra], tiered.run()[rb])


class TestLlamaTieredParity:
    # tier-1 budget (the 870 s wall): the seeded-sampled drill below is
    # the in-budget canary; the greedy + preempt halves and the mesh
    # class run under `-m slow` with the other heavy llama e2e parity
    @pytest.mark.slow
    def test_greedy_restore_parity(self, llama):
        """The acceptance drill, greedy half: session A's history is
        spilled by three follow-up sessions, then its next turn
        restores it — tokens bit-identical to a pool that never
        evicted anything."""
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, 256, (16,)).astype(np.int32)
                   for _ in range(4)]
        oracle = ContinuousBatchingServer(llama,
                                          **_llama_kw(num_pages=24))
        tiered = ContinuousBatchingServer(
            llama, **_llama_kw(num_pages=7, host_tier=HostTier()))
        _llama_session(oracle, tiered, prompts,
                       rng.integers(0, 256, (3,)).astype(np.int32),
                       n_new=4)
        tier = tiered.host_tier
        assert tier.spilled_pages_total > 0, "pool never spilled"
        assert tier.restored_pages_total >= 2, "turn 2 never restored"
        assert oracle.host_tier is None

    def test_seeded_sampled_restore_parity(self, llama):
        """The sampled half: per-request PRNG chains survive the spill
        -> restore detour — seeded sampling through a restored prefix
        is bit-identical to the never-evicted oracle."""
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 256, (16,)).astype(np.int32)
                   for _ in range(4)]
        kw = dict(do_sample=True, temperature=0.8, top_k=20, top_p=0.9)
        oracle = ContinuousBatchingServer(
            llama, **_llama_kw(num_pages=24, **kw))
        tiered = ContinuousBatchingServer(
            llama, **_llama_kw(num_pages=7, host_tier=HostTier(), **kw))
        _llama_session(oracle, tiered, prompts,
                       rng.integers(0, 256, (3,)).astype(np.int32),
                       n_new=4, seeds=[101, 102, 103, 104, 105])
        assert tiered.host_tier.restored_pages_total >= 2

    @pytest.mark.slow
    def test_restore_then_preempt_then_replay_stays_bit_exact(self, llama):
        """Restore -> preempt -> replay: the restored session and a
        rival admit optimistically into a pool too small for both;
        the loser is preempted and replayed. Tokens still match the
        never-evicted oracle bit-for-bit."""
        rng = np.random.default_rng(7)
        # session A keeps a small footprint (its turn 2 must co-admit
        # with the rival); the fat fillers spill A's history in phase 1
        prompts = [rng.integers(0, 256, (8,)).astype(np.int32)] + [
            rng.integers(0, 256, (16,)).astype(np.int32)
            for _ in range(3)]
        oracle = ContinuousBatchingServer(
            llama, **_llama_kw(num_pages=24, max_slots=2))
        tiered = ContinuousBatchingServer(
            llama, **_llama_kw(num_pages=7, max_slots=2,
                               host_tier=HostTier(),
                               admission="optimistic",
                               headroom_pages=1))
        hist = None
        for i, p in enumerate(prompts):
            ra = oracle.submit(p, max_new_tokens=6)
            rb = tiered.submit(p, max_new_tokens=6)
            oa, ob = oracle.run()[ra], tiered.run()[rb]
            np.testing.assert_array_equal(oa, ob)
            if i == 0:
                hist = np.concatenate([p, np.asarray(oa, np.int32)])
        assert tiered.host_tier.spilled_pages_total > 0
        # turn 2 of session A races a fresh rival for the tiny
        # pool — the rival admits first (small prompt, small
        # footprint), then both optimistic slots grow into the same
        # exhausted pool and one gets preempted and replayed
        ext = np.concatenate(
            [hist, rng.integers(0, 256, (3,)).astype(np.int32)])
        rival = rng.integers(0, 256, (8,)).astype(np.int32)
        subs = [(rival, 12), (ext, 12)]
        ra = [oracle.submit(p, max_new_tokens=n) for p, n in subs]
        rb = [tiered.submit(p, max_new_tokens=n) for p, n in subs]
        oa, ob = oracle.run(), tiered.run()
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(oa[x], ob[y])
        assert tiered.host_tier.restored_pages_total >= 1
        assert tiered.pool_balance().preemptions >= 1, \
            "pool never preempted — shrink num_pages"


# --------------------------------------------------------------------------
# Sharded pool (mp=2): per-shard spill gathers / restore scatters
# --------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.mesh
@pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="needs forced host devices "
           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
class TestShardedTier:
    def test_mp2_spill_restore_bit_exact_full_width_payload(self):
        """Satellite 2: on a kv-head-sharded pool the spill gather goes
        per shard (slices concatenated to full head width in the host
        payload) and the restore scatter lays the payload back against
        the pool's own sharding — tokens bit-identical to a
        single-device never-evicted oracle."""
        from jax.sharding import Mesh

        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=1,
                          num_heads=8, num_kv_heads=4,
                          intermediate_size=128, max_seq_len=128)
        pt.seed(21)
        model = LlamaForCausalLM(cfg)
        model.eval()
        mesh = Mesh(np.array(jax.devices()[:2]), ("mp",))
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, 256, (16,)).astype(np.int32)
                   for _ in range(4)]
        oracle = ContinuousBatchingServer(model,
                                          **_llama_kw(num_pages=24))
        tiered = ContinuousBatchingServer(
            model, mesh=mesh,
            **_llama_kw(num_pages=7, host_tier=HostTier()))
        hist = None
        for i, p in enumerate(prompts):
            ra = oracle.submit(p, max_new_tokens=4)
            rb = tiered.submit(p, max_new_tokens=4)
            oa, ob = oracle.run()[ra], tiered.run()[rb]
            np.testing.assert_array_equal(oa, ob)
            if i == 0:
                hist = np.concatenate([p, np.asarray(oa, np.int32)])
        tier = tiered.host_tier
        assert tier.spilled_pages_total > 0
        # the host payload carries the FULL kv-head width — the
        # per-shard gather concatenated both devices' slices
        m = tiered._prefix.lookup(hist, len(hist))
        assert m is not None
        spilled = [n for n in m.nodes if n.host is not None]
        assert spilled
        assert spilled[0].host.payload[0].shape == (1, 8, 4, 8)
        # turn 2: restore through the sharded scatter, bit-exact
        ext = np.concatenate(
            [hist, rng.integers(0, 256, (3,)).astype(np.int32)])
        ra = oracle.submit(ext, max_new_tokens=4)
        rb = tiered.submit(ext, max_new_tokens=4)
        np.testing.assert_array_equal(oracle.run()[ra],
                                      tiered.run()[rb])
        assert tier.restored_pages_total >= 2
