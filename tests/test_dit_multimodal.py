"""DiT (diffusion) + Qwen-VL (multimodal) model families.

BASELINE.md row: "DiT / SD3, Qwen-VL: diffusion + multimodal via
auto_parallel (ProcessMesh/shard_tensor) path — functional".
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


class TestDiT:
    def _model(self):
        from paddle_tpu.models.dit import DiTForDiffusion, dit_tiny
        return DiTForDiffusion(dit_tiny()), dit_tiny()

    def test_forward_shapes(self):
        m, cfg = self._model()
        x = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        t = pt.to_tensor(np.array([0, 500], dtype="int32"))
        y = pt.to_tensor(np.array([1, 2], dtype="int32"))
        out = m(x, t, y)
        assert out.shape == [2, 3, 8, 8]

    def test_diffusion_loss_and_grads(self):
        m, cfg = self._model()
        x0 = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        t = pt.to_tensor(np.array([10, 990], dtype="int32"))
        noise = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        loss = m.loss(x0, t, noise)
        assert np.isfinite(float(loss.numpy()))
        loss.backward()
        blk = m.dit.blocks[0]
        for p in (blk.qkv.weight, blk.ada.weight,
                  m.dit.patch_embed.weight, m.dit.pos_embed):
            assert p.grad is not None
            assert np.isfinite(p.grad.numpy()).all()

    def test_adaln_zero_identity_at_init(self):
        """adaLN-Zero: gates start at 0 so the final layer outputs 0 and
        each block is identity — the DiT init invariant."""
        from paddle_tpu.models.dit import DiT, dit_tiny
        m = DiT(dit_tiny())
        x = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        t = pt.to_tensor(np.array([3, 7], dtype="int32"))
        out = m(x, t)
        np.testing.assert_allclose(out.numpy(), 0.0, atol=1e-6)

    @pytest.mark.slow
    def test_training_reduces_loss(self):
        # slow: 8 optimizer steps of eager backward; diffusion loss +
        # grads stay tier-1 via test_diffusion_loss_and_grads
        m, cfg = self._model()
        opt = pt.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=m.parameters())
        x0 = pt.to_tensor(np.random.randn(4, 3, 8, 8).astype("float32"))
        t = pt.to_tensor(np.array([5, 105, 505, 905], dtype="int32"))
        noise = pt.to_tensor(np.random.randn(4, 3, 8, 8).astype("float32"))
        first = last = None
        for i in range(8):
            loss = m.loss(x0, t, noise)
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(loss.numpy())
            first = v if first is None else first
            last = v
        assert last < first

    def test_auto_parallel_shard(self):
        from paddle_tpu.models.dit import DiT, dit_tiny, shard_dit
        from paddle_tpu.parallel.auto_parallel import ProcessMesh
        mesh = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
        m = shard_dit(DiT(dit_tiny()), mesh)
        x = pt.to_tensor(np.random.randn(4, 3, 8, 8).astype("float32"))
        t = pt.to_tensor(np.array([1, 2, 3, 4], dtype="int32"))
        out = m(x, t)
        assert out.shape == [4, 3, 8, 8]
        assert np.isfinite(out.numpy()).all()


class TestQwenVL:
    def _model(self):
        from paddle_tpu.models.qwen_vl import QwenVL, qwen_vl_tiny
        cfg = qwen_vl_tiny()
        return QwenVL(cfg), cfg

    def test_multimodal_forward(self):
        m, cfg = self._model()
        ids = pt.to_tensor(np.random.randint(0, 256, (2, 32)).astype("int32"))
        px = pt.to_tensor(np.random.randn(2, 3, 16, 16).astype("float32"))
        logits = m(ids, px)
        n_vis = cfg.vision.num_patches
        assert logits.shape == [2, n_vis + 32, cfg.text.vocab_size]

    def test_text_only_forward(self):
        m, cfg = self._model()
        ids = pt.to_tensor(np.random.randint(0, 256, (2, 16)).astype("int32"))
        logits = m(ids)
        assert logits.shape == [2, 16, cfg.text.vocab_size]

    def test_loss_masks_visual_prefix_and_grads_flow(self):
        m, cfg = self._model()
        ids = pt.to_tensor(np.random.randint(0, 256, (2, 32)).astype("int32"))
        px = pt.to_tensor(np.random.randn(2, 3, 16, 16).astype("float32"))
        logits = m(ids, px)
        loss = m.loss(logits, ids)
        assert np.isfinite(float(loss.numpy()))
        loss.backward()
        assert m.visual.blocks[0].qkv.weight.grad is not None
        assert m.projector.weight.grad is not None
        assert m.lm_head.weight.grad is not None

    @pytest.mark.slow
    def test_auto_parallel_shard(self):
        from paddle_tpu.models.qwen_vl import shard_qwen_vl
        from paddle_tpu.parallel.auto_parallel import ProcessMesh
        m, cfg = self._model()
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        m = shard_qwen_vl(m, mesh)
        ids = pt.to_tensor(np.random.randint(0, 256, (2, 16)).astype("int32"))
        px = pt.to_tensor(np.random.randn(2, 3, 16, 16).astype("float32"))
        logits = m(ids, px)
        assert np.isfinite(logits.numpy()).all()
