"""Distributed tests on the 8-device virtual CPU mesh.

Pattern mirrors the reference's single-host multi-trainer tests
(collective/fleet/hybrid_parallel_mp_model.py: TP numeric equivalence vs
single device; test_dist_base.py loss-parity assertions).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.mesh import P


def test_eight_devices():
    assert len(jax.devices()) == 8


def test_topology_matches_reference_math():
    topo = dist.CommunicateTopology(["data", "pipe", "sharding", "model"],
                                    [2, 2, 1, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(data=0, pipe=0, sharding=0, model=1) == 1
    assert topo.get_rank(data=1, pipe=0, sharding=0, model=0) == 4
    assert topo.get_coord(5) == (1, 0, 0, 1)
    mp_groups = topo.get_comm_list("model")
    assert [0, 1] in mp_groups and [4, 5] in mp_groups
    hcg = dist.HybridCommunicateGroup(topo, global_rank=5)
    assert hcg.get_model_parallel_rank() == 1
    assert hcg.get_data_parallel_rank() == 1
    assert hcg.get_stage_id() == 0
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_p2p_next_rank() == 7  # pipe ring


def test_collectives_inside_shard_map():
    mesh = dist.init_mesh(dp=4, mp=2)

    def body(x):
        s = dist.all_reduce(x, group="dp")
        g = jax.lax.all_gather(x, "mp", tiled=True)
        return s, g

    x = jnp.arange(8.0).reshape(8, 1)
    f = jax.shard_map(body, mesh=mesh.mesh,
                      in_specs=P(("dp", "mp")),
                      out_specs=(P(("dp", "mp")), P(("dp", "mp"))))
    s, g = f(x)
    # all_reduce over dp of values [0,2,4,6] (mp=0 coords) etc.
    assert s.shape == (8, 1)


def test_mp_ops_semantics():
    mesh = dist.init_mesh(dp=1, mp=8)
    from paddle_tpu.parallel import mp_ops

    # c_split keeps local slice; c_concat restores
    def body(x):
        local = mp_ops.c_split(x)
        back = mp_ops.c_concat(local)
        return back

    x = jnp.arange(64.0).reshape(1, 8, 8)  # replicate input
    out = jax.shard_map(body, mesh=mesh.mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)(x[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x[0]))


def test_parallel_cross_entropy_matches_dense():
    mesh = dist.init_mesh(dp=1, mp=8)
    from paddle_tpu.parallel import mp_ops
    B, V = 4, 64
    logits = np.random.randn(B, V).astype(np.float32)
    labels = np.random.randint(0, V, size=(B,))

    def body(lg, lb):
        return mp_ops.c_softmax_with_cross_entropy(lg, lb, group="mp")

    out = jax.shard_map(body, mesh=mesh.mesh,
                        in_specs=(P(None, "mp"), P()),
                        out_specs=P(), check_vma=False)(
        jnp.asarray(logits), jnp.asarray(labels))
    ref = -(jax.nn.log_softmax(logits, -1)[np.arange(B), labels])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_tp_gspmd_matches_single_device():
    """ColumnParallel+RowParallel sandwich under pjit == dense reference."""
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    ids = np.random.randint(0, 256, size=(2, 16)).astype(np.int32)

    pt.seed(5)
    dense = LlamaForCausalLM(llama_tiny())
    dense.eval()
    ref = np.asarray(jax.jit(
        lambda ps, x: functional_call(dense, ps, x))(dense.raw_params(), ids))

    pt.seed(5)
    tp_model = LlamaForCausalLM(llama_tiny(tensor_parallel=True))
    tp_model.eval()
    mesh = dist.init_mesh(dp=1, mp=8)
    with mesh:
        from paddle_tpu.parallel.api import shard_params
        params, shardings = shard_params(tp_model, mesh)
        out = jax.jit(
            lambda ps, x: functional_call(tp_model, ps, x),
            in_shardings=(shardings, None))(params, ids)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-3, atol=5e-4)


def test_parallel_train_step_dp_tp():
    """Full sharded train step on dp=2 x mp=2 x sharding=2: loss decreases."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    mesh = dist.init_mesh(dp=2, mp=2, sharding=2)
    model = LlamaForCausalLM(llama_tiny(tensor_parallel=True))
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())

    def loss_fn(logits, labels):
        lg = logits[:, :-1]
        lb = labels[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

    with mesh:
        step, params, state, _ = dist.parallel_train_step(
            model, loss_fn, opt, mesh, zero_stage=1, grad_clip_norm=1.0)
        ids = np.random.randint(0, 256, size=(4, 32)).astype(np.int32)
        batch = {"inputs": (ids,), "labels": (ids,)}
        losses = []
        for i in range(8):
            loss, params, state = step(params, state, batch, i + 1,
                                       jax.random.PRNGKey(i))
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_fused_allreduce_gradients_noop_single():
    layer = pt.nn.Linear(2, 2)
    out = layer(pt.to_tensor(np.ones((1, 2), np.float32)))
    out.sum().backward()
    g0 = layer.weight.grad.numpy().copy()
    dist.fused_allreduce_gradients(layer.parameters())
    np.testing.assert_array_equal(layer.weight.grad.numpy(), g0)


def test_rng_tracker_distinct_streams():
    tr = dist.RNGStatesTracker()
    tr.add("global_seed", 1)
    tr.add("local_seed", 2)
    with tr.rng_state("local_seed"):
        a = pt.ops.randn([4]).numpy()
    with tr.rng_state("global_seed"):
        b = pt.ops.randn([4]).numpy()
    assert not np.allclose(a, b)
    with pytest.raises(ValueError):
        tr.add("global_seed", 3)


def test_stream_namespace_parity():
    """stream.* variants forward to the collective impl and return a
    waitable task handle (reference communication/stream/all_reduce.py)."""
    import numpy as np
    from paddle_tpu.parallel import stream
    import paddle_tpu as pt

    t = pt.to_tensor(np.ones(4, np.float32))
    task = stream.all_reduce(t, sync_op=False, use_calc_stream=True)
    assert task.wait() and task.is_completed()
    np.testing.assert_allclose(t.numpy(), 1.0)  # 1-proc: identity


def test_scatter_inside_shard_map():
    """dist.scatter: rank r receives src's stacked slice r."""
    mesh = dist.init_mesh(dp=8)

    def body(stack):
        out = dist.scatter(None, stack[0], src=0, group="dp")
        return out[None]

    # every rank holds the same stacked [8, 2] payload; rank r gets row r
    payload = jnp.arange(16.0).reshape(8, 2)
    f = jax.shard_map(body, mesh=mesh.mesh,
                      in_specs=P("dp"),
                      out_specs=P("dp"))
    out = f(jnp.broadcast_to(payload, (8, 8, 2)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(payload))


def test_scatter_eager_fallback():
    t = pt.to_tensor([0.0, 0.0])
    dist.scatter(t, [pt.to_tensor([5.0, 6.0])], src=0)
    np.testing.assert_allclose(t.numpy(), [5.0, 6.0])


@pytest.mark.slow  # ragged all_to_all compile is the file's 30s outlier
def test_alltoall_single_uneven_splits():
    """Uneven alltoall (VERDICT r3 #7): rank-varying splits via the
    [world, world] size matrix — pad-to-max chunks, one all_to_all,
    axis_index-dynamic scatter. Oracle: per-rank chunk bookkeeping."""
    mesh = dist.init_mesh(dp=4)
    # sizes[i][j] = rows rank i sends to rank j; column sums all = 4
    sizes = np.array([[1, 2, 0, 1],
                      [0, 1, 2, 1],
                      [3, 0, 1, 0],
                      [0, 1, 1, 2]])
    n_in = int(sizes.sum(1).max())   # uniform local buffer rows

    def body(x):
        return dist.collective.alltoall_single(
            None, x, in_split_sizes=sizes.tolist(), group="dp")

    # rank r rows: 100*r + k
    xs = np.stack([100 * r + np.arange(n_in) for r in range(4)])
    x = jnp.asarray(xs.reshape(-1, 1), jnp.float32)
    out = jax.shard_map(body, mesh=mesh.mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(x)
    out = np.asarray(out).reshape(4, 4)
    in_off = np.concatenate(
        [np.zeros((4, 1), int), np.cumsum(sizes, 1)[:, :-1]], 1)
    for r in range(4):
        want = np.concatenate(
            [xs[j, in_off[j, r]:in_off[j, r] + sizes[j, r]]
             for j in range(4)])
        np.testing.assert_allclose(out[r], want, err_msg=f"rank {r}")


def test_partial_allgather_reassembles():
    mesh = dist.init_mesh(dp=4)

    def body(x):
        return dist.collective.partial_allgather(x, group="dp")

    # every rank's buffer: only its own segment is "valid" = rank id
    x = jnp.asarray(np.repeat(np.arange(4), 2)[:, None], jnp.float32)
    full = jnp.tile(x, (4, 1))   # each rank gets the same 8-row buffer
    out = jax.shard_map(body, mesh=mesh.mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(full)
    out = np.asarray(out).reshape(4, 8)
    # each rank contributed segment r of ITS buffer -> reassembled full
    want = np.repeat(np.arange(4), 2)
    for r in range(4):
        np.testing.assert_allclose(out[r], want)


def test_partial_ppermute_moves_one_segment():
    mesh = dist.init_mesh(dp=4)
    perm = [(i, (i + 1) % 4) for i in range(4)]

    def body(x):
        return dist.collective.partial_ppermute(x, perm, group="dp")

    # rank r buffer filled with value r
    x = jnp.asarray(np.repeat(np.arange(4.0), 8)[:, None], jnp.float32)
    out = jax.shard_map(body, mesh=mesh.mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(x)
    out = np.asarray(out).reshape(4, 8)
    for r in range(4):
        src = (r - 1) % 4
        want = np.full(8, float(r))
        seg = slice(r * 2, r * 2 + 2)     # segment index = own rank
        want[seg] = float(src)            # received peer's segment
        np.testing.assert_allclose(out[r], want)


def test_partial_send_raises_with_guidance():
    import pytest
    with pytest.raises(RuntimeError):
        dist.collective.partial_send(jnp.zeros(4), dst=1)


def test_alltoall_single_flat_uneven_list_raises():
    # flat per-rank lists cannot describe rank-varying splits in one
    # SPMD trace; silently returning padding was a correctness trap
    import pytest
    mesh = dist.init_mesh(dp=4)

    def body(x):
        return dist.collective.alltoall_single(
            None, x, in_split_sizes=[1, 2, 0, 3],
            out_split_sizes=[1, 2, 0, 3], group="dp")

    x = jnp.zeros((24, 1), jnp.float32)
    with pytest.raises(Exception, match="size matrix"):
        jax.shard_map(body, mesh=mesh.mesh, in_specs=P("dp"),
                      out_specs=P("dp"), check_vma=False)(x)


def test_dataparallel_scale_loss_and_no_sync():
    """DataParallel semantics (VERDICT r3 weak #5): scale_loss divides by
    world size; no_sync suppresses the grad allreduce in its scope."""
    from paddle_tpu.parallel import api as papi

    layer = pt.nn.Linear(2, 2)
    dp_model = dist.DataParallel(layer)
    loss = pt.to_tensor(np.float32(8.0))
    # single process: identity
    np.testing.assert_allclose(float(dp_model.scale_loss(loss)), 8.0)

    out = dp_model(pt.to_tensor(np.ones((1, 2), np.float32)))
    out.sum().backward()
    with dp_model.no_sync():
        assert papi._SYNC_SUPPRESSED
        dist.fused_allreduce_gradients(layer.parameters())  # skipped
    assert not papi._SYNC_SUPPRESSED


def test_multislice_mesh_dp_over_dcn():
    """init_multislice_mesh: dcn_dp replicas outermost, full hybrid
    inside each 'slice'; a dp-sharded train step runs unchanged."""
    mesh = dist.init_mesh  # noqa: F841 (module imported below)
    from paddle_tpu.parallel.mesh import init_multislice_mesh
    hm = init_multislice_mesh(dcn_dp=2, dp=1, mp=2, sharding=2)
    assert hm.degree("dp") == 2 and hm.degrees["dcn_dp"] == 2
    assert hm.degree("mp") == 2 and hm.degree("sharding") == 2
    # one psum over dp inside shard_map covers the DCN-crossing replicas
    def body(x):
        return jax.lax.psum(x, "dp")

    x = jnp.arange(2.0).reshape(2, 1)
    out = jax.shard_map(body, mesh=hm.mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(out), [[1.0], [1.0]])
