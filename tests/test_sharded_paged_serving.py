"""Sharded paged serving (ISSUE 16): the K/V page pool spans a
tensor-parallel mesh.

The pool shards on the kv-head dimension over the mesh's ``mp`` axis;
block tables, per-slot lengths and ALL host-side bookkeeping
(allocator, grow/preempt/donate, radix tree, refcounts) stay global.
Contracts pinned here:

- bit-exact token parity (greedy AND seeded-sampled) vs the
  single-device oracle, including an optimistic-admission
  preemption/replay under pool pressure;
- per-device pool page bytes shrink to ~1/mp with block tables
  replicated;
- ``pool_balance()`` / ``occupancy()`` report balanced per-shard views
  and the kill-drill postmortem freezes them;
- steady-state sharded decode is zero-recompile after warmup, and a
  CostCatalog SHARED across servers at different mp never trips the
  post-warmup recompile alarm (ops are namespaced ``decode_mp4``);
- the shard_map'd Pallas kernels (interpret mode) match the unsharded
  launches bit-for-bit;
- ``fused+mesh`` stays a ROADMAP-pointered refusal (split mode is the
  mesh serving path).

Runs under conftest's forced 8 host devices; skips cleanly elsewhere.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as pt
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import PagedKVCache
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.ops.pallas import ragged_prefill as rp

pytestmark = [
    pytest.mark.mesh,
    pytest.mark.skipif(
        len(jax.devices()) < 4,
        reason="needs >= 4 forced host devices "
               "(XLA_FLAGS=--xla_force_host_platform_device_count=8)"),
]


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("mp",))


@pytest.fixture(scope="module")
def model4():
    """llama with 4 kv heads — divisible by mp=2 AND mp=4 (llama_tiny
    has 2, which caps it at mp=2)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=1,
                      num_heads=8, num_kv_heads=4,
                      intermediate_size=128, max_seq_len=128)
    pt.seed(21)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=7, lo=3, hi=10):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, (int(k),)).astype(np.int32)
            for k in rng.integers(lo, hi, (n,))]


def _run_pair(model, mesh, prompts, n_new, seeds=None, srv_kw=None):
    """The same workload through a single-device oracle and a mesh
    server (identical config otherwise); returns (oracle, sharded)
    servers after asserting bit-identical per-request tokens."""
    kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
              page_size=8, num_pages=24)
    kw.update(srv_kw or {})
    oracle = ContinuousBatchingServer(model, **kw)
    sharded = ContinuousBatchingServer(model, mesh=mesh, **kw)
    seeds = seeds or [None] * len(prompts)
    ra = [oracle.submit(p, max_new_tokens=n_new, seed=s)
          for p, s in zip(prompts, seeds)]
    rb = [sharded.submit(p, max_new_tokens=n_new, seed=s)
          for p, s in zip(prompts, seeds)]
    oa, ob = oracle.run(), sharded.run()
    for a, b in zip(ra, rb):
        np.testing.assert_array_equal(oa[a], ob[b])
    return oracle, sharded


class TestShardedPagedParity:
    @pytest.mark.slow
    def test_greedy_parity_preemption_and_pool_shrink_mp4(self, model4):
        """The acceptance drill: optimistic admission on a tight pool
        forces a preemption/replay on BOTH servers; tokens stay
        bit-exact, the mesh pool's per-device bytes measure ~1/4 of the
        oracle's, block tables stay replicated, and the kill-drill
        postmortem freezes balanced per-shard views."""
        prompts = _prompts(3, seed=11, lo=7, hi=10)
        oracle, sharded = _run_pair(
            model4, _mesh(4), prompts, n_new=24,
            srv_kw=dict(num_pages=8, admission="optimistic",
                        headroom_pages=1, recorder=True))
        # pressure really happened, identically on both sides
        bal = sharded.pool_balance()
        assert bal.preemptions >= 1
        assert bal.preemptions == oracle.pool_balance().preemptions
        # per-device pool bytes: shard0 holds <= (1/4 + eps) of the
        # oracle's pool (kv-head dim split 4 ways)
        for name in ("k", "v"):
            whole = oracle._caches["pool"][name]
            part = sharded._caches["pool"][name]
            assert part.nbytes == whole.nbytes            # global shape
            shard0 = part.addressable_shards[0].data.nbytes
            assert shard0 <= whole.nbytes // 4 + 128
        assert sharded._caches["bt"].sharding.is_fully_replicated
        # per-shard balance views: structural balance made explicit
        assert bal.num_shards == 4
        assert len(bal.per_shard) == 4
        assert all(s == bal.per_shard[0] for s in bal.per_shard)
        assert bal.per_shard[0]["free"] == bal[0]
        assert bal.shard_page_bytes is not None
        occ = sharded._kv.occupancy(num_shards=4)
        assert [s["used_pages"] for s in occ["shards"]] \
            == [occ["used_pages"]] * 4
        # kill drill: the postmortem bundle freezes the shard views
        sharded.kill()
        pm = sharded.postmortems()[-1]
        sec = pm["pool_balance"]
        assert sec["num_shards"] == 4
        assert len(sec["per_shard"]) == 4
        assert sec["shard_page_bytes"] == bal.shard_page_bytes
        assert len(pm["block_table"]["shards"]) == 4

    def test_seeded_sampled_parity_mp4(self, model4):
        prompts = _prompts(2, seed=12)
        _run_pair(model4, _mesh(4), prompts, n_new=8,
                  seeds=[101, 102],
                  srv_kw=dict(do_sample=True, temperature=0.8,
                              top_k=20, top_p=0.9))

    @pytest.mark.slow
    def test_greedy_parity_mp2_llama_tiny(self):
        """llama_tiny's 2 kv heads divide a 2-way mesh — the stock tiny
        config serves sharded without a custom head count. (slow:
        compile-heavy secondary coverage — tier-1 carries the mp=4
        acceptance drill on the same builder.)"""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(22)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        _run_pair(model, _mesh(2), _prompts(1, seed=13), n_new=5)

    @pytest.mark.slow
    def test_greedy_parity_mp2_mixtral_and_gpt(self):
        """The other two paged bundle builders take the mesh too:
        mixtral (GQA + expert-parallel MoE) and gpt (MHA, fused qkv).
        (slow: two extra model families' compiles; the sharding path
        they exercise is the same `_mesh_paged_caches` placement the
        tier-1 llama drill pins.)"""
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        from paddle_tpu.models.mixtral import (MixtralForCausalLM,
                                               mixtral_tiny)
        for seed, build in ((23, lambda: MixtralForCausalLM(
                                 mixtral_tiny())),
                            (24, lambda: GPTForCausalLM(gpt2_tiny()))):
            pt.seed(seed)
            model = build()
            model.eval()
            _run_pair(model, _mesh(2), _prompts(1, seed=seed), n_new=4)

    def test_indivisible_kv_heads_fall_back_to_replicated(self):
        """llama_tiny kv heads (2) aren't divisible by 4: the pool
        falls back to replicated placement (same rule as _apply_mesh
        weights) and still serves bit-exactly."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(25)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        _, sharded = _run_pair(model, _mesh(4), _prompts(1, seed=14),
                               n_new=3)
        assert sharded._pool_shards == 1
        assert sharded._caches["pool"]["k"].sharding.is_fully_replicated
        assert sharded.pool_balance().num_shards == 1

    def test_register_prefix_and_auto_cache_on_mesh(self, model4):
        """Prefix caching needs no mesh branch: cached page ids address
        the SHARDED pool (their K/V split across shards like any live
        page) while the radix tree, refcounts and pins stay host-side
        and global. A registered prefix pins pages, hits stay
        bit-exact vs the oracle, and a repeated prompt auto-hits off
        donated pages — on the mesh exactly as on one device."""
        rng = np.random.default_rng(19)
        prefix = rng.integers(0, 256, (10,)).astype(np.int32)
        tails = [rng.integers(0, 256, (n,)).astype(np.int32)
                 for n in (3, 5)]
        prompts = [np.concatenate([prefix, t]) for t in tails]
        # same tail resubmitted: the second pass auto-hits donations
        prompts = prompts + [prompts[0]]
        kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                  page_size=8, num_pages=24)
        oracle = ContinuousBatchingServer(model4, **kw)
        sharded = ContinuousBatchingServer(model4, mesh=_mesh(4), **kw)
        for srv in (oracle, sharded):
            assert srv.register_prefix(prefix) == 10
        bal = sharded.pool_balance()
        assert bal[2] == 1                      # one pinned page
        assert bal.per_shard[0]["pinned"] == 1  # on every shard
        ra = [oracle.submit(p, max_new_tokens=4) for p in prompts]
        rb = [sharded.submit(p, max_new_tokens=4) for p in prompts]
        oa, ob = oracle.run(), sharded.run()
        for a, b in zip(ra, rb):
            np.testing.assert_array_equal(oa[a], ob[b])
        assert sharded.stats["prefix_auto_hits"] \
            == oracle.stats["prefix_auto_hits"]

    def test_fused_mesh_refuses_with_roadmap_pointer(self, model4):
        with pytest.raises(NotImplementedError, match="ROADMAP"):
            ContinuousBatchingServer(model4, max_slots=2,
                                     max_cache_len=64,
                                     cache_backend="paged", page_size=8,
                                     num_pages=24, serving_mode="fused",
                                     mesh=_mesh(4))


class TestShardedCosts:
    def test_steady_state_sharded_decode_zero_recompile(self, model4):
        """Slot churn on the mesh after warmup must not recompile: the
        decode program's signature is static (pool + full slot batch),
        so wave 2's different prompts/slot refills reuse wave 1's
        executable — compile counts frozen, recompiles == 0."""
        srv = ContinuousBatchingServer(
            model4, max_slots=2, max_cache_len=64,
            cache_backend="paged", page_size=8, num_pages=24,
            mesh=_mesh(4), costs=True)
        wave1 = _prompts(3, seed=15, lo=5, hi=6)
        for p in wave1:
            srv.submit(p, max_new_tokens=8)
        srv.run()
        frozen = srv.costs.compiles()
        assert frozen.get("decode_mp4", 0) == 1   # namespaced, priced
        assert "decode" not in frozen             # bare name = mp1 only
        wave2 = _prompts(3, seed=16, lo=5, hi=6)  # same widths, new ids
        for p in wave2:
            srv.submit(p, max_new_tokens=8)
        srv.run()
        assert srv.costs.compiles() == frozen
        assert srv.costs.recompiles == 0

    def test_shared_catalog_across_mp_never_trips_alarm(self, model4):
        """One CostCatalog fronting an mp=1 and an mp=4 server (a fleet
        sharing a registry): the sharded server's ops are namespaced
        (``decode_mp4``), so the warmed mp=1 ``decode`` op never sees a
        new shape signature — mesh size is a deployment choice, not a
        recompile."""
        from paddle_tpu.telemetry import CostCatalog
        cat = CostCatalog(warm_after_ticks=1)
        kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
                  page_size=8, num_pages=24, costs=cat)
        flat = ContinuousBatchingServer(model4, **kw)
        for p in _prompts(2, seed=17):
            flat.submit(p, max_new_tokens=8)
        flat.run()
        assert cat.warmed_op("decode")
        sharded = ContinuousBatchingServer(model4, mesh=_mesh(4), **kw)
        for p in _prompts(2, seed=18):
            sharded.submit(p, max_new_tokens=8)
        sharded.run()
        comp = cat.compiles()
        assert comp.get("decode") == 1 and comp.get("decode_mp4") == 1
        assert cat.recompiles == 0


class TestShardedKernels:
    """shard_map'd Pallas launches (interpret mode) vs the unsharded
    kernel: per-kv-head-shard splits must be bit-exact restitches."""

    def _pool(self, S, kvh, hd, P, pg, maxp, seed):
        rng = np.random.RandomState(seed)
        r = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32) * .5)
        kp, vp = r(P, pg, kvh, hd), r(P, pg, kvh, hd)
        bt = jnp.asarray(np.stack([
            rng.choice(np.arange(1, P), maxp, replace=False)
            for _ in range(S)]).astype(np.int32))
        return r, kp, vp, bt

    def test_paged_decode_shard_map_matches_unsharded(self):
        S, nh, kvh, hd, P, pg, maxp = 4, 8, 4, 32, 12, 8, 4
        r, kp, vp, bt = self._pool(S, kvh, hd, P, pg, maxp, seed=31)
        q = r(S, nh, hd)
        lengths = jnp.asarray(np.array([pg, 13, 1, maxp * pg], np.int32))
        want = pa.paged_attention(q, kp, vp, bt, lengths, interpret=True)
        got = pa.paged_attention(q, kp, vp, bt, lengths, interpret=True,
                                 mesh=_mesh(4))
        # per-shard launches batch 1 kv head where the unsharded kernel
        # batches 4 — CPU interpret mode vectorizes the reductions in a
        # different order, so parity is to float32 ulp, not bitwise
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   rtol=1e-6, atol=1e-7)

    def test_ragged_prefill_shard_map_matches_unsharded(self):
        S, C, nh, kvh, hd, P, pg, maxp = 3, 8, 8, 4, 32, 12, 8, 4
        r, kp, vp, bt = self._pool(S, kvh, hd, P, pg, maxp, seed=32)
        q = r(S, C, nh, hd)
        t0 = jnp.asarray(np.array([0, 5, 16], np.int32))
        last = jnp.asarray(np.array([7, 9, -1], np.int32))  # idle slot
        want = rp.ragged_prefill_attention(q, kp, vp, bt, t0, last,
                                           interpret=True)
        got = rp.ragged_prefill_attention(q, kp, vp, bt, t0, last,
                                          interpret=True, mesh=_mesh(4))
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_kv_head_shards_divisibility_rule(self):
        mesh = _mesh(4)
        assert pa.kv_head_shards(mesh, 4, 8) == 4
        assert pa.kv_head_shards(mesh, 2, 4) == 1     # kvh % mp != 0
        assert pa.kv_head_shards(None, 4, 8) == 1
        assert pa.kv_head_shards(_mesh(2), 2, 4) == 2


class TestPerShardAccounting:
    def test_occupancy_shards_view_is_host_side_only(self):
        """occupancy(num_shards=N) is pure host bookkeeping — no mesh
        required — and every shard reports the global counts (the pool
        splits on kv-heads, so each page id lives on every shard)."""
        kv = PagedKVCache(num_pages=9, page_size=8, max_slots=2,
                          pages_per_slot=4)
        kv.admit_slot(0, 10)
        kv.admit_slot(1, 5)
        occ = kv.occupancy(num_shards=4)
        assert len(occ["shards"]) == 4
        for i, s in enumerate(occ["shards"]):
            assert s == {"shard": i, "free_pages": occ["free_pages"],
                         "used_pages": occ["used_pages"]}
        assert "shards" not in kv.occupancy()      # default: unchanged
