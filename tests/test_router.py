"""Multi-replica front door (ISSUE 7): ReplicaRouter + RouterSupervisor
over N StubModel ContinuousBatchingServer replicas — prefix-affinity
routing on PrefixCache sketches, deadline charging across the router,
replica failover via evacuate(), per-replica circuit breakers, rolling
restarts, and the router chaos suite.

Everything runs on the StubModel double (tests/_serving_stub.py): no
transformer compiles, closed-form expected tokens, deterministic
single-threaded drives (step() + poll()) wherever the assertion needs
an exact trace, threaded start()/wait() where the contract under test
is concurrent."""
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.prefix_cache import prefix_fingerprints
from paddle_tpu.inference.router import ReplicaRouter
from paddle_tpu.inference.serving import serve_metrics
from paddle_tpu.reliability import (CircuitBreaker, DeadlineExceeded,
                                    FaultInjector, QueueFullError,
                                    ReliabilityError, ReplicaLostError,
                                    RequestCancelled, RetryPolicy,
                                    faults)
from paddle_tpu.telemetry import FakeClock


def _prompt(*toks):
    return np.asarray(toks, np.int32)


def _rep(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 8)
    return ContinuousBatchingServer(StubModel(), **kw)


def _router(n=3, rep_kw=None, **kw):
    reps = [_rep(**(rep_kw or {})) for _ in range(n)]
    return ReplicaRouter(reps, **kw), reps


def _drive(router, reps, max_iters=3000):
    """Deterministic single-threaded drive: poll the supervisor and
    step every serving replica until the whole fleet is idle (dead
    replicas are never stepped — that is the crash being simulated)."""
    idle = 0
    for _ in range(max_iters):
        router.poll()
        busy = False
        for rep in reps:
            if rep.health == "dead":
                continue
            if rep.queue_depth() or rep.in_flight():
                rep.step()
                busy = True
        if busy:
            idle = 0
        else:
            idle += 1
            if idle >= 2:        # one extra pass: poll may requeue
                return
    raise AssertionError("router drive did not converge")


def _balanced(rep):
    """Assert this replica's pool leaked nothing (live == 0 once idle)
    and return the balance tuple."""
    free, live, pinned, cached = rep.pool_balance()
    assert live == 0, f"leaked {live} pages"
    assert free + pinned + cached == rep._kv.num_pages - 1
    return free, live, pinned, cached


# ------------------------------------------------------------- routing

class TestRouting:
    def test_affinity_routes_shared_prefix_to_same_replica(self):
        router, reps = _router()
        shared = np.arange(16, dtype=np.int32) % 16       # 2 full pages
        for i in range(5):
            p = np.concatenate([shared, _prompt(i + 1)])
            rid = router.submit(p, max_new_tokens=3)
            _drive(router, reps)
            np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                          stub_tokens(p, 3))
        # first request was a sketch miss (fallback), every follow-up
        # found the donated pages on the same replica
        assert router.stats["affinity_hits"] == 4
        assert router.stats["fallbacks"] == 1
        routed = router.stats["routed"]
        assert max(routed) == 5 and sum(routed) == 5
        winner = reps[int(np.argmax(routed))]
        assert winner.stats["prefix_auto_hits"] == 4
        assert winner.stats["prefix_auto_hit_tokens"] == 4 * 16

    def test_round_robin_cycles_serving_replicas(self):
        router, reps = _router(policy="round_robin")
        for i in range(6):
            rid = router.submit(_prompt(1, 2, i + 1), max_new_tokens=2)
            _drive(router, reps)
            router.wait(rid, timeout=5)
        assert router.stats["routed"] == [2, 2, 2]
        assert router.stats["affinity_hits"] == 0

    def test_fallback_is_least_loaded(self):
        router, reps = _router()
        # no prefixes cached anywhere: affinity 0 for everyone, so the
        # queue-depth/in-flight load signal decides. Nothing is stepped
        # between submits, so each lands on the emptiest replica.
        rids = [router.submit(_prompt(7, i + 1), max_new_tokens=2)
                for i in range(3)]
        assert router.stats["routed"] == [1, 1, 1]
        _drive(router, reps)
        for rid in rids:
            router.wait(rid, timeout=5)

    def test_dense_replicas_route_by_load(self):
        router, reps = _router(rep_kw={"cache_backend": "dense"})
        rids = [router.submit(_prompt(3, i + 1), max_new_tokens=2)
                for i in range(3)]
        assert router.stats["routed"] == [1, 1, 1]
        assert router.stats["affinity_hits"] == 0   # nothing to be
        _drive(router, reps)                        # affine to
        for rid in rids:
            router.wait(rid, timeout=5)

    def test_sketch_and_fingerprints_agree(self):
        router, reps = _router(n=1)
        p = np.arange(20, dtype=np.int32) % 16
        rid = router.submit(p, max_new_tokens=3)
        _drive(router, reps)
        router.wait(rid, timeout=5)
        sketch = reps[0].prefix_sketch()
        fps = prefix_fingerprints(p, 8)            # 2 full pages cached
        assert fps[0] in sketch and fps[1] in sketch
        cold = prefix_fingerprints(_prompt(*([9] * 8)), 8)
        assert cold[0] not in sketch

    def test_no_replica_serving_raises_replica_lost(self):
        router, reps = _router(n=2)
        for rep in reps:
            rep.kill()
        with pytest.raises(ReplicaLostError):
            router.submit(_prompt(1, 2), max_new_tokens=2)

    def test_every_replica_shedding_raises_queue_full(self):
        router, reps = _router(n=2, rep_kw={"max_queue": 0})
        with pytest.raises(QueueFullError):
            router.submit(_prompt(1, 2), max_new_tokens=2)
        assert router.stats["dispatch_retries"] == 2   # both tried

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            _router(policy="sideways")

    def test_spent_deadline_rejected_at_router(self):
        router, _ = _router(n=1)
        with pytest.raises(DeadlineExceeded):
            router.submit(_prompt(1), max_new_tokens=2, deadline_s=0.0)


# ------------------------------------------------------------ deadlines

class TestRouterDeadlines:
    def test_requeue_charges_time_spent_on_lost_replica(self):
        """The absolute deadline is fixed at router submit: a request
        stranded on a dead replica past its deadline fails typed at
        requeue — no sibling time is wasted on it."""
        fc = FakeClock()
        router, reps = _router(n=2, clock=fc,
                               rep_kw={"clock": fc})
        rid = router.submit(_prompt(1, 2, 3), max_new_tokens=4,
                            deadline_s=5.0)
        victim = int(np.argmax(router.stats["routed"]))
        reps[victim].kill()
        fc.advance(10.0)                  # expires while stranded
        router.poll()                     # harvest + requeue attempt
        with pytest.raises(DeadlineExceeded):
            router.wait(rid, timeout=5)

    def test_requeue_passes_remaining_deadline_to_sibling(self):
        fc = FakeClock()
        router, reps = _router(n=2, clock=fc, rep_kw={"clock": fc})
        t0 = fc.now()
        rid = router.submit(_prompt(1, 2, 3), max_new_tokens=4,
                            deadline_s=5.0)
        victim = int(np.argmax(router.stats["routed"]))
        fc.advance(2.0)                   # time spent queued pre-crash
        reps[victim].kill()
        router.poll()
        sibling = reps[1 - victim]
        assert sibling.queue_depth() == 1
        # the sibling sees the ORIGINAL absolute deadline, not a fresh
        # 5 s budget
        assert sibling._queue[0].deadline == pytest.approx(t0 + 5.0)
        assert rid not in router.failures
        _drive(router, reps)
        np.testing.assert_array_equal(
            router.wait(rid, timeout=5),
            stub_tokens(_prompt(1, 2, 3), 4))


# ------------------------------------------------------------- failover

class TestFailover:
    def test_kill_mid_decode_queued_complete_on_siblings(self):
        """ISSUE 7 acceptance: killing a replica mid-decode completes
        every QUEUED request on siblings with bit-exact greedy tokens,
        flushes mid-decode partials to their waiters, and leaks zero
        pages anywhere — all counter-asserted."""
        router, reps = _router()
        shared = np.arange(16, dtype=np.int32) % 16
        # seed the prefix on one replica so affinity concentrates the
        # whole workload there
        p0 = np.concatenate([shared, _prompt(1)])
        rid = router.submit(p0, max_new_tokens=3)
        _drive(router, reps)
        router.wait(rid, timeout=5)
        victim_idx = int(np.argmax(router.stats["routed"]))
        victim = reps[victim_idx]
        # two blockers occupy the victim's slots mid-decode...
        blk_p = np.concatenate([shared, _prompt(9)])
        blockers = [router.submit(blk_p, max_new_tokens=30)
                    for _ in range(2)]
        for _ in range(3):                # admit + a few decode ticks
            victim.step()
        assert victim.in_flight() == 2
        # ...and three more wait in its queue
        q_p = [np.concatenate([shared, _prompt(7, i)]) for i in range(3)]
        queued = [router.submit(p, max_new_tokens=4) for p in q_p]
        assert victim.queue_depth() == 3
        assert router.stats["routed"][victim_idx] == 6
        victim.kill()
        assert victim.health == "dead"
        _drive(router, reps)              # poll harvests + siblings run
        # queued requests completed on siblings, bit-exact
        for rid, p in zip(queued, q_p):
            np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                          stub_tokens(p, 4))
        st = router.stats
        assert st["evacuations"] >= 1
        assert st["requeued"] == 3
        assert st["replica_lost"] == 0
        # mid-decode blockers flushed their partials (bit-exact prefix)
        for rid in blockers:
            out = router.wait(rid, timeout=5)
            assert 1 <= len(out) < 30
            np.testing.assert_array_equal(
                out, stub_tokens(blk_p, 30)[:len(out)])
        for rep in reps:                  # zero leaks, even the corpse
            _balanced(rep)

    def test_failover_sampled_tokens_bit_exact(self):
        """The harvested entries carry their RESOLVED seeds, so a
        sibling draws the identical sampling chain the lost replica
        would have."""
        router, reps = _router(rep_kw={"do_sample": True,
                                       "temperature": 0.8, "top_k": 8,
                                       "seed": 123})
        p = _prompt(5, 11, 2)
        # oracle: the same request served by a healthy fleet
        ref_router, ref_reps = _router(
            n=1, rep_kw={"do_sample": True, "temperature": 0.8,
                         "top_k": 8, "seed": 123})
        ref = ref_router.submit(p, max_new_tokens=6, seed=77)
        _drive(ref_router, ref_reps)
        expect = ref_router.wait(ref, timeout=5)
        # lose the replica before the request is ever admitted
        rid = router.submit(p, max_new_tokens=6, seed=77)
        victim = int(np.argmax(router.stats["routed"]))
        reps[victim].kill()
        _drive(router, reps)
        np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                      expect)
        assert router.stats["requeued"] == 1

    def test_no_sibling_raises_replica_lost_typed(self):
        router, reps = _router(n=2)
        rid = router.submit(_prompt(1, 2), max_new_tokens=2)
        victim = int(np.argmax(router.stats["routed"]))
        reps[victim].kill()
        reps[1 - victim].kill()           # nobody left to requeue onto
        router.poll()
        assert router.stats["replica_lost"] == 1
        with pytest.raises(ReplicaLostError):
            router.wait(rid, timeout=5)

    def test_cancel_during_failover_fails_typed(self):
        router, reps = _router(n=2)
        rid = router.submit(_prompt(1, 2, 3), max_new_tokens=4)
        victim_idx = int(np.argmax(router.stats["routed"]))
        victim = reps[victim_idx]
        victim.kill()
        # harvest manually (as the supervisor would), THEN cancel while
        # the request sits in the router's hands, then requeue
        harvested = victim.evacuate(flush_partials=True)
        assert len(harvested) == 1
        assert router.cancel(rid) is False   # not live anywhere now
        router._requeue(victim_idx, harvested)
        assert router.stats["requeued"] == 0
        with pytest.raises(RequestCancelled):
            router.wait(rid, timeout=5)

    def test_backpressure_holds_at_router_until_a_sibling_can_take(self):
        """Review regression: a harvested request whose siblings are
        all FULL must be held at the router and retried (transient
        backpressure), not failed with a permanent ReplicaLostError —
        the sibling drains seconds later."""
        reps = [_rep(max_slots=1),
                _rep(max_slots=1, max_queue=0)]   # sibling: always full
        router = ReplicaRouter(reps)
        p = _prompt(1, 2, 3)
        rid = router.submit(p, max_new_tokens=4)
        assert router.stats["routed"] == [1, 0]
        reps[0].kill()
        router.poll()                     # harvest; sibling sheds
        assert router.backlog == 1        # held, NOT failed
        assert rid not in router.failures
        router.poll()                     # still nowhere to go
        assert router.backlog == 1
        reps[0].start()                   # the "sibling" recovers (the
        router.poll()                     # restarted source may take
        assert router.backlog == 0        # its old work back)
        assert router.stats["requeued"] == 1
        _drive(router, reps)
        np.testing.assert_array_equal(router.wait(rid, timeout=60),
                                      stub_tokens(p, 4))
        reps[0].stop()

    def test_wait_survives_replica_thread_death_until_failover(self):
        """Review regression: a dead serve THREAD raises a generic
        RuntimeError for every waiter without consuming per-rid state;
        router.wait must keep waiting for the supervisor's failover
        instead of leaking the raw thread death to the client."""
        router, reps = _router(n=2)
        reps[0].start()
        reps[1].start()
        p = _prompt(1, 2, 3)
        rid = router.submit(p, max_new_tokens=4)
        victim = int(np.argmax(router.stats["routed"]))
        # crash the victim's serve loop with a non-Exception (the
        # BaseException path: _thread_error set, health dead, queue
        # and slots left intact)
        reps[victim]._sup.allow = lambda: (_ for _ in ()).throw(
            SystemExit("crashed"))
        deadline = time.monotonic() + 10
        while reps[victim]._thread_error is None:
            assert time.monotonic() < deadline, "loop never crashed"
            time.sleep(0.005)
        assert reps[victim].health == "dead"
        # BEFORE any failover poll: wait must not surface the thread
        # death — it times out instead (the request is still pending)
        with pytest.raises(TimeoutError):
            router.wait(rid, timeout=0.3)
        router.poll()                     # failover to the sibling
        out = router.wait(rid, timeout=60)
        np.testing.assert_array_equal(out, stub_tokens(p, 4))
        assert router.stats["requeued"] >= 1
        reps[1 - victim].stop()

    def test_breaker_diverts_flapping_replica(self):
        fc = FakeClock()
        breakers = [CircuitBreaker(failure_threshold=2,
                                   reset_after_s=10.0, clock=fc)
                    for _ in range(2)]
        router, reps = _router(n=2, policy="least_loaded",
                               breakers=breakers, clock=fc)
        calls = {"n": 0}
        real_submit = reps[0].submit

        def flaky(*a, **kw):
            calls["n"] += 1
            raise RuntimeError("replica wedged")

        reps[0].submit = flaky
        p = _prompt(1, 2, 3)
        rids = [router.submit(p, max_new_tokens=2) for _ in range(2)]
        # both submits tried rep0 first (lowest load), failed, and
        # landed on rep1 — two consecutive failures open the breaker
        assert calls["n"] == 2
        assert breakers[0].state == CircuitBreaker.OPEN
        rids.append(router.submit(p, max_new_tokens=2))
        assert calls["n"] == 2            # open breaker: never dialed
        assert router.stats["routed"] == [0, 3]
        # cooldown elapses, the replica recovers: half-open probe
        # dispatch succeeds and closes the breaker
        reps[0].submit = real_submit
        fc.advance(11.0)
        rids.append(router.submit(p, max_new_tokens=2))
        assert router.stats["routed"] == [1, 3]
        assert breakers[0].state == CircuitBreaker.CLOSED
        _drive(router, reps)
        for rid in rids:
            np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                          stub_tokens(p, 2))

    def test_rolling_restart_zero_failed_requests(self):
        """ISSUE 7 acceptance: rolling_restart() over 3 StubModel
        replicas finishes with zero failed requests."""
        router, reps = _router()
        router.start()
        try:
            prompts = [_prompt(1, 2, 3, i + 1) for i in range(9)]
            rids = [router.submit(p, max_new_tokens=6) for p in prompts]
            router.rolling_restart(drain_timeout=60.0)
            for rid, p in zip(rids, prompts):
                np.testing.assert_array_equal(
                    router.wait(rid, timeout=60), stub_tokens(p, 6))
            assert router.stats["restarts"] == 3
            assert router.stats["replica_lost"] == 0
            assert router.failures == {}
            assert router.health == "healthy"
            # the fleet still serves after the bounce
            p = _prompt(9, 9)
            rid = router.submit(p, max_new_tokens=3)
            np.testing.assert_array_equal(router.wait(rid, timeout=60),
                                          stub_tokens(p, 3))
        finally:
            router.stop()
        for rep in reps:
            _balanced(rep)

    def test_orphaned_dispatch_replaces_instead_of_routing_to_corpse(self):
        """Review regression (dispatch-vs-evacuate race): a request a
        replica accepted but the supervisor harvested BEFORE the
        dispatching thread recorded the route must be placed again —
        not recorded as a route to a corpse the waiter polls forever.
        The race window is synthesized by pre-parking the orphan entry
        the harvest side would leave."""
        router, reps = _router(n=2)
        rrid_next = reps[0]._next_rid
        with router._lock:
            router._orphans[(0, rrid_next)] = 3
        p = _prompt(1, 2)
        rid = router.submit(p, max_new_tokens=2)
        # rep0's acceptance was claimed as orphaned: the request was
        # re-placed on rep1 and only THAT dispatch recorded
        assert router.stats["routed"] == [0, 1]
        with router._lock:
            assert router._routes[rid].idx == 1
            assert not router._orphans          # claimed
        reps[0].evacuate()        # drop the synthetic duplicate copy
        _drive(router, reps)
        np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                      stub_tokens(p, 2))

    def test_rolling_restart_drains_backlog_without_supervisor(self):
        """Review regression: requests parked by sibling backpressure
        DURING a rolling restart must be drained by rolling_restart
        itself — a supervisor thread may not be running."""
        reps = [_rep(max_slots=1),
                _rep(max_slots=1, max_queue=0)]   # sibling: always full
        router = ReplicaRouter(reps)
        reps[0].start()
        reps[1].start()
        p = _prompt(1, 2, 3)
        rids = [router.submit(p, max_new_tokens=4) for _ in range(3)]
        router.rolling_restart(drain_timeout=60)
        for rid in rids:
            np.testing.assert_array_equal(
                router.wait(rid, timeout=60), stub_tokens(p, 4))
        assert router.failures == {}
        assert router.backlog == 0
        reps[0].stop()
        reps[1].stop()

    def test_threaded_kill_failover(self):
        """The supervisor THREAD (not a manual poll) notices a crash
        and requeues; waiters blocked across the failover follow the
        request to its new replica."""
        router, reps = _router(rep_kw={"max_cache_len": 8192})
        router.start(poll_interval=0.005)
        try:
            # park long requests on every replica so the next submits
            # stay queued on their replica
            blockers = [router.submit(_prompt(9, i), max_new_tokens=5000)
                        for i in range(6)]
            deadline = time.monotonic() + 10
            while any(r.queue_depth() for r in reps):
                if time.monotonic() > deadline:
                    raise AssertionError("blockers never admitted")
                time.sleep(0.005)
            q_p = [_prompt(1, 2, i + 1) for i in range(3)]
            queued = [router.submit(p, max_new_tokens=4) for p in q_p]
            victim = max(range(3), key=lambda i: reps[i].queue_depth())
            reps[victim].kill()
            for rid, p in zip(queued, q_p):
                np.testing.assert_array_equal(
                    router.wait(rid, timeout=60), stub_tokens(p, 4))
            assert router.stats["requeued"] >= 1
            for rid in blockers:
                router.cancel(rid)
        finally:
            router.stop(drain=False)


# ---------------------------------------------------------------- chaos

@pytest.mark.chaos
class TestRouterChaos:
    def test_dispatch_fault_storm_recovers_no_leaks(self):
        """30% router.dispatch faults: failed dispatches fall through
        to siblings, every submit either routes or fails typed, no
        wedged waiters, no page leaks, and the fleet serves cleanly
        once the storm passes."""
        fi = FaultInjector(seed=42).on(faults.ROUTER_DISPATCH,
                                       probability=0.3)
        router, reps = _router(fault_injector=fi,
                               breakers=[CircuitBreaker(
                                   failure_threshold=10_000)
                                   for _ in range(3)])
        ok, failed = {}, {}
        prompts = [_prompt(2, 5, (i % 13) + 1) for i in range(20)]
        for i, p in enumerate(prompts):
            try:
                rid = router.submit(p, max_new_tokens=4)
            except ReliabilityError as e:
                failed[i] = e
                continue
            _drive(router, reps)
            ok[i] = router.wait(rid, timeout=5)
        assert len(ok) + len(failed) == len(prompts)
        for i, out in ok.items():
            np.testing.assert_array_equal(out,
                                          stub_tokens(prompts[i], 4))
        assert fi.fired() > 0, "storm never fired; raise the rate"
        assert router.stats["dispatch_retries"] >= fi.fired()
        fi.disarm()                       # recovery
        rid = router.submit(_prompt(8, 8), max_new_tokens=3)
        _drive(router, reps)
        np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                      stub_tokens(_prompt(8, 8), 3))
        for rep in reps:
            _balanced(rep)

    def test_evacuate_fault_aborts_then_retries(self):
        """An injected router.evacuate fault aborts the harvest sweep
        BEFORE any state moves: the requests stay queued on the corpse
        and the next poll retries — recovery, not loss."""
        fi = FaultInjector(seed=0).on(faults.ROUTER_EVACUATE,
                                      schedule=[0])
        router, reps = _router(n=2, fault_injector=fi)
        rid = router.submit(_prompt(1, 2, 3), max_new_tokens=4)
        victim_idx = int(np.argmax(router.stats["routed"]))
        reps[victim_idx].kill()
        assert router.poll() == 1         # first sweep dies injected
        assert reps[victim_idx].queue_depth() == 1   # nothing moved
        assert router.supervisor.failed_sweeps == 1
        assert router.poll() == 0         # retry harvests
        assert router.stats["requeued"] == 1
        _drive(router, reps)
        np.testing.assert_array_equal(
            router.wait(rid, timeout=5),
            stub_tokens(_prompt(1, 2, 3), 4))

    def test_same_seed_identical_trace_and_state(self):
        """Same injector seed + same scripted drive => identical
        injection trace, results, failure types, and counters."""

        def script(seed):
            fi = FaultInjector(seed=seed).on(faults.ROUTER_DISPATCH,
                                             probability=0.25)
            router, reps = _router(
                fault_injector=fi, seed=9,
                breakers=[CircuitBreaker(failure_threshold=10_000)
                          for _ in range(3)])
            results, fails = {}, {}
            # phase 1: sequential traffic under dispatch faults
            for i in range(6):
                p = _prompt(3, 1, i + 1)
                try:
                    rid = router.submit(p, max_new_tokens=3)
                except ReliabilityError as e:
                    fails[i] = type(e).__name__
                    continue
                _drive(router, reps)
                results[i] = tuple(int(x)
                                   for x in router.wait(rid, timeout=5))
            # phase 2: queue a burst, kill the busiest, fail over
            rids = {}
            for i in range(6, 12):
                p = _prompt(3, 1, i + 1)
                try:
                    rids[i] = (router.submit(p, max_new_tokens=3), p)
                except ReliabilityError as e:
                    fails[i] = type(e).__name__
            victim = int(np.argmax([r.queue_depth() for r in reps]))
            reps[victim].kill()
            _drive(router, reps)
            for i, (rid, p) in rids.items():
                try:
                    results[i] = tuple(int(x)
                                       for x in router.wait(rid,
                                                            timeout=5))
                except ReliabilityError as e:
                    fails[i] = type(e).__name__
            return (fi.trace, results, fails, router.stats,
                    [r.pool_balance() for r in reps])

        a, b = script(777), script(777)
        assert a == b
        assert a[0], "deterministic run injected nothing"


# ------------------------------------------------- aggregated telemetry

class TestRouterTelemetry:
    def test_aggregated_healthz_and_stats(self):
        """serve_metrics(router): /healthz answers 200 iff >= 1 replica
        is serving; /stats carries router counters + per-replica
        health."""
        router, reps = _router(telemetry=True)
        ms = serve_metrics(router)
        try:
            with urllib.request.urlopen(ms.url + "/healthz") as r:
                assert r.status == 200
            reps[0].kill()
            assert router.health == "degraded"
            with urllib.request.urlopen(ms.url + "/healthz") as r:
                assert r.status == 200    # 2 of 3 still serving
            reps[1].kill()
            reps[2].kill()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ms.url + "/healthz")
            assert ei.value.code == 503
            assert b'"dead"' in ei.value.read()
            with urllib.request.urlopen(ms.url + "/stats") as r:
                body = r.read().decode()
            assert '"replicas"' in body and '"routed"' in body
        finally:
            ms.close()

    def test_router_counters_exposed(self):
        router, reps = _router(n=2, telemetry=True)
        rid = router.submit(_prompt(1, 2, 3), max_new_tokens=3)
        victim = int(np.argmax(router.stats["routed"]))
        reps[victim].kill()
        _drive(router, reps)
        router.wait(rid, timeout=5)
        text = router.telemetry.registry.render()
        for name in ("router_routed_total", "router_requeued_total",
                     "router_evacuations_total", "router_queue_depth",
                     "router_replicas_serving", "router_health"):
            assert name in text, name

    def test_affinity_beats_round_robin_counters(self):
        """ISSUE 7 acceptance (counter form of the router bench): on a
        shared-prefix workload over 3 replicas, affinity routing's
        replica-level prefix-hit counters beat round-robin's."""

        def run(policy):
            router, reps = _router(policy=policy)
            rng = np.random.default_rng(0)
            groups = [rng.integers(0, 16, (16,)).astype(np.int32)
                      for _ in range(2)]
            for rnd in range(6):
                for g in groups:
                    p = np.concatenate([g, _prompt(rnd + 1)])
                    rid = router.submit(p, max_new_tokens=2)
                    _drive(router, reps)
                    np.testing.assert_array_equal(
                        router.wait(rid, timeout=5), stub_tokens(p, 2))
            hits = sum(r.stats["prefix_auto_hits"] for r in reps)
            return hits, router

        aff_hits, aff_router = run("affinity")
        rr_hits, _ = run("round_robin")
        # affinity: each group misses once then always hits (5 + 5);
        # round-robin spreads each group over all 3 replicas
        assert aff_hits == 10
        assert aff_router.stats["affinity_hits"] == 10
        assert rr_hits < aff_hits


# ----------------------------------------------------------------- bench


@pytest.mark.slow
@pytest.mark.bench
class TestRouterBenchSmoke:
    def test_router_bench_runs_and_orders_modes(self):
        """Smoke-run benchmarks/router_bench.py at toy scale: it must
        complete (walls included), affinity must beat round-robin on
        the fleet-wide hit counters, and the robustness legs must
        report zero failed requests."""
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks"))
        import router_bench
        out = router_bench.main(["--requests-per-group", "4",
                                 "--groups", "2", "--replicas", "3",
                                 "--system-tokens", "16",
                                 "--tail-tokens", "3",
                                 "--new-tokens", "3",
                                 "--failover-k", "4"])
        by_mode = {m["mode"]: m for m in out["modes"]}
        aff, rr = by_mode["affinity-3"], by_mode["round_robin-3"]
        assert aff["hits"] > rr["hits"]
        assert aff["prefill_tokens"] < rr["prefill_tokens"]
        assert aff["affinity_hits"] > 0
        assert out["failover"]["k"] == 4
        assert out["rolling_restart"]["failed"] == 0


# ----------------------------------------------------- evacuate() unit

class TestEvacuateHook:
    def test_evacuate_harvests_queued_keeps_inflight(self):
        srv = _rep(max_slots=1)
        ra = srv.submit(_prompt(1, 2), max_new_tokens=6)
        srv.step()                        # admit ra mid-decode
        rb = srv.submit(_prompt(3, 4), max_new_tokens=2)
        harvested = srv.evacuate()        # default: queued only
        assert [h.rid for h in harvested] == [rb]
        assert srv.queue_depth() == 0
        assert srv.in_flight() == 1       # ra keeps decoding
        out = srv.run()
        np.testing.assert_array_equal(out[ra],
                                      stub_tokens(_prompt(1, 2), 6))
        assert rb not in out and rb not in srv.failures

    def test_evacuate_flush_partials_matches_hard_stop(self):
        srv = _rep(max_slots=1)
        ra = srv.submit(_prompt(1, 2), max_new_tokens=10)
        srv.step()
        srv.step()
        harvested = srv.evacuate(flush_partials=True)
        assert harvested == []
        out = srv._results[ra]            # partial recorded, bit-exact
        np.testing.assert_array_equal(
            out, stub_tokens(_prompt(1, 2), 10)[:len(out)])
        _balanced(srv)                    # pages donated/freed, no leak

    def test_kill_preserves_state_for_harvest_then_restarts(self):
        srv = _rep(max_slots=1, max_cache_len=8192).start()
        ra = srv.submit(_prompt(1, 2), max_new_tokens=5000)
        deadline = time.monotonic() + 10
        while srv.queue_depth():          # wait for admission
            assert time.monotonic() < deadline
            time.sleep(0.005)
        rb = srv.submit(_prompt(5, 6), max_new_tokens=4)
        srv.kill()
        assert srv.health == "dead"
        assert srv.queue_depth() == 1     # rb still harvestable
        assert srv.in_flight() == 1       # ra still holds its slot
        assert rb not in srv.failures     # nothing failed behind our back
        harvested = srv.evacuate(flush_partials=True)
        assert [h.rid for h in harvested] == [rb]
        part = srv.wait(ra, timeout=5)    # flushed partial
        np.testing.assert_array_equal(
            part, stub_tokens(_prompt(1, 2), 5000)[:len(part)])
        srv.start()                       # crash drill over: restart
        rc = srv.submit(_prompt(7), max_new_tokens=3)
        np.testing.assert_array_equal(srv.wait(rc, timeout=60),
                                      stub_tokens(_prompt(7), 3))
        srv.stop()


# ------------------------------------------- ISSUE 8 satellites

class TestOrphanTTL:
    def test_foreign_rid_fails_typed_at_source_after_ttl(self):
        """ISSUE 8 satellite (PR-7 known cut): a FOREIGN request
        (submitted straight to a replica, not through the router)
        harvested off an evacuated queue used to age out of
        ``_orphans`` silently, leaving its waiter to its own timeout.
        Now TTL expiry fails it promptly at the SOURCE replica with a
        typed ``ReplicaLostError``, and the router counts it."""
        router, reps = _router(2)
        foreign = reps[0].submit(_prompt(1, 2, 3), max_new_tokens=4)
        reps[0].kill()                    # dies with the queue intact
        router.poll()                     # evacuate: rid has no route
        assert router.stats["orphaned"] == 0     # parked, not failed
        router.poll()                     # TTL ticking...
        router.poll()                     # ...expired: abandoned typed
        assert router.stats["orphaned"] == 1
        with pytest.raises(ReplicaLostError, match="foreign"):
            reps[0].wait(foreign, timeout=1.0)

    def test_orphaned_counted_in_router_telemetry(self):
        router, reps = _router(2, telemetry=True)
        reps[0].submit(_prompt(9, 9), max_new_tokens=3)
        reps[0].kill()
        for _ in range(3):
            router.poll()
        reg = router.telemetry.registry
        assert reg.get("router_orphaned_total").value == 1.0

    def test_router_owned_rids_are_never_orphan_failed(self):
        """Router-routed traffic keeps its PR-7 claim-and-requeue path:
        an evacuation of router-owned rids produces no orphan
        failures."""
        router, reps = _router(2, rep_kw={"max_slots": 1})
        rid = router.submit(_prompt(4, 5), max_new_tokens=4)
        src = next(i for i, r in enumerate(reps)
                   if r.queue_depth() or r.in_flight())
        reps[src].kill()
        for _ in range(4):
            router.poll()
        assert router.stats["orphaned"] == 0
        _drive(router, reps)
        out = router.wait(rid, timeout=10)
        np.testing.assert_array_equal(
            out, stub_tokens(_prompt(4, 5), 4)[:len(out)])


class TestPreemptPressureRouting:
    def test_pressure_diverts_load(self):
        """ISSUE 8: parked preempted requests weigh on the routing
        score (heavier than plain queue depth), so new traffic sheds
        away from a replica thrashing its KV pool."""
        router, reps = _router(2, policy="least_loaded")
        assert reps[0].preempt_pressure() == 0
        reps[0]._preempted.extend(object() for _ in range(3))
        for _ in range(3):
            router.submit(_prompt(1, 2), max_new_tokens=2)
        assert router.stats["routed"] == [0, 3]   # all shed to rep1
        reps[0]._preempted.clear()
        # queue depth 3 on rep1 now outweighs rep0's zero pressure
        router.submit(_prompt(1, 2), max_new_tokens=2)
        assert router.stats["routed"] == [1, 3]

    def test_priority_travels_through_dispatch(self):
        router, reps = _router(2)
        router.submit(_prompt(7, 7), max_new_tokens=2, priority=2)
        pending = next(r._queue[0] for r in reps if r.queue_depth())
        assert pending.priority == 2

    def test_pressure_weight_configurable_diverts_sooner(self):
        """ISSUE 12 satellite: the 2x pressure heuristic is now the
        ``pressure_weight`` knob — a higher weight diverts away from a
        thrashing replica SOONER (while a lower one still prefers it),
        and 0 ignores pressure entirely."""
        def routed_with(weight):
            router, reps = _router(2, policy="least_loaded",
                                   pressure_weight=weight)
            # rep0: 1 parked preempted request; rep1: 2 queued requests
            reps[0]._preempted.append(object())
            for _ in range(2):
                reps[1].submit(_prompt(9, 9), max_new_tokens=2)
            router.submit(_prompt(1, 2), max_new_tokens=2)
            return router.stats["routed"]

        # weight 5: rep0 scores 5 > rep1's 2 -> divert to rep1 already
        # at pressure 1; weight 1 (and 0): rep0 scores 1 (or 0) < 2 ->
        # the default-2x tie-break order is not yet reached
        assert routed_with(5.0) == [0, 1]
        assert routed_with(1.0) == [1, 0]
        assert routed_with(0.0) == [1, 0]

    def test_pressure_weight_validated(self):
        with pytest.raises(ValueError, match="pressure_weight"):
            _router(2, pressure_weight=-1.0)


class TestDeadReplicaParkedFlush:
    def test_poll_flushes_parked_preempted_on_dead_replica(self):
        """A dead replica whose only remaining work is PARKED preempted
        requests (queue 0, in-flight 0) must still be swept: the poll
        pre-check counts preempt_pressure, and flush_partials hands the
        parked partials to their waiters."""
        router, reps = _router(
            2, rep_kw={"max_slots": 2, "admission": "optimistic",
                       "num_pages": 17})
        rid = router.submit(_prompt(1, 2, 3, 4), max_new_tokens=12)
        route = router._routes[rid]
        rep = reps[route.idx]
        for _ in range(4):
            rep.step()                       # decode a real partial
        with rep._lock:                      # park it (production path)
            slot = next(s for s in range(rep.max_slots)
                        if rep._slots[s] is not None)
            rep._preempt_slot_locked(slot)
        assert rep.in_flight() == 0 and rep.queue_depth() == 0
        assert rep.preempt_pressure() == 1
        rep.kill()
        router.poll()                        # must not skip the corpse
        out = router.wait(rid, timeout=5)
        np.testing.assert_array_equal(
            out, stub_tokens(_prompt(1, 2, 3, 4), 12)[:len(out)])
        assert len(out) > 0
        _balanced(rep)
