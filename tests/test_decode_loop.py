"""On-device scan decode (inference/decode_loop.py) vs the per-step host
loop it replaces — the loop bodies are the same fused_multi_transformer
time_step program, so results must match exactly up to float tolerance.

Reference analogue: the serving loop around
paddle/fluid/operators/fused/fused_multi_transformer_op.cu (one launch per
token); here the whole loop is one XLA program (lax.scan carry = caches).
"""
import numpy as np
import pytest

import paddle_tpu as pt


def _tiny_stack(rng, D=16, L=2, H=4):
    mk = lambda *s: pt.to_tensor(
        rng.standard_normal(s).astype("float32") * 0.05)
    return dict(
        ln_scales=[mk(D) + 1.0 for _ in range(L)],
        ln_biases=[mk(D) for _ in range(L)],
        qkv_weights=[mk(D, 3 * D) for _ in range(L)],
        qkv_biases=[mk(3 * D) for _ in range(L)],
        linear_weights=[mk(D, D) for _ in range(L)],
        linear_biases=[mk(D) for _ in range(L)],
        ffn_ln_scales=[mk(D) + 1.0 for _ in range(L)],
        ffn_ln_biases=[mk(D) for _ in range(L)],
        ffn1_weights=[mk(D, 4 * D) for _ in range(L)],
        ffn1_biases=[mk(4 * D) for _ in range(L)],
        ffn2_weights=[mk(4 * D, D) for _ in range(L)],
        ffn2_biases=[mk(D) for _ in range(L)],
        trans_qkvw=False, num_heads=H)


class TestScanDecode:
    def test_matches_per_step_loop(self):
        import paddle_tpu.incubate.nn.functional as IF
        from paddle_tpu.inference import scan_decode
        rng = np.random.default_rng(0)
        D, L, H, T_MAX, T_PRE, STEPS = 16, 2, 4, 12, 4, 5
        args = _tiny_stack(rng, D, L, H)

        def step_fn(x, caches, t):
            return IF.fused_multi_transformer(
                x, cache_kvs=caches, time_step=t, **args)

        x_pre = pt.to_tensor(
            rng.standard_normal((2, T_PRE, D)).astype("float32"))
        fixed = [pt.to_tensor(np.zeros((2, 2, H, T_MAX, D // H),
                                       "float32")) for _ in range(L)]
        out, caches = IF.fused_multi_transformer(
            x_pre, cache_kvs=fixed, time_step=0, **args)
        x0 = out.numpy()[:, -1:]

        # per-step host loop (the serving pattern scan_decode replaces)
        import jax
        ref_caches = jax.tree_util.tree_map(lambda c: c, caches)
        x_ref = x0
        for i in range(STEPS):
            o, ref_caches = step_fn(pt.to_tensor(x_ref), ref_caches,
                                    T_PRE + i)
            x_ref = o.numpy()

        got, got_caches = scan_decode(step_fn, pt.to_tensor(x0), caches,
                                      T_PRE, STEPS, donate=False)
        np.testing.assert_allclose(np.asarray(got), x_ref,
                                   rtol=1e-4, atol=1e-5)
        for gc, rc in zip(got_caches, ref_caches):
            np.testing.assert_allclose(np.asarray(gc), np.asarray(
                pt.core.tensor.unwrap(rc)), rtol=1e-4, atol=1e-5)

    def test_greedy_generate_matches_python_loop(self):
        import jax.numpy as jnp

        import paddle_tpu.incubate.nn.functional as IF
        from paddle_tpu.inference import greedy_generate
        rng = np.random.default_rng(1)
        D, L, H, V, T_MAX, NEW = 16, 1, 4, 11, 10, 4
        args = _tiny_stack(rng, D, L, H)
        table = jnp.asarray(rng.standard_normal((V, D)).astype("float32"))
        w_head = jnp.asarray(
            rng.standard_normal((D, V)).astype("float32"))

        def embed_fn(tok, t):
            return table[tok][:, None, :]          # [B, 1, D]

        def step_fn(x, caches, t):
            return IF.fused_multi_transformer(
                x, cache_kvs=caches, time_step=t, **args)

        def head_fn(out):
            return pt.core.tensor.unwrap(out) @ w_head

        B = 2
        caches = [pt.to_tensor(np.zeros((2, B, H, T_MAX, D // H),
                                        "float32")) for _ in range(L)]
        first = np.array([3, 7], np.int32)

        # python reference loop
        import jax
        ref_caches = caches
        tok = first
        ref_ids = []
        for i in range(NEW):
            ref_ids.append(tok.copy())
            x = np.asarray(table)[tok][:, None, :]
            o, ref_caches = step_fn(pt.to_tensor(x), ref_caches, i)
            logits = np.asarray(pt.core.tensor.unwrap(o))[:, -1] @ \
                np.asarray(w_head)
            tok = logits.argmax(-1).astype(np.int32)

        ids, _ = greedy_generate(embed_fn, step_fn, head_fn, caches,
                                 pt.to_tensor(first), 0, NEW)
        np.testing.assert_array_equal(np.asarray(ids),
                                      np.stack(ref_ids, 1))

    def test_jit_cache_hits_for_functions_and_bound_methods(self):
        """code-review r5: repeated calls must NOT retrace — the compiled
        program is cached even when step_fn is a bound method (plain
        attribute writes on bound methods silently fail)."""
        import jax.numpy as jnp

        from paddle_tpu.inference import decode_loop

        calls = []

        class Stepper:
            def step(self, x, caches, t):
                calls.append(1)
                return x + caches["c"], caches

        s = Stepper()
        x = jnp.ones((1, 1, 4))
        caches = {"c": jnp.ones((1, 1, 4))}
        decode_loop.scan_decode(s.step, x, caches, 0, 3, donate=False)
        n_traces = len(calls)
        decode_loop.scan_decode(s.step, x, caches, 0, 3, donate=False)
        assert len(calls) == n_traces, "second call retraced (cache miss)"

        calls.clear()

        def fstep(x, caches, t):
            calls.append(1)
            return x * 2.0, caches

        decode_loop.scan_decode(fstep, x, caches, 0, 3, donate=False)
        n_traces = len(calls)
        decode_loop.scan_decode(fstep, x, caches, 0, 3, donate=False)
        assert len(calls) == n_traces

    def test_greedy_generate_eos_padding(self):
        """Once a row emits eos, every later position is eos."""
        import jax.numpy as jnp

        from paddle_tpu.inference import greedy_generate
        V, D, NEW, EOS = 5, 8, 6, 2
        table = jnp.zeros((V, D))

        def embed_fn(tok, t):
            return table[tok][:, None, :]

        def step_fn(x, caches, t):
            return x, caches

        def head_fn(out):
            # always emit EOS
            return jnp.zeros((out.shape[0], V)).at[:, EOS].set(1.0)

        ids, _ = greedy_generate(embed_fn, step_fn, head_fn,
                                 {"c": jnp.zeros((1,))},
                                 jnp.asarray([0], jnp.int32), 0, NEW,
                                 eos_token_id=EOS)
        got = np.asarray(ids)[0]
        assert got[0] == 0 and (got[1:] == EOS).all()
