"""OpTest — numpy-oracle operator test harness.

Mirrors the reference's single most load-bearing fixture
(python/paddle/fluid/tests/unittests/eager_op_test.py:313 OpTest):
each case declares an op, inputs, and a numpy reference; `check_output`
runs the op through BOTH execution modes — eager (tape-recording
dispatch) and the jitted functional path (`jax.jit` over raw arrays,
the static-graph analogue) — and compares each against the oracle.
`check_grad` compares tape-analytic gradients against central finite
differences, like the reference's check_grad (:1937).
"""
import numpy as np

import jax
import paddle_tpu as pt
from paddle_tpu.core.tensor import unwrap


class OpTest:
    """Subclass and define setup() assigning:
      self.op       — callable taking Tensors (e.g. pt.add)
      self.inputs   — dict name → np.ndarray (positional order preserved)
      self.attrs    — dict of keyword attrs (default {})
      self.outputs  — np.ndarray or tuple of arrays: the numpy oracle
    """

    atol = 1e-5
    rtol = 1e-5
    grad_eps = 1e-3
    grad_atol = 5e-3
    grad_rtol = 5e-3

    def setup(self):
        raise NotImplementedError

    # ------------------------------------------------------------ helpers
    def _prep(self):
        self.attrs = {}
        self.setup()

    def _run_eager(self):
        tensors = [pt.to_tensor(v) for v in self.inputs.values()]
        out = self.op(*tensors, **self.attrs)
        return out

    def _run_jit(self):
        """Static-mode analogue: trace the op over raw jax arrays."""
        vals = [unwrap(pt.to_tensor(v)) for v in self.inputs.values()]

        def fn(*args):
            outs = self.op(*[pt.to_tensor(a) for a in args], **self.attrs)
            return jax.tree_util.tree_map(
                unwrap, outs, is_leaf=lambda x: isinstance(x, pt.Tensor))

        return jax.jit(fn)(*vals)

    @staticmethod
    def _flat(out):
        if isinstance(out, (tuple, list)):
            return [np.asarray(o.numpy() if hasattr(o, "numpy") else o)
                    for o in out]
        return [np.asarray(out.numpy() if hasattr(out, "numpy") else out)]

    # ------------------------------------------------------------ checks
    def check_output(self, atol=None, rtol=None):
        self._prep()
        refs = self.outputs if isinstance(self.outputs, (tuple, list)) \
            else (self.outputs,)
        atol = self.atol if atol is None else atol
        rtol = self.rtol if rtol is None else rtol
        got_eager = self._flat(self._run_eager())
        got_jit = self._flat(self._run_jit())
        assert len(got_eager) >= len(refs), (
            f"{self.op}: produced {len(got_eager)} outputs, oracle has "
            f"{len(refs)}")
        for i, ref in enumerate(refs):
            np.testing.assert_allclose(
                got_eager[i], ref, atol=atol, rtol=rtol,
                err_msg=f"eager output {i} mismatch for {self.op}")
            np.testing.assert_allclose(
                got_jit[i], ref, atol=atol, rtol=rtol,
                err_msg=f"jit output {i} mismatch for {self.op}")

    def check_grad(self, inputs_to_check=None, output_index=0, eps=None,
                   atol=None, rtol=None):
        """Analytic (tape) vs central finite-difference gradients of
        sum(op(x) * W) for fixed random W (reference check_grad pattern)."""
        self._prep()
        eps = eps or self.grad_eps
        atol = self.grad_atol if atol is None else atol
        rtol = self.grad_rtol if rtol is None else rtol
        names = list(self.inputs.keys())
        inputs_to_check = inputs_to_check or [
            n for n in names
            if np.issubdtype(np.asarray(self.inputs[n]).dtype, np.floating)]

        def scalar_from(arrs, weight):
            tensors = [pt.to_tensor(a) for a in arrs]
            for t, n in zip(tensors, names):
                if n in inputs_to_check:
                    t.stop_gradient = False
            out = self.op(*tensors, **self.attrs)
            if isinstance(out, (tuple, list)):
                out = out[output_index]
            s = (out * pt.to_tensor(weight.astype(np.float64)
                                    .astype(str(out.dtype)))).sum()
            return s, tensors

        # analytic
        arrs = [np.asarray(v, dtype=np.float64).astype(np.float32)
                if np.issubdtype(np.asarray(v).dtype, np.floating)
                else np.asarray(v) for v in self.inputs.values()]
        probe = self.op(*[pt.to_tensor(a) for a in arrs], **self.attrs)
        if isinstance(probe, (tuple, list)):
            probe = probe[output_index]
        rng = np.random.RandomState(0)
        weight = rng.uniform(0.5, 1.5, size=probe.shape).astype(np.float32)

        s, tensors = scalar_from(arrs, weight)
        s.backward()
        analytic = {}
        for t, n in zip(tensors, names):
            if n in inputs_to_check:
                assert t.grad is not None, f"no grad for input {n}"
                analytic[n] = np.asarray(t.grad.numpy(), dtype=np.float64)

        # numeric central difference
        for idx, n in enumerate(names):
            if n not in inputs_to_check:
                continue
            # ascontiguousarray: an F-ordered input (e.g. built from a
            # transpose) would make reshape(-1) below return copies, not
            # views, silently dropping the accumulated numeric grads
            base = np.ascontiguousarray(arrs[idx], dtype=np.float64)
            num = np.zeros_like(base)
            flat = base.reshape(-1)
            gnum = num.reshape(-1)
            for j in range(flat.size):
                for sgn in (+1, -1):
                    pert = flat.copy()
                    pert[j] += sgn * eps
                    trial = list(arrs)
                    trial[idx] = pert.reshape(base.shape).astype(np.float32)
                    val, _ = scalar_from(trial, weight)
                    gnum[j] += sgn * float(val.numpy())
                gnum[j] /= (2 * eps)
            np.testing.assert_allclose(
                analytic[n], num, atol=atol, rtol=rtol,
                err_msg=f"grad mismatch for input {n} of {self.op}")
