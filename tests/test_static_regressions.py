"""Regression tests for static-graph/executor/export/sparse findings
(code-review round: persist-var KeyError, grad-wrt-intermediate, minimize
outside program_guard, dynamic-batch export, name_scope uniqueness, sparse
BatchNorm running stats, int segment_max empty segments)."""
import os.path as osp
import tempfile

import numpy as np

import paddle_tpu as pt
from paddle_tpu import static as st
from paddle_tpu.ops.registry import OPS


def test_unused_persistable_var_does_not_crash():
    main, sp = st.Program(), st.Program()
    with st.program_guard(main, sp):
        x = st.data("x", [2, 3])
        w_used = st.create_parameter([3, 2], name="w_used_reg")
        st.create_parameter([2, 2], name="w_unused_reg")
        y = OPS["matmul"](x, w_used)
    exe = st.Executor()
    exe.run(sp)
    out = exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                  fetch_list=[y])
    assert out[0].shape == (2, 2)


def test_gradients_wrt_intermediate():
    prog, sprog = st.Program(), st.Program()
    with st.program_guard(prog, sprog):
        x = st.data("x2", [4])
        y = OPS["square"](x)
        z = OPS["sum"](y)
        (gy,) = st.gradients(z, y)
    exe = st.Executor()
    exe.run(sprog)
    out = exe.run(prog, feed={"x2": np.arange(4, dtype=np.float32)},
                  fetch_list=[gy])
    np.testing.assert_allclose(out[0], np.ones(4))


def test_minimize_outside_program_guard():
    prog, sprog = st.Program(), st.Program()
    with st.program_guard(prog, sprog):
        x = st.data("x3", [2, 3])
        w = st.create_parameter([3, 1], name="w3_min_reg")
        pred = OPS["matmul"](x, w)
        loss = OPS["mean"](pred)
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)  # after the guard exits
    assert prog._train_spec is not None
    exe = st.Executor()
    exe.run(sprog)
    before = np.asarray(st.global_scope()._vars["w3_min_reg"]).copy()
    exe.run(prog, feed={"x3": np.ones((2, 3), np.float32)},
            fetch_list=[loss])
    after = np.asarray(st.global_scope()._vars["w3_min_reg"])
    assert not np.allclose(before, after)


def test_dynamic_batch_export():
    prog, sprog = st.Program(), st.Program()
    with st.program_guard(prog, sprog):
        x = st.data("x4", [-1, 4])
        w = st.create_parameter([4, 2], name="w4_exp_reg")
        y = OPS["matmul"](x, w)
    exe = st.Executor()
    exe.run(sprog)
    d = tempfile.mkdtemp()
    st.save_inference_model(osp.join(d, "m"), [x], [y], exe, program=prog)
    from paddle_tpu import inference as infer
    cfg = infer.Config(osp.join(d, "m") + ".pdmodel",
                       osp.join(d, "m") + ".pdmeta")
    pred = infer.create_predictor(cfg)
    ih = pred.get_input_handle(pred.get_input_names()[0])
    ih.copy_from_cpu(np.ones((8, 4), np.float32))
    pred.run()
    oh = pred.get_output_handle(pred.get_output_names()[0])
    assert oh.copy_to_cpu().shape == (8, 2)


def test_name_scope_no_collision():
    pa, sa = st.Program(), st.Program()
    with st.program_guard(pa, sa):
        with st.name_scope("blk"):
            st.nn.fc(st.data("xa", [1, 2]), 2)
    pb, sb = st.Program(), st.Program()
    with st.program_guard(pb, sb):
        with st.name_scope("blk"):
            st.nn.fc(st.data("xb", [1, 2]), 2)
    assert not (set(pa._param_names) & set(pb._param_names))


def test_sparse_batchnorm_running_stats():
    from paddle_tpu import sparse
    x = np.random.RandomState(0).randn(1, 2, 2, 2, 3).astype(np.float32) \
        * 2 + 10
    s = sparse.to_sparse_coo(pt.to_tensor(x), 4)
    bn = sparse.nn.BatchNorm(3, momentum=0.0)  # running <- batch directly
    bn.train()
    bn(s)
    rm = np.asarray(bn._mean_buf.numpy())
    assert abs(rm.mean() - 10) < 2


def test_int_segment_max_empty_segment():
    from paddle_tpu import geometric as G
    out = G.segment_max(pt.to_tensor(np.array([5, 7, 9], np.int32)),
                        pt.to_tensor(np.array([0, 0, 2])))
    np.testing.assert_array_equal(out.numpy(), [7, 0, 9])


def test_enable_static_global_mode():
    """paddle.enable_static(): build + run a program with no program_guard
    (reference workflow: enable_static -> static.data -> Executor.run)."""
    import paddle_tpu as pt
    import paddle_tpu.static as static

    main = static.Program()
    pt.enable_static()
    try:
        assert not pt.in_dynamic_mode()
        with static.program_guard(main):
            x = static.data("x", [2, 3])
            y = (x * 2.0 + 1.0)
        exe = static.Executor()
        xin = np.arange(6, dtype=np.float32).reshape(2, 3)
        (out,) = exe.run(main, feed={"x": xin}, fetch_list=[y])
        np.testing.assert_allclose(out, xin * 2 + 1, rtol=1e-6)
    finally:
        pt.disable_static()
    assert pt.in_dynamic_mode()
    # eager path restored
    t = pt.to_tensor([1.0]) * 3
    np.testing.assert_allclose(t.numpy(), [3.0])
