"""Test config: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the reference's single-host multi-process test pattern
(test_parallel_dygraph_dataparallel.py start_local_trainers) with JAX's
host-device-count trick — 8 virtual CPU devices simulate the TPU slice.
"""
import os

# Hard override: the environment's sitecustomize forces JAX_PLATFORMS=axon
# (the real TPU); distributed tests need the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax and registered the axon TPU plugin
# already; the config update (not just the env var) forces CPU regardless.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# JAX 0.4.x compat: tests call jax.shard_map(..., check_vma=...) — the
# public name (and kwarg spelling) only exists from 0.5; route through
# the repo shim so one suite runs on both.
if not hasattr(jax, "shard_map"):
    from paddle_tpu._compat import shard_map as _compat_shard_map
    jax.shard_map = _compat_shard_map

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    yield
