"""Request-journey tracing, flight recorder, postmortem capture
(ISSUE 10).

Contracts under test:

- ``FlightRecorder``: bounded ring (oldest overwritten), kind filter,
  bounded postmortem store; DISABLED recorder performs zero clock
  reads and zero lock acquisitions (FakeClock + counting-lock
  asserted), and a server treats it exactly like None.
- per-tick dispatch profile: every non-empty tick publishes its
  host->device dispatch map to the recorder (``tick`` events), the
  ``serving_tick_dispatches`` histogram and
  ``server_dispatches_total{op}`` — the ROADMAP item-4 baseline.
- journeys: a request routed -> killed-replica failover -> requeued ->
  admitted -> preempted -> replayed -> finished yields ONE complete
  ``journey(rid)`` timeline across replicas and ONE connected flow in
  the merged fleet Perfetto export (acceptance scenario).
- postmortems: breaker open freezes the parked queue + pool balance +
  block-table occupancy; request failures and replica death capture
  bundles too; ``/debug/journey/<rid>`` + ``/debug/postmortem`` serve
  them.
- chaos determinism: same-seed fault storms produce identical recorder
  event sequences (timestamps aside); ``fault_fires_total{point}``
  makes storms visible on /metrics.
- PR-2 span timelines gain ``request.parked`` / ``request.replay``.

Everything runs on the StubModel double — tier-1 fast, no transformer
compiles."""
import importlib.util
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.router import ReplicaRouter
from paddle_tpu.inference.serving import serve_metrics
from paddle_tpu.reliability import (CircuitBreaker, CircuitOpenError,
                                    FaultInjector, RetryPolicy, faults)
from paddle_tpu.telemetry import (FakeClock, FlightRecorder, Journey,
                                  JourneyRecorder, MetricRegistry,
                                  ServerTelemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prompt(*toks):
    return np.asarray(toks, np.int32)


def _server(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 8)
    return ContinuousBatchingServer(StubModel(), **kw)


def _drive(srv, max_ticks=20_000, stop=None):
    """Single-threaded tolerant drive (chaos-suite pattern): step until
    idle, swallowing injected tick faults like the supervised loop
    would. ``stop`` (predicate) ends the drive early."""
    ticks = 0
    while True:
        with srv._lock:
            busy = srv._busy_locked()
        if not busy or (stop is not None and stop()):
            return
        try:
            srv.step()
        except Exception:
            pass
        ticks += 1
        assert ticks < max_ticks, "drive did not converge"


class _CountingLock:
    """Context-manager shim standing in for a threading.Lock so tests
    can assert the disabled path never acquires it."""

    def __init__(self):
        self.acquisitions = 0

    def __enter__(self):
        self.acquisitions += 1
        return self

    def __exit__(self, *exc):
        return False


# --------------------------------------------------------------------------
# FlightRecorder unit contracts
# --------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bound_and_order(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("ev", i=i)
        evs = rec.events()
        assert len(rec) == 4 and rec.total == 10
        assert [e["i"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == [6, 7, 8, 9]

    def test_kind_filter_and_last(self):
        rec = FlightRecorder()
        for i in range(6):
            rec.record("a" if i % 2 else "b", i=i)
        assert [e["i"] for e in rec.events(kind="a")] == [1, 3, 5]
        assert [e["i"] for e in rec.events(kind="a", last=2)] == [3, 5]
        # unfiltered `last` copies only the window (postmortem capture
        # must pay O(keep_events), not O(capacity))
        assert [e["i"] for e in rec.events(last=2)] == [4, 5]

    def test_reserved_field_keys_degrade_not_crash(self):
        rec = FlightRecorder()
        rec.record("ev", kind="sneaky", t=99, seq=-1, ok=1)
        (e,) = rec.events()
        assert e["kind"] == "ev" and e["seq"] == 0 and e["ok"] == 1
        assert e["kind_"] == "sneaky" and e["t_"] == 99

    def test_postmortem_bundles_bounded_and_snapshot(self):
        rec = FlightRecorder(keep_events=3, max_postmortems=2)
        for i in range(5):
            rec.record("ev", i=i)
        b1 = rec.postmortem("first", pool={"free": 1})
        assert [e["i"] for e in b1["events"]] == [2, 3, 4]
        assert b1["pool"] == {"free": 1}
        rec.postmortem("second")
        rec.postmortem("third")
        reasons = [b["reason"] for b in rec.postmortems()]
        assert reasons == ["second", "third"]   # bounded, newest win

    def test_disabled_recorder_zero_clock_zero_locks(self):
        fc = FakeClock()
        rec = FlightRecorder(clock=fc, enabled=False)
        lock = _CountingLock()
        rec._lock = lock
        assert rec.record("ev", x=1) is None
        assert rec.postmortem("why") is None
        assert fc.reads == 0 and lock.acquisitions == 0
        assert rec.events() == [] or True   # events() may lock; state empty

    def test_server_treats_disabled_recorder_as_none(self):
        fc = FakeClock()
        rec = FlightRecorder(clock=fc, enabled=False)
        srv = _server(recorder=rec)
        assert srv._rec is None
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens([1, 2, 3], 4))
        assert fc.reads == 0 and rec.events() == []
        assert srv.postmortems() == []


# --------------------------------------------------------------------------
# JourneyRecorder unit contracts
# --------------------------------------------------------------------------
class TestJourneyRecorder:
    def test_timeline_and_handles(self):
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc)
        h = jr.begin("t1")
        h.event("submitted", rid=7)
        fc.advance(1.5)
        h.at("replica0").event("queued")
        tl = jr.journey("t1")
        assert [(e["phase"], e["where"]) for e in tl] == \
            [("submitted", "router"), ("queued", "replica0")]
        assert tl[1]["t"] - tl[0]["t"] == pytest.approx(1.5)
        assert jr.journey("nope") is None

    def test_reserved_field_keys_degrade_not_crash(self):
        """A field named like a reserved key ('where' collides with
        the handle's positional hop label) must degrade to a suffixed
        field — regression: deadline expiry once emitted
        event('expired', where=...) and TypeError'd the serve tick."""
        jr = JourneyRecorder()
        h = jr.begin("t1")
        h.event("expired", where="queued", phase="x", t=1)
        (e,) = jr.journey("t1")
        assert e["phase"] == "expired" and e["where"] == "router"
        assert e["where_"] == "queued" and e["phase_"] == "x"

    def test_deadline_expiry_with_journey_attached(self):
        """End-to-end regression for the same bug: a journeyed request
        expiring in queue AND one expiring mid-decode/parked must not
        kill the tick."""
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc)
        srv = _server(clock=fc)
        h = jr.begin("rq")
        rid = srv.submit(_prompt(1, 2), max_new_tokens=4,
                         deadline_s=1.0, journey=h)
        fc.advance(2.0)
        srv.step()                       # expires in queue — must not raise
        assert rid in srv.failures
        phases = [(e["phase"], e.get("at")) for e in jr.journey("rq")]
        assert ("expired", "queued") in phases

    def test_eviction_drops_oldest_whole(self):
        jr = JourneyRecorder(max_journeys=2)
        for i in range(3):
            jr.begin(f"t{i}").event("submitted")
        assert jr.journey("t0") is None and jr.dropped == 1
        assert jr.journey("t2") is not None
        # events for an evicted tid are dropped silently
        Journey(jr, "t0", "router").event("late")
        assert jr.journey("t0") is None

    def test_disabled_zero_clock_zero_locks(self):
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc, enabled=False)
        lock = _CountingLock()
        jr._lock = lock
        h = jr.begin("t1")
        h.event("submitted")
        assert fc.reads == 0 and lock.acquisitions == 0

    def test_router_treats_disabled_journeys_as_none(self):
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc, enabled=False)
        reps = [_server() for _ in range(2)]
        router = ReplicaRouter(reps, policy="least_loaded", journeys=jr)
        rid = router.submit(_prompt(4, 5), max_new_tokens=3)
        for _ in range(50):
            router.poll()
            busy = False
            for rep in reps:
                if rep.queue_depth() or rep.in_flight():
                    rep.step()
                    busy = True
            if not busy:
                break
        np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                      stub_tokens([4, 5], 3))
        assert fc.reads == 0 and len(jr) == 0
        assert router.journey(rid) is None


# --------------------------------------------------------------------------
# Per-tick dispatch profile (ROADMAP item-4 baseline)
# --------------------------------------------------------------------------
class TestTickDispatchProfile:
    def test_recorder_tick_events_carry_per_op_profile(self):
        rec = FlightRecorder()
        srv = _server(recorder=rec)
        r0 = srv.submit(_prompt(1, 2, 3), max_new_tokens=5)
        r1 = srv.submit(_prompt(3, 1), max_new_tokens=5)
        out = srv.run()
        np.testing.assert_array_equal(out[r0], stub_tokens([1, 2, 3], 5))
        np.testing.assert_array_equal(out[r1], stub_tokens([3, 1], 5))
        ticks = rec.events(kind="tick")
        assert ticks, "no tick profiles recorded"
        first = ticks[0]["dispatches"]
        # admission tick: ragged prefill launch + slot-state pushes +
        # block-table sync + the decode program itself
        assert first["prefill"] >= 1 and first["decode"] == 1
        assert first["state_push"] >= 1 and first["block_table"] >= 1
        assert ticks[0]["total"] == sum(first.values())
        # steady-state decode ticks: decode only — the megakernel
        # baseline this PR exists to record
        assert any(e["dispatches"] == {"decode": 1} for e in ticks)
        assert srv.stats["tick_dispatches"] == \
            sum(e["total"] for e in ticks)

    def test_dispatch_metrics_published(self):
        tele = ServerTelemetry()
        srv = _server(telemetry=tele)
        srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        srv.run()
        h = tele.registry.get("serving_tick_dispatches")
        assert h is not None and h.count >= 1
        c = tele.registry.get("server_dispatches_total")
        assert c.labels(op="decode").value >= 1
        assert c.labels(op="prefill").value >= 1
        assert srv.stats["tick_dispatches"] == h.sum


# --------------------------------------------------------------------------
# Server-side recorder events + postmortems
# --------------------------------------------------------------------------
def _pressure_server(rec=None, tele=None, breaker=None, fi=None):
    """Optimistic server sized so the high-priority grower preempts the
    low-priority victim: usable pool 5 pages, two slots."""
    return _server(max_slots=2, num_pages=6, admission="optimistic",
                   recorder=rec, telemetry=tele, breaker=breaker,
                   fault_injector=fi,
                   retry_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0))


V_PROMPT = [5, 6, 7, 8, 9, 10, 11, 12]    # one FULL page: its preempt
#                                           teardown donates a node


def _park_victim(srv):
    """Submit a high-priority grower + low-priority victim and step
    until the victim is parked (still parked: pool exhausted)."""
    f = srv.submit(_prompt(1, 2, 3, 4), max_new_tokens=28, priority=1)
    v = srv.submit(_prompt(*V_PROMPT), max_new_tokens=28, priority=0)
    _drive(srv, stop=lambda: srv.preempt_pressure() > 0)
    assert srv.preempt_pressure() > 0, "victim never parked"
    return f, v


class TestServerRecorder:
    def test_lifecycle_event_sequence(self):
        rec = FlightRecorder()
        srv = _server(recorder=rec)
        rid = srv.submit(_prompt(9, 9), max_new_tokens=3)
        srv.run()
        kinds = [e["kind"] for e in rec.events()]
        assert kinds[0] == "admit"
        assert "finish" in kinds and "tick" in kinds
        fin = rec.events(kind="finish")[0]
        assert fin["rid"] == rid and fin["tokens"] == 3

    def test_preempt_grow_replay_events(self):
        rec = FlightRecorder()
        srv = _pressure_server(rec=rec)
        f, v = _park_victim(srv)
        _drive(srv)                     # run to completion
        np.testing.assert_array_equal(
            srv._results[v], stub_tokens(V_PROMPT, 28))
        kinds = [e["kind"] for e in rec.events()]
        assert "grow" in kinds and "preempt" in kinds
        assert "replay" in kinds and "donate" in kinds
        pre = rec.events(kind="preempt")[0]
        assert pre["rid"] == v
        rep = rec.events(kind="replay")
        assert rep and rep[0]["rid"] == v

    def test_breaker_open_postmortem_has_parked_queue_and_pool(self):
        """Acceptance: a chaos-killed request produces a postmortem
        bundle containing the parked queue and the pool balance."""
        rec = FlightRecorder()
        srv = _pressure_server(
            rec=rec, breaker=CircuitBreaker(failure_threshold=1))
        f, v = _park_victim(srv)
        srv._on_tick_failure(RuntimeError("chaos"))   # retries exhausted
        bundles = srv.postmortems()
        assert bundles, "breaker open captured no bundle"
        b = bundles[-1]
        assert b["reason"] == "breaker_open"
        assert any(p["rid"] == v for p in b["parked"])
        assert b["pool_balance"]["preempted"] >= 1
        assert b["pool_balance"]["free"] + b["pool_balance"]["live"] \
            + b["pool_balance"]["pinned"] + b["pool_balance"]["cached"] \
            == srv._kv.num_pages - 1
        assert b["block_table"]["slots"]           # occupancy captured
        assert "cached_pages" in b["prefix_cache"]
        assert any(e["kind"] == "breaker" for e in b["events"])
        # both requests were killed typed — the bundle is their record
        assert isinstance(srv.failures[v], CircuitOpenError)
        assert isinstance(srv.failures[f], CircuitOpenError)

    def test_request_failure_captures_bundle_and_fault_metric(self):
        rec = FlightRecorder()
        tele = ServerTelemetry()
        fi = FaultInjector(seed=0).on(faults.PREFILL, schedule=[0])
        srv = _server(recorder=rec, telemetry=tele, fault_injector=fi)
        rid = srv.submit(_prompt(1, 1, 1), max_new_tokens=4)
        srv.run()
        assert rid in srv.failures
        bundles = srv.postmortems()
        assert bundles and bundles[-1]["reason"] == "request_failed"
        assert bundles[-1]["rid"] == rid
        # satellite: the fire is visible on /metrics AND in the ring
        fires = tele.registry.get("fault_fires_total")
        assert fires.labels(point=faults.PREFILL).value == 1
        assert any(e["kind"] == "fault"
                   and e["point"] == faults.PREFILL
                   for e in rec.events())

    def test_shared_injector_counts_fires_in_every_registry(self):
        """A fleet-shared injector must make a storm visible on EVERY
        attached registry, not just the last-constructed component's
        (regression: publish_to was last-wins)."""
        fi = FaultInjector(seed=0).on(faults.PREFILL, schedule=[0])
        tele0, tele1 = ServerTelemetry(), ServerTelemetry()
        srv0 = _server(telemetry=tele0, fault_injector=fi)
        _server(telemetry=tele1, fault_injector=fi)   # later component
        srv0.submit(_prompt(1,), max_new_tokens=2)
        srv0.run()                    # the fire happens on srv0
        for reg in (tele0.registry, tele1.registry):
            assert reg.get("fault_fires_total") \
                .labels(point=faults.PREFILL).value == 1

    def test_evict_oldest_shed_records_fail_but_no_bundle(self):
        """Shedding under overload is EXPECTED: the recorder gets the
        fail event, but no postmortem bundle is captured on the
        submit() hot path (a storm of sheds must not flood the bounded
        bundle store)."""
        rec = FlightRecorder()
        srv = _server(recorder=rec, max_queue=1,
                      shed_policy="evict_oldest")
        old = srv.submit(_prompt(1,), max_new_tokens=2)
        srv.submit(_prompt(2,), max_new_tokens=2)    # sheds `old`
        assert old in srv.failures
        assert any(e["kind"] == "fail" and e["rid"] == old
                   for e in rec.events())
        assert srv.postmortems() == []

    def test_kill_captures_crash_scene(self):
        rec = FlightRecorder()
        srv = _server(recorder=rec)
        rid = srv.submit(_prompt(2, 2), max_new_tokens=4)
        srv.kill()
        b = srv.postmortems()[-1]
        assert b["reason"] == "killed" and rid in b["queue"]
        assert any(e["kind"] == "killed" for e in rec.events())
        assert any(e["kind"] == "health" and e["state"] == "dead"
                   for e in rec.events())


# --------------------------------------------------------------------------
# parked/replay span phases (PR-2 satellite)
# --------------------------------------------------------------------------
class TestPreemptionSpans:
    def test_parked_and_replay_spans_in_timeline(self):
        tele = ServerTelemetry()
        srv = _pressure_server(tele=tele)
        f, v = _park_victim(srv)
        _drive(srv)
        names = {e["name"] for e in tele.tracer.events()
                 if e.get("args", {}).get("rid") == v}
        assert "request.parked" in names
        assert "request.replay" in names
        # the un-preempted grower keeps the normal phase names
        f_names = {e["name"] for e in tele.tracer.events()
                   if e.get("args", {}).get("rid") == f}
        assert "request.parked" not in f_names
        assert "request.replay" not in f_names


# --------------------------------------------------------------------------
# The journey acceptance scenario + fleet Perfetto export
# --------------------------------------------------------------------------
def _fleet_drive(router, reps, max_iters=3000):
    idle = 0
    for _ in range(max_iters):
        router.poll()
        busy = False
        for rep in reps:
            if rep.health == "dead":
                continue
            if rep.queue_depth() or rep.in_flight() \
                    or rep.preempt_pressure():
                rep.step()
                busy = True
        if busy:
            idle = 0
        else:
            idle += 1
            if idle >= 2:
                return
    raise AssertionError("fleet drive did not converge")


class TestJourneyAcceptance:
    def _scenario(self):
        """One request is routed to replica0, stranded by its death
        while queued, failed over to replica1, admitted there,
        preempted by a higher-priority grower, replayed bit-exactly,
        and finished — the full ISSUE-10 acceptance path."""
        jr = JourneyRecorder()
        reps = [_server(max_slots=2, num_pages=6,
                        admission="optimistic",
                        telemetry=ServerTelemetry())
                for _ in range(2)]
        router = ReplicaRouter(reps, policy="least_loaded", journeys=jr,
                               recorder=FlightRecorder())
        v_prompt = [5, 6, 7, 8]
        # victim first: both replicas idle -> replica0 takes it
        v = router.submit(_prompt(*v_prompt), max_new_tokens=28,
                          priority=0)
        # grower second: replica0 now loaded -> replica1 takes it
        f = router.submit(_prompt(1, 2, 3, 4), max_new_tokens=28,
                          priority=1)
        assert router._routes[v].idx == 0
        assert router._routes[f].idx == 1
        reps[1].step()                  # admit the grower on replica1
        reps[0].kill()                  # V still queued on the corpse
        _fleet_drive(router, reps)
        out = router.wait(v, timeout=10)
        np.testing.assert_array_equal(out, stub_tokens(v_prompt, 28))
        np.testing.assert_array_equal(router.wait(f, timeout=10),
                                      stub_tokens([1, 2, 3, 4], 28))
        return router, reps, v, f

    def test_complete_journey_across_replicas(self):
        router, reps, v, f = self._scenario()
        tl = router.journey(v)
        phases = [e["phase"] for e in tl]
        # every acceptance phase present, in causal order
        expected = ["submitted", "dispatched", "queued", "evacuated",
                    "dispatched", "queued", "admitted", "first_token",
                    "preempted", "replay", "finished", "collected"]
        it = iter(phases)
        missing = [p for p in expected if p not in it]
        assert not missing, \
            f"phases {missing} missing/out of order in {phases}"
        # hops carry their true locations
        assert ("queued", "replica0") in \
            [(e["phase"], e["where"]) for e in tl]
        assert ("evacuated", "router") in \
            [(e["phase"], e["where"]) for e in tl]
        wheres = {e["where"] for e in tl}
        assert {"router", "replica0", "replica1"} <= wheres
        # replica death also captured a fleet postmortem with routing
        bundles = router.postmortems()
        dead = [b for b in bundles if b["reason"] == "replica 0 dead"]
        assert dead and dead[0]["source"] == "router"
        assert dead[0]["replicas"][0]["health"] == "dead"
        assert "routes" in dead[0]["routing"]

    def test_fleet_perfetto_export_one_connected_flow(self, tmp_path):
        router, reps, v, f = self._scenario()
        path = tmp_path / "fleet.json"
        n = router.export_fleet_trace(str(path))
        payload = json.loads(path.read_text())
        evs = payload["traceEvents"]
        assert len(evs) == n
        # per-process naming: router + one pid per replica
        names = {e["pid"]: e["args"]["name"] for e in evs
                 if e.get("ph") == "M"}
        assert names == {0: "router", 1: "replica0", 2: "replica1"}
        # each replica's tracer spans landed on its own pid
        assert any(e.get("ph") == "X" and e["pid"] == 2 for e in evs)
        # the failed-over journey is ONE connected flow: its flow
        # events share an id and span router + both replicas
        flows = [e for e in evs
                 if e.get("cat") == "journey" and e.get("id") == f"r{v}"]
        assert len(flows) >= 3
        assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
        assert {e["pid"] for e in flows} == {0, 1, 2}
        # journey phase instants rendered at the emitting hop's pid
        marks = [e for e in evs if e.get("ph") == "i"
                 and e.get("args", {}).get("journey") == f"r{v}"]
        assert any(m["name"] == "journey.preempted" and m["pid"] == 2
                   for m in marks)

    def test_flow_steps_bind_to_journey_events(self, tmp_path):
        """ISSUE 12 satellite (PR 9 known cut): flow steps bind to the
        JOURNEY EVENTS themselves — one step per event at its exact
        (ts, pid) — not to consecutive-``where`` groups. An A->B->A
        bounce whose return hop emits MORE events at A must render an
        arrow anchored at each event, so the bounce reads as two
        distinct crossings (the old grouping collapsed the extra A
        events into the group's first timestamp)."""
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc)
        router = ReplicaRouter([_server()], journeys=jr)
        h = jr.begin("r0", where="router")
        script = [("submitted", "router"), ("dispatched", "router"),
                  ("queued", "replica0"), ("evacuated", "router"),
                  ("held", "router"), ("dispatched", "router")]
        for phase, where in script:
            fc.advance(1.0)
            jr.event("r0", phase, where)
        path = tmp_path / "bounce.json"
        router.export_fleet_trace(str(path))
        evs = json.loads(path.read_text())["traceEvents"]
        flows = [e for e in evs
                 if e.get("cat") == "journey" and e.get("id") == "r0"]
        # one flow step per journey event, phased s/t.../f
        assert len(flows) == len(script)
        assert [e["ph"] for e in flows] == \
            ["s"] + ["t"] * (len(script) - 2) + ["f"]
        # each step anchored at ITS event's pid and timestamp — the
        # bounce back to the router contributes three distinct anchors,
        # not one collapsed hop at the group's first event
        marks = [e for e in evs if e.get("ph") == "i"
                 and e.get("args", {}).get("journey") == "r0"]
        assert [(f["pid"], f["ts"]) for f in flows] == \
            [(m["pid"], m["ts"]) for m in marks]
        assert [f["pid"] for f in flows] == [0, 0, 1, 0, 0, 0]

    def test_single_location_journey_draws_no_flow(self, tmp_path):
        jr = JourneyRecorder()
        router = ReplicaRouter([_server()], journeys=jr)
        jr.begin("r9", where="router")
        jr.event("r9", "submitted", "router")
        jr.event("r9", "collected", "router")
        path = tmp_path / "flat.json"
        router.export_fleet_trace(str(path))
        evs = json.loads(path.read_text())["traceEvents"]
        assert not [e for e in evs if e.get("cat") == "journey"]


# --------------------------------------------------------------------------
# /debug endpoints
# --------------------------------------------------------------------------
def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, json.loads(r.read().decode())


class TestDebugEndpoints:
    def test_router_journey_and_postmortem_endpoints(self):
        jr = JourneyRecorder()
        reps = [_server(telemetry=ServerTelemetry(),
                        recorder=FlightRecorder())
                for _ in range(2)]
        router = ReplicaRouter(reps, policy="least_loaded", journeys=jr,
                               recorder=FlightRecorder(),
                               telemetry=True)
        rid = router.submit(_prompt(3, 3), max_new_tokens=3)
        for _ in range(50):
            router.poll()
            if not any(rep.queue_depth() or rep.in_flight()
                       for rep in reps):
                break
            for rep in reps:
                if rep.queue_depth() or rep.in_flight():
                    rep.step()
        reps[0].kill()
        router.poll()                    # dead-replica postmortem
        ms = serve_metrics(router)
        try:
            status, body = _get(f"{ms.url}/debug/journey/{rid}")
            assert status == 200 and body["rid"] == str(rid)
            assert body["journey"][0]["phase"] == "submitted"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{ms.url}/debug/journey/424242")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{ms.url}/debug/journey/not-a-rid")
            assert ei.value.code == 404
            status, body = _get(f"{ms.url}/debug/postmortem")
            assert status == 200
            reasons = [b["reason"] for b in body["postmortems"]]
            assert "replica 0 dead" in reasons
        finally:
            ms.close()

    def test_server_postmortem_endpoint_and_no_journey(self):
        srv = _server(telemetry=True, recorder=FlightRecorder())
        rid = srv.submit(_prompt(7,), max_new_tokens=2)
        srv.kill()
        ms = serve_metrics(srv)
        try:
            status, body = _get(f"{ms.url}/debug/postmortem")
            assert status == 200
            assert body["postmortems"][-1]["reason"] == "killed"
            assert rid in body["postmortems"][-1]["queue"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{ms.url}/debug/journey/0")
            assert ei.value.code == 404    # servers mint no journeys
        finally:
            ms.close()


# --------------------------------------------------------------------------
# Chaos: same-seed storms replay identical recorder sequences
# --------------------------------------------------------------------------
@pytest.mark.chaos
class TestChaosDeterminism:
    def _storm(self, seed):
        rec = FlightRecorder()
        fi = (FaultInjector(seed=seed)
              .on(faults.PREFILL, probability=0.25)
              .on(faults.DECODE_TICK, probability=0.15)
              .on(faults.KV_GROW, probability=0.1)
              .on(faults.SERVER_PREEMPT, probability=0.2))
        srv = _pressure_server(rec=rec, fi=fi)
        rng = np.random.default_rng(7)
        rids = []
        for _ in range(6):
            p = rng.integers(0, 16, (int(rng.integers(3, 9)),))
            rids.append(srv.submit(p.astype(np.int32),
                                   max_new_tokens=12,
                                   priority=int(rng.integers(0, 3))))
        _drive(srv)
        results = {r: srv._results.get(r) for r in rids}
        strip = [{k: v for k, v in e.items() if k != "t"}
                 for e in rec.events()]
        return strip, fi.trace, results, srv

    def test_same_seed_identical_event_sequence(self):
        evs1, trace1, res1, srv1 = self._storm(31)
        evs2, trace2, res2, srv2 = self._storm(31)
        assert trace1 == trace2          # injector contract (sanity)
        assert evs1 == evs2              # recorder sequence contract
        for r in res1:
            if res1[r] is None:
                assert res2[r] is None
            else:
                np.testing.assert_array_equal(res1[r], res2[r])
        # the storm fired and was recorded; no pages leaked
        assert any(e["kind"] == "fault" for e in evs1)
        bal = srv1.pool_balance()
        assert bal[1] == 0
        assert bal[0] + bal[2] + bal[3] == srv1._kv.num_pages - 1

    def test_different_seed_differs(self):
        evs1, trace1, _, _ = self._storm(31)
        evs2, trace2, _, _ = self._storm(32)
        assert trace1 != trace2 or evs1 != evs2


# --------------------------------------------------------------------------
# Lints (wired into tier-1 like check_no_bare_except)
# --------------------------------------------------------------------------
def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestMetricDocsLint:
    def test_repo_is_clean(self, capsys):
        mod = _load_script("check_metric_docs")
        assert mod.main(["check_metric_docs.py"]) == 0
        assert "OK" in capsys.readouterr().out

    def test_new_metrics_are_registered_and_seen(self):
        mod = _load_script("check_metric_docs")
        names = mod.registered_metrics(os.path.join(REPO, "paddle_tpu"))
        for required in ("serving_tick_dispatches",
                         "server_dispatches_total",
                         "fault_fires_total",
                         "router_orphaned_total"):
            assert required in names, f"{required} not found by scan"

    def test_detects_drift(self):
        mod = _load_script("check_metric_docs")
        missing = mod.undocumented(
            {"bogus_metric_total": ["x.py"],
             "serving_tick_seconds": ["y.py"]},
            "only serving_tick_seconds is documented here")
        assert missing == [("bogus_metric_total", ["x.py"])]
