"""Pallas rope kernel (SURVEY 2.4 rotary -> Pallas)."""
import numpy as np

import jax.numpy as jnp

from paddle_tpu.ops.pallas import rope as rope_mod


def test_pallas_rope_matches_jnp():
    B, S, H, D = 2, 64, 4, 32
    x = jnp.asarray(np.random.randn(B, S, H, D).astype("float32"))
    cos, sin = rope_mod.precompute_freqs(D, 128)
    ref = rope_mod.apply_rotary(x, cos, sin)
    out = rope_mod.apply_rotary_pallas(x, cos, sin, block_s=32,
                                       interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pallas_rope_ragged_falls_back_correctly():
    """Ragged seq routes to the jnp math and matches the sliced result
    (checks the dispatch condition, not just no-crash)."""
    B, S, H, D = 1, 50, 2, 16
    x = jnp.asarray(np.random.randn(B, S, H, D).astype("float32"))
    cos, sin = rope_mod.precompute_freqs(D, 128)
    out = rope_mod.apply_rotary_pallas(x, cos, sin, block_s=32,
                                       interpret=True)
    ref = rope_mod._apply_rotary_jnp(x, cos, sin)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    assert out.shape == x.shape


def test_pallas_rope_guards_table_overrun():
    """seq > precomputed table must NOT silently clamp (jnp path raises
    loudly on the broadcast)."""
    B, S, H, D = 1, 64, 2, 16
    x = jnp.asarray(np.random.randn(B, S, H, D).astype("float32"))
    cos, sin = rope_mod.precompute_freqs(D, 32)    # table shorter than S
    try:
        rope_mod.apply_rotary_pallas(x, cos, sin, block_s=32,
                                     interpret=True)
        raised = False
    except Exception:
        raised = True
    assert raised
