"""ZeRO-vs-DP loss parity across stages (VERDICT distributed-test-depth
item; reference pattern: dygraph_group_sharded_stage3.py ZeRO-vs-DP
parity asserted over training steps)."""
import numpy as np
import pytest

import jax

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu._compat import host_memory_kind

_HOST_KIND = host_memory_kind()


def _make(seed=0):
    pt.seed(seed)
    net = pt.nn.Sequential(pt.nn.Linear(16, 32), pt.nn.GELU(),
                           pt.nn.Linear(32, 8))
    opt = pt.optimizer.AdamW(learning_rate=0.01,
                             parameters=net.parameters())
    return net, opt


def _loss_fn(out, labels):
    return ((out - labels) ** 2).mean()


def _train(zero_stage, steps=5):
    mesh = dist.init_mesh(dp=2, sharding=2 if zero_stage else 1)
    net, opt = _make(0)
    from paddle_tpu.parallel.api import parallel_train_step
    step_fn, params, opt_state, _ = parallel_train_step(
        net, _loss_fn, opt, mesh, zero_stage=zero_stage)
    rng = np.random.RandomState(0)
    # one FIXED batch: descent on it is deterministic, where per-step
    # fresh random targets make the loss trend platform-luck
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)
    batch = {"inputs": (x,), "labels": (y,)}
    losses = []
    for i in range(steps):
        loss, params, opt_state = step_fn(params, opt_state, batch,
                                          i + 1, None)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_dp(stage):
    base = _train(0)
    zs = _train(stage)
    np.testing.assert_allclose(zs, base, rtol=2e-4, atol=1e-5)
    assert base[-1] < base[0]


def test_zero_offload_parity_and_host_placement():
    """offload=True: optimizer state lives in pinned_host between steps and
    training matches the on-device run bit-for-bit semantics (reference
    group_sharded offload flag)."""
    mesh = dist.init_mesh(dp=2, sharding=2)
    net, opt = _make(0)
    from paddle_tpu.parallel.api import parallel_train_step
    step_fn, params, opt_state, (p_sh, s_sh) = parallel_train_step(
        net, _loss_fn, opt, mesh, zero_stage=2, offload=True)
    leaves = [l for l in jax.tree_util.tree_leaves(opt_state)
              if hasattr(l, "sharding") and l.ndim >= 1]
    assert leaves and all(
        l.sharding.memory_kind == _HOST_KIND for l in leaves)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)
    batch = {"inputs": (x,), "labels": (y,)}
    losses = []
    for i in range(5):
        loss, params, opt_state = step_fn(params, opt_state, batch,
                                          i + 1, None)
        losses.append(float(loss))
    # new state is streamed back to host memory every step
    leaves = [l for l in jax.tree_util.tree_leaves(opt_state)
              if hasattr(l, "sharding") and l.ndim >= 1]
    assert all(l.sharding.memory_kind == _HOST_KIND for l in leaves)
    np.testing.assert_allclose(losses, _train(2), rtol=2e-4, atol=1e-5)


def test_group_sharded_offload_api():
    """group_sharded_parallel(offload=True) plumbs through to the step."""
    mesh = dist.init_mesh(dp=2, sharding=2)
    net, opt = _make(1)
    model, opt2, _ = dist.sharding.group_sharded_parallel(
        net, opt, "os_g", offload=True)
    step_fn, params, opt_state, _ = model.build_train_step(_loss_fn,
                                                           mesh=mesh)
    leaves = [l for l in jax.tree_util.tree_leaves(opt_state)
              if hasattr(l, "sharding") and l.ndim >= 1]
    assert leaves and all(
        l.sharding.memory_kind == _HOST_KIND for l in leaves)
