"""TrainSupervisor + supervised hapi fit: exact resume, anomaly
policy, retries, preemption — plus the PR's satellites (CallbackList
fire-all contract, ElasticManager.close, TrainEpochRange atomic save,
bare-except lint)."""
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import TensorDataset
from paddle_tpu.reliability import (AnomalyPolicy, FaultInjector,
                                    ResumableLoader, RetryPolicy,
                                    CircuitBreaker, StepFailedError,
                                    TrainAnomalyError, TrainSupervisor,
                                    faults)
from paddle_tpu.telemetry import FakeClock, MetricRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------- tiny pure model
def _data(n=10):
    return list(np.arange(n, dtype=np.float64))


def _loader(seed=5, batch_size=3, shuffle=True):
    return ResumableLoader(_data(), batch_size=batch_size, shuffle=shuffle,
                           seed=seed)


def _step(s, b):
    m = float(np.mean(b))
    return s * 0.9 + 0.01 * m, s * 0.95 + 0.01 * m


def _zero_retry(**kw):
    return RetryPolicy(base_delay_s=0.0, jitter=0.0, **kw)


class TestResumableLoader:
    def test_order_is_pure_function_of_seed_and_epoch(self):
        a, b = _loader(), _loader()
        for _ in range(9):                 # crosses an epoch boundary
            np.testing.assert_array_equal(a.next_batch(), b.next_batch())

    def test_cursor_resume_is_exact(self):
        a = _loader()
        seen = [a.next_batch() for _ in range(5)]
        sd = a.state_dict()
        rest_a = [a.next_batch() for _ in range(5)]
        b = _loader()
        b.set_state_dict(sd)
        rest_b = [b.next_batch() for _ in range(5)]
        for x, y in zip(rest_a, rest_b):
            np.testing.assert_array_equal(x, y)
        assert len(seen) == 5

    def test_drop_last_and_epoch_wrap(self):
        dl = ResumableLoader(_data(10), batch_size=4, drop_last=True)
        assert len(dl) == 2
        sizes = [len(dl.next_batch()) for _ in range(5)]
        assert sizes == [4] * 5            # partial tail batch dropped
        assert dl.epoch >= 2

    def test_set_state_dict_adopts_saved_seed(self):
        """Resuming onto a loader rebuilt with a DIFFERENT seed must
        replay the run's original batch stream, not the new seed's."""
        a = _loader(seed=7)
        for _ in range(2):
            a.next_batch()
        sd = a.state_dict()
        b = _loader(seed=0)                  # wrong seed at rebuild
        b.set_state_dict(sd)
        assert b.seed == 7
        for _ in range(4):
            np.testing.assert_array_equal(a.next_batch(), b.next_batch())

    def test_shuffle_epochs_differ(self):
        dl = _loader(batch_size=10)
        e0 = dl.next_batch()
        e1 = dl.next_batch()
        assert not np.array_equal(e0, e1)

    def test_drop_last_smaller_than_batch_refused(self):
        """Regression: this combination used to spin forever in
        next_batch (every epoch dropped its only, short batch)."""
        with pytest.raises(ValueError, match="drop_last"):
            ResumableLoader(_data(3), batch_size=8, drop_last=True)


class TestSupervisorLoop:
    def test_exact_resume_bit_matches_uninterrupted(self, tmp_path):
        full = TrainSupervisor(str(tmp_path / "a"), save_interval_steps=4) \
            .run(_step, 1.0, _loader(), max_steps=11).losses
        d = str(tmp_path / "b")
        r1 = TrainSupervisor(d, save_interval_steps=4).run(
            _step, 1.0, _loader(), max_steps=5)
        r2 = TrainSupervisor(d, save_interval_steps=4).run(
            _step, 1.0, _loader(), max_steps=11)
        assert r2.resumed_from == 5
        assert r1.losses + r2.losses == full

    def test_transient_faults_retried_without_perturbing_losses(
            self, tmp_path):
        full = TrainSupervisor(str(tmp_path / "a"), save_interval_steps=4) \
            .run(_step, 1.0, _loader(), max_steps=11).losses
        fi = (FaultInjector(seed=3)
              .on(faults.TRAIN_STEP, probability=0.3)
              .on(faults.DATA_NEXT, probability=0.2))
        sup = TrainSupervisor(str(tmp_path / "b"), save_interval_steps=4,
                              injector=fi, retry=_zero_retry(),
                              max_step_retries=50)
        rep = sup.run(_step, 1.0, _loader(), max_steps=11)
        assert rep.retries > 0
        assert rep.losses == full           # retries are invisible

    def test_retry_budget_exhaustion_is_typed(self, tmp_path):
        fi = FaultInjector(seed=0).on(faults.TRAIN_STEP, probability=1.0)
        sup = TrainSupervisor(str(tmp_path), injector=fi,
                              retry=_zero_retry(), max_step_retries=3)
        with pytest.raises(StepFailedError):
            sup.run(_step, 1.0, _loader(), max_steps=2)

    def test_open_breaker_gates_next_attempt(self, tmp_path):
        """An already-open breaker (e.g. shared with another loop)
        short-circuits run_with_retries during its cooldown window;
        after the cooldown the half-open probe attempt runs."""
        clk = FakeClock()
        cb = CircuitBreaker(failure_threshold=1, reset_after_s=60,
                            clock=clk)
        cb.record_failure()                     # pre-opened
        sup = TrainSupervisor(str(tmp_path), breaker=cb)
        with pytest.raises(StepFailedError, match="open"):
            sup.run_with_retries(lambda: 1, faults.TRAIN_STEP)
        clk.advance(61)
        assert sup.run_with_retries(lambda: 1, faults.TRAIN_STEP) == 1
        assert cb.state == cb.CLOSED            # probe success closed it

    def test_stop_iteration_returns_the_probe_token(self, tmp_path):
        """ISSUE 8 regression: StopIteration (normal end-of-data) is
        neither success nor failure — the half-open single-probe token
        allow() took must be RELEASED, or the breaker wedges half-open
        and every later step fails 'cooling down' forever."""
        def exhausted():
            raise StopIteration

        clk = FakeClock()
        cb = CircuitBreaker(failure_threshold=1, reset_after_s=60,
                            clock=clk)
        cb.record_failure()                     # pre-opened
        sup = TrainSupervisor(str(tmp_path), breaker=cb)
        clk.advance(61)                         # cooldown elapsed
        with pytest.raises(StopIteration):
            sup.run_with_retries(exhausted, faults.DATA_NEXT)
        assert cb.state == cb.HALF_OPEN
        # the probe must be available again, and succeed
        assert sup.run_with_retries(lambda: 1, faults.TRAIN_STEP) == 1
        assert cb.state == cb.CLOSED

    def test_breaker_open_aborts_typed(self, tmp_path):
        fi = FaultInjector(seed=0).on(faults.TRAIN_STEP, probability=1.0)
        sup = TrainSupervisor(
            str(tmp_path), injector=fi, retry=_zero_retry(),
            max_step_retries=100,
            breaker=CircuitBreaker(failure_threshold=4, clock=FakeClock()))
        with pytest.raises(StepFailedError, match="breaker"):
            sup.run(_step, 1.0, _loader(), max_steps=2)

    def test_anomaly_skip_then_rollback_then_recover(self, tmp_path):
        calls = {"n": 0}

        def poison(s, b):
            calls["n"] += 1
            if 6 <= calls["n"] <= 8:       # one burst of 3 NaN steps
                return float("nan"), s
            return _step(s, b)

        reg = MetricRegistry()
        sup = TrainSupervisor(
            str(tmp_path), save_interval_steps=2, registry=reg,
            anomaly=AnomalyPolicy(max_consecutive=3, max_rollbacks=1))
        rep = sup.run(poison, 1.0, _loader(), max_steps=8)
        assert rep.status == "completed"
        assert rep.anomalies == 3 and rep.rollbacks == 1
        c = reg.counter("train_anomaly_total", "", labelnames=("kind",))
        assert c.labels(kind="nonfinite_loss").value == 3
        assert reg.counter("train_rollback_total", "").value == 1

    def test_persistent_anomaly_aborts_typed(self, tmp_path):
        sup = TrainSupervisor(
            str(tmp_path), save_interval_steps=1,
            anomaly=AnomalyPolicy(max_consecutive=2, max_rollbacks=1))
        with pytest.raises(TrainAnomalyError) as ei:
            sup.run(lambda s, b: (float("nan"), s), 1.0, _loader(),
                    max_steps=4)
        assert ei.value.kind == "nonfinite_loss"

    def test_anomaly_before_any_checkpoint_aborts(self, tmp_path):
        sup = TrainSupervisor(
            str(tmp_path), save_interval_steps=100,
            anomaly=AnomalyPolicy(max_consecutive=1, max_rollbacks=5))
        with pytest.raises(TrainAnomalyError, match="nothing to roll"):
            sup.run(lambda s, b: (float("inf"), s), 1.0, _loader(),
                    max_steps=4)

    def test_request_preemption_checkpoints_and_exits_clean(self,
                                                            tmp_path):
        d = str(tmp_path)
        sup = TrainSupervisor(d, save_interval_steps=100)
        n = {"v": 0}

        def step(s, b):
            n["v"] += 1
            if n["v"] == 3:
                sup.request_preemption()
            return _step(s, b)

        rep = sup.run(step, 1.0, _loader(), max_steps=11)
        assert rep.status == "preempted" and rep.steps_done == 3
        assert sup.preempts_total == 1
        full = TrainSupervisor(str(tmp_path / "x"),
                               save_interval_steps=100).run(
            _step, 1.0, _loader(), max_steps=11).losses
        rep2 = TrainSupervisor(d, save_interval_steps=100).run(
            _step, 1.0, _loader(), max_steps=11)
        assert rep2.resumed_from == 3
        assert rep.losses + rep2.losses == full

    def test_sigterm_routes_to_preemption(self, tmp_path):
        if threading.current_thread() is not threading.main_thread():
            pytest.skip("signal handlers need the main thread")
        sup = TrainSupervisor(str(tmp_path), save_interval_steps=100)
        sup.install_signal_handlers()
        try:
            n = {"v": 0}

            def step(s, b):
                n["v"] += 1
                if n["v"] == 2:
                    os.kill(os.getpid(), signal.SIGTERM)
                return _step(s, b)

            rep = sup.run(step, 1.0, _loader(), max_steps=50)
        finally:
            sup.uninstall_signal_handlers()
        assert rep.status == "preempted"
        assert rep.steps_done < 50
        # the clean exit left a durable, valid checkpoint
        assert sup.store.latest_valid_step() == rep.steps_done

    def test_same_supervisor_reinvoked_after_preempt_resumes(self,
                                                             tmp_path):
        """Regression: the preempt flag used to stay sticky, so an
        IN-PROCESS re-invocation of the same supervisor instantly
        re-preempted at step 0 forever."""
        d = str(tmp_path)
        sup = TrainSupervisor(d, save_interval_steps=100)
        n = {"v": 0}

        def step(s, b):
            n["v"] += 1
            if n["v"] == 3:
                sup.request_preemption()
            return _step(s, b)

        rep = sup.run(step, 1.0, _loader(), max_steps=11)
        assert rep.status == "preempted"
        rep2 = sup.run(_step, 1.0, _loader(), max_steps=11)  # SAME sup
        assert rep2.status == "completed"
        assert rep2.resumed_from == 3 and rep2.steps_done == 8

    def test_finite_data_source_completes_with_durable_final(self,
                                                             tmp_path):
        """Regression: a data source that raises StopIteration used to
        escape run() raw, skipping the final save and the report."""
        class Finite:
            def __init__(self, n):
                self.n = n

            def next_batch(self):
                if self.n == 0:
                    raise StopIteration
                self.n -= 1
                return np.full(3, float(self.n))

        sup = TrainSupervisor(str(tmp_path), save_interval_steps=100)
        rep = sup.run(_step, 1.0, Finite(4), max_steps=50)
        assert rep.status == "completed" and rep.steps_done == 4
        assert sup.store.latest_valid_step() == 4   # final save landed

    def test_async_save_run_resumes(self, tmp_path):
        d = str(tmp_path)
        full = TrainSupervisor(str(tmp_path / "x")).run(
            _step, 1.0, _loader(), max_steps=9).losses
        TrainSupervisor(d, save_interval_steps=2, async_save=True).run(
            _step, 1.0, _loader(), max_steps=4)
        rep = TrainSupervisor(d, save_interval_steps=2,
                              async_save=True).run(
            _step, 1.0, _loader(), max_steps=9)
        assert rep.resumed_from == 4
        assert full[4:] == rep.losses

    def test_global_rng_state_round_trips(self, tmp_path):
        """track_global_rng: the core.random stream continues across a
        kill exactly where it stopped."""
        def rng_step(s, b):
            u = float(np.asarray(
                pt.rand([1]).numpy()))   # consumes the global stream
            return s + u, s + u

        def run(d, k, fresh_seed):
            if fresh_seed:
                pt.seed(123)
            return TrainSupervisor(d, save_interval_steps=1).run(
                rng_step, 0.0, _loader(shuffle=False), max_steps=k)

        full = run(str(tmp_path / "a"), 6, True).losses
        run(str(tmp_path / "b"), 3, True)
        pt.seed(999)      # clobber: restore must bring the real state back
        rep = run(str(tmp_path / "b"), 6, False)
        assert full[3:] == rep.losses

    def test_restore_state_can_leave_global_rng_alone(self, tmp_path):
        """restore_state(restore_rng=False) is for callers doing a
        model-state-only rollback that keeps moving FORWARD through
        data (rewinding the global stream there would replay past
        subkeys); fit and the standalone loop both roll back the full
        cursor and use the default."""
        from paddle_tpu.core import random as _random
        sup = TrainSupervisor(str(tmp_path), save_interval_steps=1)
        pt.seed(41)
        sup.save_state(1, {"w": 1.0}, force=True)
        pt.rand([1])                       # advance the global stream
        moved = _random.get_rng_state()
        _, meta, done = sup.restore_state(restore_rng=False)
        assert done == 1
        assert _random.get_rng_state()[1] == moved[1]   # not rewound
        sup.restore_state()                # default still rewinds
        assert _random.get_rng_state()[1] != moved[1]


class TestSupervisedFit:
    def _model(self, learning_rate=0.01):
        pt.seed(7)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.Adam(
            learning_rate=learning_rate, parameters=net.parameters()),
            loss=nn.BCEWithLogitsLoss())
        return m

    def _dataset(self, n=48):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((n, 4)).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
        return TensorDataset([x, y])

    class _Rec:
        def __init__(self, hook=None):
            self.losses = []
            self.hook = hook

        def set_model(self, m):
            pass

        def __getattr__(self, name):
            if name.startswith("on_"):
                return lambda *a, **k: None
            raise AttributeError(name)

        def on_train_batch_end(self, step, logs=None):
            self.losses.append(logs["loss"])
            if self.hook:
                self.hook(len(self.losses))

    def test_fit_preempt_resume_bit_matches(self, tmp_path):
        ds = self._dataset()
        rec_full = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec_full],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "a"), save_interval_steps=4))
        assert len(rec_full.losses) == 12
        sup = TrainSupervisor(str(tmp_path / "b"), save_interval_steps=4)
        rec1 = self._Rec(hook=lambda n: n == 5
                         and sup.request_preemption())
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec1], supervisor=sup)
        assert len(rec1.losses) == 5
        rec2 = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec2],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "b"), save_interval_steps=4))
        assert rec1.losses + rec2.losses == rec_full.losses

    def test_fit_lr_schedule_live_and_resume_bit_matches(self, tmp_path):
        """Regression: update_fn's default lr evaluated get_lr() at jit
        TRACE time, baking the epoch-0 LR as a compile-time constant.
        Two visible symptoms, both asserted here: the scheduler never
        took effect in-run (trajectory identical to a constant-LR run),
        and a killed run re-traced on resume with the restored advanced
        schedule, diverging from the uninterrupted run. lr is now a
        traced argument."""
        def sched_model():
            return self._model(pt.optimizer.lr.StepDecay(
                0.05, step_size=1, gamma=0.5))

        ds = self._dataset()                       # 6 batches per epoch
        rec_full, m_full = self._Rec(), sched_model()
        m_full.fit(ds, batch_size=8, epochs=3, verbose=0,
                   callbacks=[rec_full],
                   supervisor=TrainSupervisor(str(tmp_path / "a"),
                                              save_interval_steps=4))
        assert len(rec_full.losses) == 18
        # schedule takes effect: identical to a constant-LR run through
        # epoch 0, diverging once the first epoch-end step() halves it
        rec_const = self._Rec()
        self._model(0.05).fit(ds, batch_size=8, epochs=2, verbose=0,
                              callbacks=[rec_const],
                              supervisor=TrainSupervisor(
                                  str(tmp_path / "c"),
                                  save_interval_steps=4))
        assert rec_const.losses[:6] == rec_full.losses[:6]
        assert rec_const.losses[6:12] != rec_full.losses[6:12]
        # kill mid-epoch-1 (8 steps in), resume in a fresh model:
        # per-step losses must bit-match the uninterrupted run
        sup = TrainSupervisor(str(tmp_path / "b"), save_interval_steps=4)
        rec1 = self._Rec(hook=lambda n: n == 8
                         and sup.request_preemption())
        sched_model().fit(ds, batch_size=8, epochs=3, verbose=0,
                          callbacks=[rec1], supervisor=sup)
        assert len(rec1.losses) == 8
        rec2, m2 = self._Rec(), sched_model()
        m2.fit(ds, batch_size=8, epochs=3, verbose=0, callbacks=[rec2],
               supervisor=TrainSupervisor(str(tmp_path / "b"),
                                          save_interval_steps=4))
        assert rec1.losses + rec2.losses == rec_full.losses
        assert m2._optimizer.get_lr() == m_full._optimizer.get_lr()

    def test_fit_resume_across_epoch_boundary(self, tmp_path):
        ds = self._dataset()
        rec_full = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec_full],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "a"), save_interval_steps=4))
        sup = TrainSupervisor(str(tmp_path / "b"), save_interval_steps=4)
        self._model().fit(ds, batch_size=8, epochs=1, verbose=0,
                          callbacks=[self._Rec()], supervisor=sup)
        rec2 = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec2],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "b"), save_interval_steps=4))
        assert rec2.losses == rec_full.losses[6:]   # epoch 0 not re-run

    def test_fit_same_model_and_supervisor_resume_in_process(self,
                                                             tmp_path):
        """Re-invoking fit on the SAME model + supervisor after a
        preemption resumes (stop_training and the preempt flag reset at
        fit entry) and stays bit-exact."""
        ds = self._dataset()
        rec_full = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=2, verbose=0,
                          callbacks=[rec_full],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "a"), save_interval_steps=4))
        sup = TrainSupervisor(str(tmp_path / "b"), save_interval_steps=4)
        m = self._model()
        rec1 = self._Rec(hook=lambda n: n == 5
                         and sup.request_preemption())
        m.fit(ds, batch_size=8, epochs=2, verbose=0, callbacks=[rec1],
              supervisor=sup)
        assert m.stop_training
        rec2 = self._Rec()
        m.fit(ds, batch_size=8, epochs=2, verbose=0, callbacks=[rec2],
              supervisor=sup)                     # same model, same sup
        assert rec1.losses + rec2.losses == rec_full.losses

    def test_fit_num_iters_stop_saves_mid_epoch_cursor(self, tmp_path):
        """Regression: a num_iters (or early-stopping) break used to
        stamp the end-of-epoch cursor (epoch+1, 0), silently skipping
        the epoch's untrained remainder on resume."""
        ds = self._dataset()                   # 6 batches per epoch
        rec_full = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=1, verbose=0,
                          callbacks=[rec_full],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "a"),
                              save_interval_steps=100))
        d = str(tmp_path / "b")
        self._model().fit(ds, batch_size=8, epochs=1, verbose=0,
                          num_iters=2, callbacks=[self._Rec()],
                          supervisor=TrainSupervisor(
                              d, save_interval_steps=100))
        rec2 = self._Rec()
        self._model().fit(ds, batch_size=8, epochs=1, verbose=0,
                          callbacks=[rec2],
                          supervisor=TrainSupervisor(
                              d, save_interval_steps=100))
        # batches 2..5 of epoch 0 run now — nothing skipped, bit-equal
        assert rec2.losses == rec_full.losses[2:]

    def test_fit_num_iters_does_not_spin_remaining_epochs(self, tmp_path):
        """Regression: after num_iters the epoch loop used to keep
        cycling through the remaining epochs, force-saving a cursor of
        (epoch, 0) each time — advancing the resume point past data
        that was never trained."""
        ds = self._dataset()
        sup = TrainSupervisor(str(tmp_path), save_interval_steps=100)
        epochs_seen = []

        class EpochRec(self._Rec):
            def on_epoch_begin(self, epoch, logs=None):
                epochs_seen.append(epoch)

        self._model().fit(ds, batch_size=8, epochs=50, num_iters=2,
                          verbose=0, callbacks=[EpochRec()],
                          supervisor=sup)
        assert epochs_seen == [0]             # no zombie epochs
        _, meta, _ = sup.restore_state()
        assert meta["cursor"] == {"epoch": 0, "batch": 2}

    def test_fit_iterable_dataset_refused(self, tmp_path):
        """An iterable stream has no index space, so the exact-resume
        contract cannot hold — supervised fit must refuse loudly, not
        stamp cursors that lie on resume."""
        from paddle_tpu.io import IterableDataset

        class Stream(IterableDataset):
            def __iter__(self):
                yield (np.zeros(4, np.float32), np.zeros(1, np.float32))

        sup = TrainSupervisor(str(tmp_path))
        with pytest.raises(ValueError, match="map-style"):
            self._model().fit(Stream(), batch_size=8, verbose=0,
                              supervisor=sup)

    def test_fit_rollback_before_any_checkpoint_aborts_typed(self,
                                                             tmp_path):
        """Parity with TrainSupervisor.run: a rollback decision with an
        empty store must raise TrainAnomalyError, not silently burn the
        rollback budget restoring nothing."""
        x = np.full((16, 4), np.nan, np.float32)   # NaN loss from step 1
        y = np.zeros((16, 1), np.float32)
        sup = TrainSupervisor(
            str(tmp_path), save_interval_steps=1000,
            anomaly=AnomalyPolicy(max_consecutive=1, max_rollbacks=2))
        with pytest.raises(TrainAnomalyError, match="nothing to roll"):
            self._model().fit(TensorDataset([x, y]), batch_size=8,
                              epochs=1, verbose=0, supervisor=sup)

    def test_fit_real_data_error_propagates_loudly(self, tmp_path):
        """A non-injected dataset failure must surface, not silently
        truncate the epoch (a raised-through generator is closed, so a
        blind retry would read as end-of-data)."""
        class Bad:
            def __len__(self):
                return 24

            def __getitem__(self, i):
                if i == 13:
                    raise RuntimeError("disk hiccup")
                x = np.zeros(4, np.float32)
                return x, np.zeros(1, np.float32)

        sup = TrainSupervisor(str(tmp_path), save_interval_steps=4)
        with pytest.raises(RuntimeError, match="disk hiccup"):
            self._model().fit(Bad(), batch_size=8, epochs=1, shuffle=False,
                              verbose=0, callbacks=[self._Rec()],
                              supervisor=sup)

    def test_fit_rollback_replays_same_batches_bit_exact(self, tmp_path):
        """ISSUE 5 satellite (PR 4 scope cut): a NaN rollback restores
        the DATA CURSOR and rng chain alongside model state, so the
        rolled-back run replays the same batches from the same state
        and its committed losses bit-match a clean run. (Before: the
        rollback kept moving forward in data, silently skipping the
        batches between the checkpoint and the anomaly.)"""
        rng = np.random.default_rng(3)
        x = rng.standard_normal((48, 4)).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)

        class Transient:
            """Batches 4-5 corrupt until the rollback 'repairs' the
            pipeline — a transient data fault, not a poisoned
            dataset (which must replay into the same wall and abort
            typed instead)."""
            healed = False

            def __len__(self):
                return 48

            def __getitem__(self, i):
                if not Transient.healed and i >= 32:
                    return x[i], np.full((1,), np.nan, np.float32)
                return x[i], y[i]

        clean = self._Rec()
        self._model().fit(TensorDataset([x, y]), batch_size=8, epochs=1,
                          shuffle=False, verbose=0, callbacks=[clean],
                          supervisor=TrainSupervisor(
                              str(tmp_path / "a"), save_interval_steps=2))
        assert len(clean.losses) == 6
        sup = TrainSupervisor(
            str(tmp_path / "b"), save_interval_steps=2,
            anomaly=AnomalyPolicy(max_consecutive=2, max_rollbacks=1))
        rec = self._Rec(hook=lambda n: (sup.rollbacks
                                        and setattr(Transient, "healed",
                                                    True)))
        Transient.healed = False
        self._model().fit(Transient(), batch_size=8, epochs=1,
                          shuffle=False, verbose=0, callbacks=[rec],
                          supervisor=sup)
        assert sup.rollbacks == 1 and sup.anomalies == 2
        committed = [l for l in rec.losses if np.isfinite(l)]
        # 4 committed before the anomaly burst + the REPLAYED batches
        # 4 and 5 — identical to the uninterrupted run, bit for bit
        assert committed == clean.losses

    def test_fit_persistent_nan_replays_into_wall_and_aborts(self,
                                                             tmp_path):
        """With the cursor restored, a DETERMINISTIC data anomaly
        replays after rollback, burns the budget, and aborts typed —
        it can no longer be silently skipped over by drifting forward
        in data."""
        rng = np.random.default_rng(4)
        x = rng.standard_normal((24, 4)).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
        y[8:] = np.nan                       # batches 1-2 always poisoned
        sup = TrainSupervisor(
            str(tmp_path), save_interval_steps=1,
            anomaly=AnomalyPolicy(max_consecutive=2, max_rollbacks=1))
        with pytest.raises(TrainAnomalyError):
            self._model().fit(TensorDataset([x, y]), batch_size=8,
                              epochs=2, shuffle=False, verbose=0,
                              supervisor=sup)
        assert sup.rollbacks == 1

    def test_guarded_step_rebuilds_when_check_grads_changes(self):
        m = self._model()
        m._build_guarded_step(check_grads=True)
        first = m._gstep_fn
        m._build_guarded_step(check_grads=True)
        assert m._gstep_fn is first             # cache hit
        m._build_guarded_step(check_grads=False)
        assert m._gstep_fn is not first         # policy change rebuilds

    def test_fit_nan_step_skipped_params_unpoisoned(self, tmp_path):
        """A poisoned batch (NaN labels) must not touch params: the
        guarded step refuses the commit, training continues, and the
        final params are finite."""
        rng = np.random.default_rng(0)
        x = rng.standard_normal((24, 4)).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
        y[8:16] = np.nan                    # batch 1 of 3 is poisoned
        ds = TensorDataset([x, y])
        reg = MetricRegistry()
        sup = TrainSupervisor(str(tmp_path), save_interval_steps=100,
                              registry=reg,
                              anomaly=AnomalyPolicy(max_consecutive=10))
        m = self._model()
        m.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0,
              callbacks=[self._Rec()], supervisor=sup)
        w = m.network.state_dict()
        for v in w.values():
            assert np.isfinite(np.asarray(v.numpy())).all()
        c = reg.counter("train_anomaly_total", "", labelnames=("kind",))
        total = sum(child for child in (
            c.labels(kind="nonfinite_loss").value,
            c.labels(kind="nonfinite_grad").value))
        assert total == 2                   # poisoned batch, both epochs


# ------------------------------------------------------------ satellites
class TestCallbackListFiresAll:
    def test_all_callbacks_fire_then_first_error_raised(self):
        from paddle_tpu.hapi.callbacks import Callback, CallbackList
        from paddle_tpu.reliability import CallbackError
        fired = []

        class Boom(Callback):
            def on_epoch_end(self, epoch, logs=None):
                fired.append("boom")
                raise ValueError("poisoned logger")

        class Quiet(Callback):
            def on_epoch_end(self, epoch, logs=None):
                fired.append("quiet")

        cbs = CallbackList([Boom(), Quiet(), Boom()])
        with pytest.raises(CallbackError) as ei:
            cbs.on_epoch_end(0, {})
        assert fired == ["boom", "quiet", "boom"]   # nobody starved
        assert ei.value.rid == "Boom"
        assert isinstance(ei.value.__cause__, ValueError)
        assert len(ei.value.errors) == 2

    def test_clean_sweep_raises_nothing(self):
        from paddle_tpu.hapi.callbacks import Callback, CallbackList
        cbs = CallbackList([Callback(), Callback()])
        cbs.on_epoch_end(0, {})
        cbs.on_train_end()


class _DictStore:
    """Minimal TCPStore stand-in for ElasticManager unit tests."""

    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v.encode() if isinstance(v, str) else bytes(v)

    def get(self, k):
        return self.d[k]

    def check(self, k):
        return k in self.d


class TestElasticClose:
    def test_close_joins_heartbeat_and_watch_threads(self):
        from paddle_tpu.parallel.elastic import ElasticManager
        mgr = ElasticManager(store=_DictStore(), node_id="0", np=1,
                             heartbeat_interval=0.01)
        mgr.register()
        mgr.watch()
        hb, watch = mgr._hb_thread, mgr._watch_thread
        assert hb.daemon and watch.daemon       # can't hang shutdown
        mgr.close()
        assert not hb.is_alive() and not watch.is_alive()
        assert mgr._hb_thread is None and mgr._watch_thread is None
        mgr.close()                              # idempotent

    def test_context_manager_closes(self):
        from paddle_tpu.parallel.elastic import ElasticManager
        with ElasticManager(store=_DictStore(), node_id="0", np=1,
                            heartbeat_interval=0.01) as mgr:
            mgr.register()
            hb = mgr._hb_thread
        assert not hb.is_alive()


class TestTrainEpochRangeAtomic:
    def test_crash_during_save_reruns_not_skips_epoch(self, tmp_path):
        """Satellite regression: a kill between 'save' and 'epoch
        advance' re-runs the unsaved epoch on resume (never skips), and
        never re-runs an epoch whose save committed."""
        from paddle_tpu.incubate.checkpoint import TrainEpochRange
        from paddle_tpu.reliability import InjectedFault
        d = str(tmp_path)
        model = nn.Linear(4, 4)
        # crash e1's commit: rename visit 0 = e0 (ok), visit 1 = e1
        fi = FaultInjector(seed=0).on(faults.CKPT_RENAME, schedule=[1])
        r1 = TrainEpochRange(4, "job", checkpoint_dir=d, fault_injector=fi)
        r1.add("model", model)
        seen = []
        with pytest.raises(InjectedFault):
            for epoch in r1:
                seen.append(epoch)
        assert seen == [0, 1]                   # died saving e1
        model2 = nn.Linear(4, 4)
        r2 = TrainEpochRange(4, "job", checkpoint_dir=d)
        r2.add("model", model2)
        assert r2.restored_from() == 0          # e1's torn save invisible
        assert list(r2) == [1, 2, 3]            # e1 re-runs, e0 does not
        np.testing.assert_allclose(model2.weight.numpy(),
                                   model.weight.numpy())

    def test_torn_epoch_dir_ignored_on_scan(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import TrainEpochRange
        d = str(tmp_path)
        model = nn.Linear(2, 2)
        r1 = TrainEpochRange(3, "job", checkpoint_dir=d)
        r1.add("model", model)
        for _ in r1:
            pass
        # corrupt the newest snapshot post-commit (bit rot)
        newest = os.path.join(r1.store.step_path(2), "manifest.json")
        with open(newest, "w") as f:
            f.write("{broken")
        r2 = TrainEpochRange(3, "job", checkpoint_dir=d)
        assert r2.restored_from() == -1   # only epoch 2 kept; it's torn
        assert list(r2) == [0, 1, 2]

    def test_foreign_format_run_dir_warns(self, tmp_path):
        """A run directory holding pre-durable-format checkpoints
        (meta.json + per-epoch payload dirs) must not be silently
        mistaken for a fresh run."""
        from paddle_tpu.incubate.checkpoint import TrainEpochRange
        d = tmp_path / "job"
        d.mkdir()
        (d / "meta.json").write_text('{"epoch": 7}')
        (d / "e7").mkdir()
        with pytest.warns(RuntimeWarning, match="cannot read"):
            r = TrainEpochRange(9, "job", checkpoint_dir=str(tmp_path))
        assert r.restored_from() == -1


class TestNoBareExcept:
    def test_lint_clean_on_package_benchmarks_and_scripts(self):
        """Satellite: scripts/check_no_bare_except.py stays green over
        every directory it now covers — paddle_tpu/, benchmarks/ and
        scripts/ (wired here so a regression fails tier-1)."""
        from importlib import util
        spec = util.spec_from_file_location(
            "check_no_bare_except",
            os.path.join(REPO, "scripts", "check_no_bare_except.py"))
        mod = util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.DEFAULT_DIRS == ("paddle_tpu", "benchmarks",
                                    "scripts")
        for d in mod.DEFAULT_DIRS:
            hits = mod.bare_excepts(os.path.join(REPO, d))
            assert hits == [], f"bare excepts found in {d}: {hits}"
        # ISSUE 6 satellite: every messageful NotImplementedError in
        # the serving stack points at its ROADMAP item (or carries an
        # explicit no-roadmap opt-out) — scope cuts stay discoverable
        for d in mod.DEFAULT_DIRS:
            _, cuts = mod.scan(os.path.join(REPO, d), REPO)
            assert cuts == [], f"unpointered scope cuts in {d}: {cuts}"

    def test_lint_flags_unpointered_scope_cut(self, tmp_path):
        """A new NotImplementedError in a serving-stack dir must name a
        ROADMAP item; 'ROADMAP' in the message or a '# no-roadmap:'
        comment passes, a silent cut fails."""
        from importlib import util
        spec = util.spec_from_file_location(
            "check_no_bare_except",
            os.path.join(REPO, "scripts", "check_no_bare_except.py"))
        mod = util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        d = tmp_path / "paddle_tpu" / "inference"
        d.mkdir(parents=True)
        (d / "x.py").write_text(
            "def a():\n"
            "    raise NotImplementedError('quantized pool later')\n"
            "def b():\n"
            "    raise NotImplementedError('see ROADMAP item 3')\n"
            "def c():\n"
            "    # no-roadmap: abstract refusal\n"
            "    raise NotImplementedError('not a cut')\n"
            "def d():\n"
            "    raise NotImplementedError\n")
        _, cuts = mod.scan(str(tmp_path / "paddle_tpu"),
                           str(tmp_path))
        assert [line for _, line in cuts] == [2]

    def test_lint_flags_a_bare_except(self, tmp_path):
        from importlib import util
        spec = util.spec_from_file_location(
            "check_no_bare_except",
            os.path.join(REPO, "scripts", "check_no_bare_except.py"))
        mod = util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = tmp_path / "bad.py"
        bad.write_text("try:\n    pass\nexcept:\n    pass\n")
        hits = mod.bare_excepts(str(tmp_path))
        assert len(hits) == 1 and hits[0][1] == 3

    def test_cli_exit_codes(self, tmp_path):
        script = os.path.join(REPO, "scripts", "check_no_bare_except.py")
        ok = subprocess.run([sys.executable, script,
                             os.path.join(REPO, "paddle_tpu")],
                            capture_output=True)
        assert ok.returncode == 0
        bad = tmp_path / "bad.py"
        bad.write_text("try:\n    pass\nexcept:\n    pass\n")
        r = subprocess.run([sys.executable, script, str(tmp_path)],
                           capture_output=True, text=True)
        assert r.returncode == 1 and "bare 'except:'" in r.stdout
