"""Device-cost ledger, compile watch, and tick-phase attribution
(ISSUE 13).

Contracts under test:

- ``CostCatalog``: each (op, shape-signature) priced ONCE from the
  compiled program's own ``cost_analysis`` (exact FLOPs asserted for a
  known matmul), the catalog's executable is what dispatches (tokens
  bit-identical with the catalog on or off, greedy AND sampled), every
  dispatch charges, compiles are counted/timed, and a compile after
  warmup is flagged a RECOMPILE.
- server wiring: steady-state paged decode publishes nonzero
  ``server_flops_total{op}`` / ``server_hbm_bytes_total{op}`` and an
  MFU gauge; steady state stays ZERO-recompile across slot churn and
  admission waves (the shape-signature-leak guard); a forced new
  chunk width after warmup lands a ``compile`` recorder event with
  ``recompile=True`` and a ``compile_stall`` journey phase; tick
  phases publish and ride recorder tick events + postmortem bundles;
  ``/stats["costs"]`` and heartbeat-digest utilization.
- DISABLED catalog: treated exactly like None — zero clock reads and
  zero lock acquisitions on the tick path (FakeClock + counting-lock,
  the flight-recorder contract).
- skipped_page_dma cross-validation (PR-10 known cut): the goodput
  ledger's host-side DMA model tracks the COMPILED paged-attention
  program's bytes linearly in block-table width, with a documented
  constant factor.
- fleet merge: ``serving_mfu`` folds by MEAN, not sum.
- ``scripts/bench_track.py``: schema'd appends, the committed
  BENCHLOG/bands pass ``--check``, and an injected synthetic
  regression (or a malformed log line, or a missing banded metric)
  exits nonzero.

Everything but the cross-validation compiles runs on the StubModel
double — tier-1 fast."""
import importlib.util
import json
import os
import socket
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.serving import serve_metrics
from paddle_tpu.telemetry import (CostCatalog, FakeClock, FlightRecorder,
                                  MetricRegistry, ServerTelemetry,
                                  merge_snapshots)
from paddle_tpu.telemetry.costs import TICK_PHASES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prompt(*toks):
    return np.asarray(toks, np.int32)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _CountingLock:
    def __init__(self):
        self.acquisitions = 0

    def __enter__(self):
        self.acquisitions += 1
        return self

    def __exit__(self, *exc):
        return False


def _paged_server(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 32)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 4)
    return ContinuousBatchingServer(StubModel(), **kw)


# --------------------------------------------------------------------------
# CostCatalog unit contracts
# --------------------------------------------------------------------------
class TestCostCatalogUnit:
    def test_program_prices_exact_flops_and_caches(self):
        cat = CostCatalog()
        fn = jax.jit(lambda a, b: jnp.dot(a, b))
        x = jnp.ones((64, 128), jnp.float32)
        y = jnp.ones((128, 32), jnp.float32)
        prog = cat.program("decode", fn, (x, y))
        assert prog.compiled_now and not prog.recompile
        # the compiler's own number: 2*M*N*K MACs for a plain matmul
        assert prog.flops == 2 * 64 * 32 * 128
        assert prog.hbm_bytes > 0
        out = prog(x, y)                    # dispatch == charge
        np.testing.assert_allclose(np.asarray(out), np.asarray(fn(x, y)))
        # same signature: cache hit, no second compile
        again = cat.program("decode", fn, (x, y))
        assert again is prog and not again.compiled_now
        assert cat.compiles() == {"decode": 1}
        # new signature: a second priced entry
        x2 = jnp.ones((32, 128), jnp.float32)
        prog2 = cat.program("decode", fn, (x2, y))
        assert prog2.compiled_now and prog2.flops == 2 * 32 * 32 * 128
        assert cat.compiles() == {"decode": 2}
        cat.flush_tick()
        tot = cat.totals()
        assert tot["decode"]["dispatches"] == 1
        assert tot["decode"]["flops"] == prog.flops

    def test_compile_metrics_published(self):
        reg = MetricRegistry()
        cat = CostCatalog(registry=reg)
        fn = jax.jit(lambda a: a + 1)
        prog = cat.program("prefill", fn, (jnp.ones((4,)),))
        prog(jnp.ones((4,)))
        cat.flush_tick()
        assert reg.get("server_compiles_total") \
            .labels(op="prefill").value == 1
        assert reg.get("serving_compile_seconds").count == 1
        assert reg.get("server_hbm_bytes_total") \
            .labels(op="prefill").value > 0

    def test_unpriceable_fn_falls_back_raw_not_a_compile(self):
        reg = MetricRegistry()
        cat = CostCatalog(registry=reg)
        # warm the catalog so a false recompile alarm WOULD fire
        fn = jax.jit(lambda a: a + 1)
        x = jnp.ones((4,))
        for _ in range(3):
            cat.program("decode", fn, (x,))(x)
            cat.flush_tick()
        assert cat.warmed

        def plain(x):                       # no .lower: not jitted
            return x * 2

        prog = cat.program("decode", plain, (jnp.ones((2,)),))
        assert cat.price_errors == 1
        assert prog.flops == 0.0 and prog.hbm_bytes == 0.0
        np.testing.assert_allclose(np.asarray(prog(jnp.ones((2,)))),
                                   [2.0, 2.0])
        # a pricing FAILURE is not an XLA compile: no compile counted,
        # no recompile/compile_stall alarm even after warmup
        assert not prog.compiled_now and not prog.recompile
        assert cat.recompiles == 0
        assert cat.compiles() == {"decode": 1}
        assert reg.get("server_compiles_total") \
            .labels(op="decode").value == 1

    def test_warmup_then_recompile_flagged(self):
        cat = CostCatalog(warm_after_ticks=2)
        fn = jax.jit(lambda a: a + 1)
        x = jnp.ones((4,))
        prog = cat.program("decode", fn, (x,))
        prog(x)
        cat.flush_tick()                    # compile tick: quiet resets
        assert not cat.warmed
        for _ in range(2):                  # two quiet charged ticks
            cat.program("decode", fn, (x,))(x)
            cat.flush_tick()
        assert cat.warmed and cat.recompiles == 0
        prog2 = cat.program("decode", fn, (jnp.ones((8,)),))
        assert prog2.compiled_now and prog2.recompile
        assert cat.recompiles == 1

    def test_warmup_is_per_op(self):
        """ISSUE 14 satellite (lifts the PR-12 global-warmup cut): each
        op warms independently, so the fused program's legitimate new
        chunk-width signatures while ITS ladder is still climbing never
        fire a recompile alarm just because decode already warmed —
        and decode's shape-leak watch isn't reset by them either."""
        cat = CostCatalog(warm_after_ticks=2)
        fn = jax.jit(lambda a: a + 1)
        x = jnp.ones((4,))
        cat.program("decode", fn, (x,))(x)
        cat.flush_tick()
        for _ in range(2):                  # decode warms
            cat.program("decode", fn, (x,))(x)
            cat.flush_tick()
        assert cat.warmed_op("decode") and cat.warmed
        # a FIRST fused compile after decode warmed: not a recompile
        y = jnp.ones((8,))
        p1 = cat.program("fused", fn, (y,))
        p1(y)
        cat.flush_tick()
        assert p1.compiled_now and not p1.recompile
        assert cat.recompiles == 0
        assert not cat.warmed               # fused still climbing
        # fused climbs its pow2 ladder while unwarm: still no alarm,
        # and decode's armed watch is untouched by the churn
        z = jnp.ones((16,))
        p2 = cat.program("fused", fn, (z,))
        p2(z)
        cat.flush_tick()
        assert not p2.recompile and cat.recompiles == 0
        assert cat.warmed_op("decode")
        for _ in range(2):                  # fused warms too
            cat.program("fused", fn, (z,))(z)
            cat.flush_tick()
        assert cat.warmed_op("fused") and cat.warmed
        assert sorted(cat.snapshot()["warm_ops"]) == ["decode", "fused"]
        # NOW a new fused signature is a real recompile — and it trips
        # only fused's alarm, not a decode one
        p3 = cat.program("fused", fn, (jnp.ones((32,)),))
        assert p3.recompile and cat.recompiles == 1
        p4 = cat.program("decode", fn, (x,))
        assert not p4.compiled_now          # cache hit, no new alarm

    def test_mfu_exact_on_fake_clock(self):
        fc = FakeClock()
        reg = MetricRegistry()
        cat = CostCatalog(registry=reg, clock=fc, peak_flops=1000.0,
                          peak_hbm_bytes_per_s=100.0)
        fn = jax.jit(lambda a, b: jnp.dot(a, b))
        x = jnp.ones((4, 8), jnp.float32)
        y = jnp.ones((8, 2), jnp.float32)
        prog = cat.program("decode", fn, (x, y))     # 128 flops
        prog(x, y)
        tp = cat.phase_timer()
        fc.advance(0.5)
        tp.mark("decode_launch")
        cat.flush_tick()
        # (128 flops / 0.5 s) / 1000 peak = 0.256
        assert cat.mfu() == pytest.approx(prog.flops / 0.5 / 1000.0)
        assert reg.get("serving_mfu").value == pytest.approx(cat.mfu())
        snap = cat.snapshot()
        assert snap["roofline_ratio"] >= snap["mfu"]
        assert snap["last_tick_phases"] == {"decode_launch": 0.5}
        ph = reg.get("serving_tick_phase_seconds")
        assert ph.labels(phase="decode_launch").count == 1

    def test_charge_bytes_is_flops_free(self):
        cat = CostCatalog()
        cat.charge_bytes("block_table", 4096)
        cat.charge_bytes("block_table", 4096)
        cat.flush_tick()
        tot = cat.totals()["block_table"]
        assert tot == {"flops": 0.0, "hbm_bytes": 8192.0,
                       "dispatches": 2}

    def test_bad_peaks_rejected(self):
        with pytest.raises(ValueError):
            CostCatalog(peak_flops=0)
        with pytest.raises(ValueError):
            CostCatalog(peak_hbm_bytes_per_s=-1)


# --------------------------------------------------------------------------
# Disabled catalog: structurally zero cost (flight-recorder contract)
# --------------------------------------------------------------------------
class TestDisabledCatalog:
    def test_disabled_zero_clock_zero_locks_server_treats_as_none(self):
        fc = FakeClock()
        cat = CostCatalog(enabled=False, clock=fc)
        lock = _CountingLock()
        cat._lock = lock
        # program() on a disabled catalog is the identity — no AOT, no
        # clock
        fn = jax.jit(lambda a: a + 1)
        assert cat.program("decode", fn, (jnp.ones((2,)),)) is fn
        srv = _paged_server(costs=cat)
        assert srv._costs is None and srv._phase_timer is None
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        out = srv.run()
        np.testing.assert_array_equal(out[rid],
                                      stub_tokens([1, 2, 3], 4))
        assert fc.reads == 0 and lock.acquisitions == 0
        assert cat._tick == {} and cat._phases == {}
        assert srv.device_costs() is None
        assert srv.utilization() == {}

    def test_costs_true_builds_on_server_clock_and_registry(self):
        tele = ServerTelemetry()
        srv = _paged_server(telemetry=tele, costs=True)
        assert srv._costs is not None
        assert srv._costs.clock is srv._clock
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        srv.run()
        assert tele.registry.get("server_flops_total") \
            .labels(op="decode").value > 0
        del rid


# --------------------------------------------------------------------------
# Server wiring: pricing, parity, steady state, recompiles, phases
# --------------------------------------------------------------------------
class TestServerCosting:
    def test_steady_state_publishes_nonzero_costs_and_mfu(self):
        tele = ServerTelemetry()
        cat = CostCatalog(registry=tele.registry)
        srv = _paged_server(telemetry=tele, costs=cat)
        rng = np.random.default_rng(3)
        rids = []
        for _ in range(4):
            p = rng.integers(0, 16, (6,)).astype(np.int32)
            rids.append((srv.submit(p, max_new_tokens=6), p))
        outs = srv.run()
        for rid, p in rids:
            np.testing.assert_array_equal(outs[rid], stub_tokens(p, 6))
        flops = tele.registry.get("server_flops_total")
        hbm = tele.registry.get("server_hbm_bytes_total")
        assert flops.labels(op="decode").value > 0
        assert hbm.labels(op="decode").value > 0
        assert flops.labels(op="prefill").value > 0
        assert tele.registry.get("serving_mfu").value > 0
        snap = srv.device_costs()
        assert snap["ops"]["decode"]["dispatches"] > 0
        # every decode dispatch charged the same (single-signature)
        # compiled program: totals divide exactly
        dec = snap["ops"]["decode"]
        assert dec["flops"] % dec["dispatches"] == 0
        # transfers priced as bytes moved, zero FLOPs
        assert snap["ops"]["block_table"]["flops"] == 0
        assert snap["ops"]["block_table"]["hbm_bytes"] > 0
        assert snap["ops"]["state_push"]["hbm_bytes"] > 0
        util = srv.utilization()
        assert util["mfu"] == pytest.approx(cat.mfu())

    def test_tokens_bit_identical_with_and_without_catalog(self):
        for sample in (False, True):
            outs = []
            for costs in (None, True):
                srv = _paged_server(costs=costs, do_sample=sample,
                                    seed=11)
                rng = np.random.default_rng(7)
                rids = [srv.submit(rng.integers(0, 16, (5,))
                                   .astype(np.int32),
                                   max_new_tokens=7, seed=i)
                        for i in range(4)]
                got = srv.run()
                outs.append([got[r] for r in rids])
            for a, b in zip(*outs):
                np.testing.assert_array_equal(a, b)

    def test_steady_state_zero_recompiles_across_churn_and_waves(self):
        """The shape-signature-leak guard (ISSUE 13 satellite): after
        a warmup wave covers the workload's chunk widths, slot churn
        and admission waves must compile NOTHING new — a leak that
        reintroduced per-tick compiles fails here."""
        cat = CostCatalog()
        srv = _paged_server(costs=cat, prefill_tokens_per_tick=4,
                            max_slots=2)
        rng = np.random.default_rng(5)

        def wave():
            rids = []
            for _ in range(4):          # 4 requests through 2 slots:
                p = rng.integers(0, 16, (6,)).astype(np.int32)
                rids.append((srv.submit(p, max_new_tokens=5), p))
            outs = srv.run()
            for rid, p in rids:
                np.testing.assert_array_equal(outs[rid],
                                              stub_tokens(p, 5))

        wave()                          # warmup: compiles the ladder
        assert cat.warmed
        compiles = cat.compiles()
        for _ in range(3):              # churn waves, fresh prompts
            wave()
        assert cat.recompiles == 0
        assert cat.compiles() == compiles

    def test_recompile_lands_recorder_event_and_compile_stall(self):
        rec = FlightRecorder()
        cat = CostCatalog()
        srv = _paged_server(costs=cat, recorder=rec, journeys=True,
                            max_cache_len=64, page_size=4)
        # warm on short prompts (small chunk widths)
        for _ in range(2):
            rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
            srv.run()
        assert cat.warmed
        # a prompt wider than any warmed chunk width forces a fresh
        # ragged-prefill signature: a mid-serving RECOMPILE
        long_p = np.arange(17, dtype=np.int32) % 16
        rid = srv.submit(long_p, max_new_tokens=4)
        out = srv.run()
        np.testing.assert_array_equal(out[rid],
                                      stub_tokens(long_p, 4))
        assert cat.recompiles >= 1
        evs = [e for e in rec.events(kind="compile") if e["recompile"]]
        assert evs and evs[-1]["op"] == "prefill"
        assert evs[-1]["seconds"] >= 0
        timeline = srv.journey(rid)
        assert any(e.get("phase") == "compile_stall" for e in timeline)

    def test_phases_published_and_embedded_in_tick_events(self):
        tele = ServerTelemetry()
        rec = FlightRecorder()
        cat = CostCatalog(registry=tele.registry)
        srv = _paged_server(telemetry=tele, costs=cat, recorder=rec)
        rid = srv.submit(_prompt(2, 4, 6), max_new_tokens=6)
        srv.run()
        del rid
        snap = cat.snapshot()
        phases = snap["last_tick_phases"]
        assert phases and set(phases) <= set(TICK_PHASES)
        assert all(v >= 0 for v in phases.values())
        h = tele.registry.get("serving_tick_phase_seconds")
        assert h.labels(phase="decode_launch").count > 0
        assert h.labels(phase="admission").count > 0
        ticks = rec.events(kind="tick")
        assert ticks and "phases" in ticks[-1]
        assert set(ticks[-1]["phases"]) <= set(TICK_PHASES)

    def test_postmortem_freezes_costs_section(self):
        rec = FlightRecorder()
        srv = _paged_server(costs=True, recorder=rec)
        rid = srv.submit(_prompt(3, 1, 4), max_new_tokens=4)
        srv.run()
        del rid
        srv.kill()
        bundle = srv.postmortems()[-1]
        assert bundle["reason"] == "killed"
        costs = bundle["costs"]
        assert costs["ops"]["decode"]["flops"] > 0
        assert "last_tick_phases" in costs
        assert "compiles" in costs

    def test_stats_endpoint_carries_costs(self):
        tele = ServerTelemetry()
        srv = _paged_server(telemetry=tele, costs=True)
        rid = srv.submit(_prompt(1, 5, 2), max_new_tokens=3)
        srv.run()
        del rid
        ms = serve_metrics(srv)
        try:
            status, body = _get(ms.url + "/stats")
            assert status == 200
            stats = json.loads(body)["stats"]
            assert stats["costs"]["ops"]["decode"]["flops"] > 0
            assert "goodput" not in stats or True   # ledger-optional
        finally:
            ms.close()

    def test_heartbeat_digest_carries_utilization(self):
        from paddle_tpu.inference.remote import ReplicaHost
        srv = _paged_server(costs=True, ledger=True)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        srv.run()
        del rid
        host = ReplicaHost(srv)          # not started: digest is pure
        d = host._digest()
        assert 0.0 <= d["util"]["goodput_ratio"] <= 1.0
        assert d["util"]["mfu"] > 0
        json.dumps(d)                    # digest must stay wire-safe


# --------------------------------------------------------------------------
# Heartbeat utilization over the real wire (loopback)
# --------------------------------------------------------------------------
def _loopback_available():
    try:
        s = socket.create_server(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


@pytest.mark.net
@pytest.mark.skipif(not _loopback_available(),
                    reason="cannot bind a loopback socket here")
class TestUtilizationOverWire:
    def test_remote_replica_reads_util_from_digest(self):
        from paddle_tpu.inference.remote import (RemoteReplica,
                                                 ReplicaHost)
        srv = _paged_server(costs=True, ledger=True)
        host = ReplicaHost(srv, heartbeat_s=0.01).start()
        rep = RemoteReplica(host.address)
        try:
            rep.start()
            rid = rep.submit(_prompt(2, 5, 9), max_new_tokens=5)
            out = rep.wait(rid)
            np.testing.assert_array_equal(out,
                                          stub_tokens([2, 5, 9], 5))
            deadline = time.time() + 5.0
            util = {}
            while time.time() < deadline:
                util = rep.utilization()
                if util.get("mfu"):
                    break
                time.sleep(0.02)
            assert util.get("mfu", 0) > 0
            assert 0.0 <= util["goodput_ratio"] <= 1.0
        finally:
            rep.close()
            host.close()
            if srv._thread is not None:
                srv.stop(timeout=10)


# --------------------------------------------------------------------------
# skipped_page_dma cross-validation (PR-10 known cut closed)
# --------------------------------------------------------------------------
class TestSkippedDmaCrossValidation:
    """The goodput ledger's ``skipped_page_dma`` kind models the paged
    kernels' masked page traffic host-side as
    ``(table_width - live_pages) * page_size`` token-equivalents per
    live slot per launch. Here that model is held against the COMPILED
    programs' own ``cost_analysis`` bytes.

    Divergence, pinned: the compiled fallback touches each DMAed page
    a small CONSTANT number of times — gather materialization (write +
    read), the GQA head repeat, the QK^T and AV reads — plus
    [table-width]-sized f32 softmax intermediates, so compiled bytes
    per masked page = k x (page_size x kv-row bytes) with k a
    shape-dependent constant (~6 at llama-ish head dims, measured).
    The ledger counts each masked token ONCE. What the ledger needs —
    and what is asserted — is that the compiled cost is AFFINE in the
    table width (slopes agree across spans) with a per-page slope
    within a documented constant band of the model, so relative waste
    comparisons (the ROADMAP item-2 win condition) track the compiled
    programs."""

    S, NH, KVH, HD, PG, POOL = 4, 4, 2, 64, 16, 64

    def _decode_bytes(self, maxp):
        from paddle_tpu.ops.pallas.paged_attention import paged_attention
        q = jnp.ones((self.S, self.NH, self.HD), jnp.float32)
        k = jnp.ones((self.POOL, self.PG, self.KVH, self.HD),
                     jnp.float32)
        v = jnp.ones_like(k)
        bt = jnp.zeros((self.S, maxp), jnp.int32)
        ln = jnp.full((self.S,), 5, jnp.int32)
        ca = jax.jit(paged_attention).lower(
            q, k, v, bt, ln).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca["bytes accessed"])

    def test_decode_model_tracks_compiled_bytes(self):
        b8, b16, b32 = (self._decode_bytes(p) for p in (8, 16, 32))
        # affine in table width: per-page slope stable across spans
        slope_a = (b16 - b8) / (16 - 8)
        slope_b = (b32 - b16) / (32 - 16)
        assert slope_a > 0
        assert abs(slope_a - slope_b) / slope_b < 0.25
        # the model's bytes for one masked page, per slot
        row_bytes = 2 * self.KVH * self.HD * 4          # K+V, f32
        model_page = self.PG * row_bytes
        ratio = (slope_b / self.S) / model_page
        # documented constant band (see class docstring): the program
        # touches each page ~4-8x; way outside means the model or the
        # kernel's traffic shape changed — re-derive, don't ignore
        assert 2.0 <= ratio <= 12.0, \
            f"compiled-vs-model bytes ratio {ratio:.2f} left [2, 12]"

    def test_ragged_prefill_bytes_scale_with_table_width(self):
        from paddle_tpu.ops.pallas.ragged_prefill import \
            ragged_prefill_attention

        def bytes_at(maxp):
            q = jnp.ones((self.S, 2, self.NH, self.HD), jnp.float32)
            k = jnp.ones((self.POOL, self.PG, self.KVH, self.HD),
                         jnp.float32)
            v = jnp.ones_like(k)
            bt = jnp.zeros((self.S, maxp), jnp.int32)
            t0 = jnp.zeros((self.S,), jnp.int32)
            ca = jax.jit(ragged_prefill_attention).lower(
                q, k, v, bt, t0).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca["bytes accessed"])

        b8, b32 = bytes_at(8), bytes_at(32)
        slope = (b32 - b8) / (32 - 8) / self.S
        row_bytes = 2 * self.KVH * self.HD * 4
        ratio = slope / (self.PG * row_bytes)
        # the ragged kernel shares the decode fallback's gather
        # structure but reads the gathered frame once per chunk row —
        # wider band, same linear-tracking property
        assert 1.0 <= ratio <= 25.0, \
            f"ragged compiled-vs-model ratio {ratio:.2f} left [1, 25]"

    def test_fused_tick_live_slice_deletes_masked_page_bytes(self):
        """ISSUE 14: the fused-tick program still pays gather bytes
        AFFINE in whatever table width it is handed (same structure as
        the split kernels above) — the win is that the server only
        ever hands it the LIVE slice. Priced at the live width, the
        launch's bytes undercut even the narrowest full-width launch;
        the server-level flatness-in-CONFIGURED-width assertion (fixed
        live pages, 4x table growth, <10% byte drift) lives in
        tests/test_fused_tick.py."""
        from paddle_tpu.ops.pallas.fused_tick import (
            build_schedule, fused_tick_attention)

        last_np = np.full((self.S,), 5, np.int32)    # 1 live page/slot
        ss, sp, _ = build_schedule(last_np, self.PG, n_slots=self.S)

        def bytes_at(maxp):
            q = jnp.ones((self.S, 2, self.NH, self.HD), jnp.float32)
            k = jnp.ones((self.POOL, self.PG, self.KVH, self.HD),
                         jnp.float32)
            v = jnp.ones_like(k)
            bt = jnp.zeros((self.S, maxp), jnp.int32)
            t0 = jnp.zeros((self.S,), jnp.int32)
            last = jnp.asarray(last_np)
            dec = jnp.zeros((self.S,), jnp.int32)
            ca = jax.jit(fused_tick_attention).lower(
                q, k, v, bt, t0, last, dec, jnp.asarray(ss),
                jnp.asarray(sp)).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca["bytes accessed"])

        b_live, b8, b32 = bytes_at(1), bytes_at(8), bytes_at(32)
        assert (b32 - b8) / (32 - 8) > 0      # handed width still costs
        assert b_live < b8                    # ...so hand it the slice


# --------------------------------------------------------------------------
# Fleet merge: serving_mfu folds by MEAN
# --------------------------------------------------------------------------
class TestMfuFleetMerge:
    def test_mfu_merges_by_mean_not_sum(self):
        snaps = []
        for mfu, slots in ((0.4, 3), (0.8, 5)):
            reg = MetricRegistry()
            reg.gauge("serving_mfu", "").set(mfu)
            reg.gauge("serving_active_slots", "").set(slots)
            snaps.append(reg.snapshot())
        merged = merge_snapshots(snaps)
        assert merged["serving_mfu"]["samples"][()] == \
            pytest.approx(0.6)
        # control: ordinary gauges still SUM
        assert merged["serving_active_slots"]["samples"][()] == 8


# --------------------------------------------------------------------------
# bench_track: schema, append, and the regression gate
# --------------------------------------------------------------------------
class TestBenchTrack:
    def test_validate_rejects_bad_rounds(self):
        bt = _load_script("bench_track")
        ok = bt.validate_round({"metric": "m_1", "value": 1.5,
                                "unit": "tok/s"})
        assert ok["ts"]                       # auto-stamped
        for bad in (
                {"value": 1, "unit": "x"},                   # no metric
                {"metric": "m", "unit": "x"},                # no value
                {"metric": "m", "value": 1},                 # no unit
                {"metric": "bad-name", "value": 1, "unit": "x"},
                {"metric": "tokéns", "value": 1, "unit": "x"},
                {"metric": "m", "value": float("nan"), "unit": "x"},
                {"metric": "m", "value": True, "unit": "x"},
                {"metric": "m", "value": 1, "unit": "x",
                 "surprise": 1},                             # unknown
                {"metric": "m", "value": 1, "unit": "x",
                 "vs_baseline": float("inf")},
        ):
            with pytest.raises(bt.BenchLogError):
                bt.validate_round(bad)

    def test_append_and_load_round_trip(self, tmp_path):
        bt = _load_script("bench_track")
        log = str(tmp_path / "log.jsonl")
        bt.append_round({"metric": "m_a", "value": 2.0, "unit": "x",
                         "note": "n"}, path=log)
        bt.append_round({"metric": "m_a", "value": 3.0, "unit": "x"},
                        path=log)
        rounds = bt.load_rounds(log)
        assert [r["value"] for r in rounds] == [2.0, 3.0]

    def test_committed_log_passes_committed_bands(self):
        bt = _load_script("bench_track")
        ok, report = bt.check()
        assert ok, "\n".join(report)
        assert any("paged_decode_flops_per_token" in line
                   for line in report)

    def test_synthetic_regression_exits_nonzero(self, tmp_path):
        bt = _load_script("bench_track")
        log = str(tmp_path / "log.jsonl")
        bands = str(tmp_path / "bands.json")
        bt.append_round({"metric": "paged_decode_mfu", "value": 0.02,
                         "unit": "ratio"}, path=log)
        with open(bands, "w") as f:
            json.dump({"paged_decode_mfu": {"min": 0.01}}, f)
        assert bt.main(["check", "--log", log, "--bands", bands]) == 0
        # the regression round lands LAST — latest wins, gate trips
        bt.append_round({"metric": "paged_decode_mfu", "value": 0.001,
                         "unit": "ratio"}, path=log)
        assert bt.main(["--check", "--log", log, "--bands", bands]) == 1

    def test_missing_banded_metric_fails(self, tmp_path):
        bt = _load_script("bench_track")
        log = str(tmp_path / "log.jsonl")
        bands = str(tmp_path / "bands.json")
        bt.append_round({"metric": "other", "value": 1.0, "unit": "x"},
                        path=log)
        with open(bands, "w") as f:
            json.dump({"never_recorded": {"min": 0}}, f)
        ok, report = bt.check(log_path=log, bands_path=bands)
        assert not ok and "never_recorded" in report[0]

    def test_malformed_log_line_fails_loudly(self, tmp_path):
        bt = _load_script("bench_track")
        log = str(tmp_path / "log.jsonl")
        with open(log, "w") as f:
            f.write('{"metric": "m", "value": 1.0, "unit": "x", '
                    '"ts": "t"}\n')
            f.write("not json at all\n")
        with pytest.raises(bt.BenchLogError):
            bt.load_rounds(log)
        ok, report = bt.check(log_path=log,
                              bands_path=os.path.join(
                                  REPO, "scripts", "bench_bands.json"))
        assert not ok and "FAIL" in report[0]
