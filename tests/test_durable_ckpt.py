"""Durable checkpoint layer: atomic commit, checksum verification,
newest-VALID fallback, retention, async saves, and the native
CheckpointManager built on top of it."""
import os

import numpy as np
import pytest

import jax.numpy as jnp
from paddle_tpu.io.checkpoint import CheckpointManager
from paddle_tpu.reliability import (CheckpointCorruptError, CheckpointStore,
                                    FaultInjector, faults)
from paddle_tpu.reliability import ckpt as dckpt
from paddle_tpu.telemetry import FakeClock, MetricRegistry


def _state(v=0.0):
    return {"w": jnp.arange(6.0).reshape(2, 3) + v,
            "b": np.full(3, v, np.float32),
            "nest": {"step": int(v), "extra": [np.float64(v), None]}}


def _corrupt(path, name="leaf_00000.pkl"):
    with open(os.path.join(path, name), "ab") as f:
        f.write(b"\x00torn")


class TestWriteRead:
    def test_roundtrip_preserves_structure_and_values(self, tmp_path):
        p = str(tmp_path / "c")
        meta = {"step": 3, "rng_key": jnp.array([1, 2], jnp.uint32),
                "cursor": {"epoch": 1, "index": 4}}
        manifest = dckpt.write_checkpoint(p, _state(2.0), meta, step=3)
        assert manifest["step"] == 3
        # per-leaf checksums: one file per leaf + skeleton + meta + manifest
        assert any(k.startswith("leaf_") for k in manifest["files"])
        state, m2 = dckpt.read_checkpoint(p)
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.arange(6.0).reshape(2, 3) + 2.0)
        np.testing.assert_array_equal(state["b"], np.full(3, 2.0))
        assert state["nest"]["step"] == 2
        assert state["nest"]["extra"][1] is None
        assert m2["cursor"] == {"epoch": 1, "index": 4}
        np.testing.assert_array_equal(np.asarray(m2["rng_key"]), [1, 2])

    def test_bf16_leaf_roundtrips(self, tmp_path):
        p = str(tmp_path / "c")
        w = jnp.arange(4.0, dtype=jnp.bfloat16)
        dckpt.write_checkpoint(p, {"w": w})
        state, _ = dckpt.read_checkpoint(p)
        assert state["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(state["w"], np.float32), np.arange(4.0))

    @pytest.mark.parametrize("victim", ["leaf_00000.pkl", "skeleton.pkl",
                                        "meta.pkl"])
    def test_any_torn_file_is_detected(self, tmp_path, victim):
        p = str(tmp_path / "c")
        dckpt.write_checkpoint(p, _state())
        _corrupt(p, victim)
        with pytest.raises(CheckpointCorruptError, match=victim):
            dckpt.read_checkpoint(p)
        with pytest.raises(CheckpointCorruptError):
            dckpt.verify_checkpoint(p)

    def test_missing_manifest_and_missing_file_are_typed(self, tmp_path):
        p = str(tmp_path / "c")
        dckpt.write_checkpoint(p, _state())
        os.remove(os.path.join(p, "leaf_00000.pkl"))
        with pytest.raises(CheckpointCorruptError, match="missing file"):
            dckpt.read_checkpoint(p)
        os.remove(os.path.join(p, dckpt.MANIFEST_NAME))
        with pytest.raises(CheckpointCorruptError, match="missing manifest"):
            dckpt.read_checkpoint(p)

    def test_overwrite_refused_unless_requested(self, tmp_path):
        p = str(tmp_path / "c")
        dckpt.write_checkpoint(p, _state(1.0))
        with pytest.raises(FileExistsError):
            dckpt.write_checkpoint(p, _state(2.0))
        dckpt.write_checkpoint(p, _state(2.0), overwrite=True)
        state, _ = dckpt.read_checkpoint(p)
        assert state["nest"]["step"] == 2

    def test_checkpoint_meta_peeks_without_state(self, tmp_path):
        p = str(tmp_path / "c")
        dckpt.write_checkpoint(p, _state(), {"step": 9, "tag": "x"})
        meta = dckpt.checkpoint_meta(p)
        assert meta["step"] == 9 and meta["tag"] == "x"

    def test_injected_write_leaves_torn_file_not_checkpoint(self, tmp_path):
        """A kill mid-write leaves a TORN temp file — and NO visible
        checkpoint under the final name."""
        p = str(tmp_path / "c")
        fi = FaultInjector(seed=0).on(faults.CKPT_WRITE, schedule=[1])
        with pytest.raises(Exception):
            dckpt.write_checkpoint(p, _state(), injector=fi)
        assert not os.path.exists(p)
        tmps = [d for d in os.listdir(tmp_path) if ".tmp." in d]
        assert len(tmps) == 1
        # the torn file really is a strict prefix (half-written)
        torn = sorted(os.listdir(os.path.join(tmp_path, tmps[0])))
        assert torn, "injected write crash left no remnant"

    def test_injected_rename_leaves_no_visible_checkpoint(self, tmp_path):
        p = str(tmp_path / "c")
        fi = FaultInjector(seed=0).on(faults.CKPT_RENAME, schedule=[0])
        with pytest.raises(Exception):
            dckpt.write_checkpoint(p, _state(), injector=fi)
        assert not os.path.exists(p)


class TestCheckpointStore:
    def test_restore_falls_back_to_newest_valid(self, tmp_path):
        reg = MetricRegistry()
        store = CheckpointStore(str(tmp_path), registry=reg)
        for s in (1, 2, 3):
            store.save(s, _state(float(s)))
        _corrupt(store.step_path(3))
        state, meta, step = store.restore()
        assert step == 2 and state["nest"]["step"] == 2
        assert store.skipped and store.skipped[0][0] == 3
        assert reg.counter("ckpt_corrupt_total", "").value == 1
        # explicit-step restore of the corrupt one raises typed
        with pytest.raises(CheckpointCorruptError):
            store.restore(step=3)

    def test_empty_store_restores_none(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        assert store.restore() == (None, None, None)
        assert store.latest_valid_step() is None

    def test_crashed_save_invisible_and_swept(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.save(1, _state(1.0))
        store.injector = FaultInjector(seed=0).on(faults.CKPT_WRITE,
                                                  schedule=[0])
        with pytest.raises(Exception):
            store.save(2, _state(2.0))
        assert store.all_steps() == [1]          # torn save invisible
        assert any(".tmp." in d for d in os.listdir(store.directory))
        store.injector = None
        store.save(3, _state(3.0))
        assert not any(".tmp." in d for d in os.listdir(store.directory))
        _, _, step = store.restore()
        assert step == 3

    def test_sweep_spares_live_foreign_process_tmp(self, tmp_path):
        """Preemption handover: the replacement trainer's sweep must
        not delete a temp dir that a still-LIVE other process (the old
        trainer flushing its final save) is writing — only dirs whose
        owner pid is dead (or our own crashed-injected leftovers) are
        abandoned."""
        store = CheckpointStore(str(tmp_path))
        live = tmp_path / ".step_0000000009.tmp.1.123"     # pid 1: alive
        dead = tmp_path / ".step_0000000008.tmp.999999999.123"
        live.mkdir()
        dead.mkdir()
        store.save(1, _state(1.0))
        assert live.exists()
        assert not dead.exists()

    def test_prune_counts_valid_only_and_keeps_newest_valid(self, tmp_path):
        store = CheckpointStore(str(tmp_path), max_to_keep=2)
        for s in (1, 2, 3):
            store.save(s, _state(float(s)))
        assert store.all_steps() == [2, 3]
        _corrupt(store.step_path(3))
        # bit rot is discovered by a later process: fresh store, empty
        # per-instance validity cache, so pruning re-verifies dir 3
        store2 = CheckpointStore(str(tmp_path), max_to_keep=2)
        store2.save(4, _state(4.0))
        # valid = [2, 4]: both kept; corrupt 3 pruned away
        assert store2.valid_steps() == [2, 4]
        _, _, step = store2.restore()
        assert step == 4

    def test_same_instance_corruption_discovered_by_restore(self, tmp_path):
        """The validity cache trusts steps this instance committed;
        restore() always re-hashes, demoting a rotted dir in-place."""
        store = CheckpointStore(str(tmp_path), max_to_keep=2)
        for s in (1, 2, 3):
            store.save(s, _state(float(s)))
        _corrupt(store.step_path(3))
        _, _, step = store.restore()             # discovery point
        assert step == 2
        store.save(4, _state(4.0))
        assert store.valid_steps() == [2, 4]

    def test_kill_inside_overwrite_swap_recovers_old(self, tmp_path):
        """Crash between the swap's two renames (old parked, new not
        yet live): recovery restores the parked OLD checkpoint — an
        overwrite can replace a checkpoint, never lose one."""
        store = CheckpointStore(str(tmp_path))
        store.save(5, _state(1.0))
        store.injector = FaultInjector(seed=0).on(faults.CKPT_SWAP,
                                                  schedule=[0])
        with pytest.raises(Exception):
            store.save(5, _state(2.0))           # overwrite same step
        # a fresh store (next process) heals the interrupted swap
        store2 = CheckpointStore(str(tmp_path))
        state, meta, step = store2.restore()
        assert step == 5
        assert state["nest"]["step"] == 1        # the OLD content
        dckpt.verify_checkpoint(store2.step_path(5))
        # and the healed store keeps working
        store2.save(6, _state(6.0))
        assert store2.valid_steps() == [5, 6]

    def test_save_restore_histograms_on_fake_clock(self, tmp_path):
        reg = MetricRegistry()
        clk = FakeClock()
        store = CheckpointStore(str(tmp_path), registry=reg, clock=clk)
        store.save(1, _state())
        store.restore()
        snap = reg.snapshot()
        assert snap["ckpt_save_seconds"]["samples"][()]["count"] == 1
        assert snap["ckpt_restore_seconds"]["samples"][()]["count"] == 1
        assert reg.gauge("ckpt_last_good_step", "").value == 1


class TestAsyncCheckpointer:
    def test_saves_complete_and_barrier_waits(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        ac = dckpt.AsyncCheckpointer(store)
        for s in (1, 2, 3):
            ac.save(s, _state(float(s)))
        ac.wait()
        assert store.valid_steps() == [1, 2, 3]
        ac.close()

    def test_background_failure_is_sticky(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.injector = FaultInjector(seed=0).on(faults.CKPT_RENAME,
                                                  schedule=[0])
        ac = dckpt.AsyncCheckpointer(store)
        ac.save(1, _state())
        with pytest.raises(Exception):
            ac.wait()
        assert store.all_steps() == []           # torn attempt invisible

    def test_first_background_failure_wins(self, tmp_path):
        """Docstring contract: the FIRST background failure (the root
        cause) is what re-raises, never overwritten by later ones."""
        store = CheckpointStore(str(tmp_path))
        calls = {"n": 0}

        def boom(step, state, meta=None):
            calls["n"] += 1
            raise ValueError(f"failure-{calls['n']}")

        store.save = boom
        ac = dckpt.AsyncCheckpointer(store)
        with pytest.raises(ValueError, match="failure-1"):
            ac.save(1, _state())
            ac.save(2, _state())     # raises here or at the barrier —
            ac.wait()                # either way it must be failure-1

    def test_snapshot_decouples_from_caller_mutation(self, tmp_path):
        """The async save must capture values at submit time — the
        caller may clobber its arrays right after."""
        store = CheckpointStore(str(tmp_path))
        ac = dckpt.AsyncCheckpointer(store)
        arr = np.arange(4.0)
        ac.save(1, {"w": arr})
        arr[:] = -1.0
        ac.wait()
        state, _, _ = store.restore()
        np.testing.assert_array_equal(state["w"], np.arange(4.0))


class TestCheckpointManager:
    def test_interval_skips_do_not_count_against_keep(self, tmp_path):
        """Satellite: with save_interval_steps=5 and max_to_keep=2,
        21 step calls produce saves {0,5,10,15,20} and retention keeps
        the two newest REAL saves — skipped steps never evict."""
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2,
                                save_interval_steps=5)
        for s in range(21):
            saved = mgr.save(s, _state(float(s)))
            assert saved == (s % 5 == 0)
        assert mgr.all_steps() == [15, 20]
        assert mgr.restore()["nest"]["step"] == 20

    def test_latest_valid_survives_pruning_and_restore(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2,
                                save_interval_steps=5)
        for s in range(21):
            mgr.save(s, _state(float(s)))
        _corrupt(mgr.store.step_path(20))
        # newest dir is torn -> restore lands on newest VALID
        assert mgr.restore()["nest"]["step"] == 15
        assert mgr.latest_step() == 15
        # a later off-interval forced save prunes the corpse, keeps 15
        mgr.save(21, _state(21.0), force=True)
        assert mgr.all_steps() == [15, 21]
        assert mgr.restore()["nest"]["step"] == 21

    def test_explicit_step_and_metrics(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), save_interval_steps=1)
        mgr.save(3, _state(3.0), metrics={"loss": 0.25})
        mgr.save(4, _state(4.0))
        assert mgr.restore(step=3)["nest"]["step"] == 3
        assert mgr.metrics(3) == {"loss": 0.25}
        assert mgr.metrics(4) is None
        assert mgr.metrics(99) is None          # never saved: no crash
        assert mgr.restore(step=99) is None     # absence != corruption

    def test_async_manager_round_trip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=True,
                                save_interval_steps=1)
        mgr.save(1, _state(1.0))
        mgr.save(2, _state(2.0))
        assert mgr.latest_step() == 2            # implies barrier
        assert mgr.restore()["nest"]["step"] == 2
        mgr.close()

    def test_empty_manager_restore_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.restore() is None
        assert mgr.latest_step() is None
        assert mgr.all_steps() == []

    def test_foreign_format_directory_warns_loudly(self, tmp_path):
        """A directory holding checkpoints this format cannot read
        (e.g. written by the pre-durable orbax-backed manager) must not
        be silently mistaken for a fresh start."""
        import warnings
        (tmp_path / "42").mkdir()                   # orbax-style step dir
        (tmp_path / "42" / "d").write_bytes(b"x")
        mgr = CheckpointManager(str(tmp_path))
        with pytest.warns(RuntimeWarning, match="cannot read"):
            assert mgr.restore() is None
        # a real durable save silences the warning path
        mgr.save(1, _state(1.0), force=True)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert mgr.restore()["nest"]["step"] == 1
