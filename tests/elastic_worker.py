"""Worker for the elastic kill-and-relaunch e2e test (launched as a real
process by paddle_tpu.parallel.launch.Controller).

Phase "train": world_size ranks in lockstep (native TCPStore barrier),
rank 0 checkpoints every step, CRASH_RANK exits non-zero at CRASH_STEP.
Phase "resume": a single worker (the smaller cluster) restores the last
checkpoint ONTO A DIFFERENT MESH LAYOUT via the converter and finishes
training, writing result.json.
"""
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu.parallel as dist
from paddle_tpu.parallel.mesh import P
from paddle_tpu.parallel.checkpoint_converter import (build_shardings,
                                                      load_on_mesh)
from paddle_tpu.io.checkpoint import save_sharded

RANK = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
WORLD = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
CKDIR = os.environ["CKPT_DIR"]
PHASE = os.environ.get("PHASE", "train")
CRASH_RANK = int(os.environ.get("CRASH_RANK", "-1"))
CRASH_STEP = int(os.environ.get("CRASH_STEP", "3"))
TOTAL = int(os.environ.get("TOTAL_STEPS", "6"))
MASTER = os.environ.get("PADDLE_MASTER", "127.0.0.1:29712")

TARGET = np.linspace(-1.0, 1.0, 32).reshape(8, 4).astype(np.float32)


def loss_and_grad(w):
    diff = w - jnp.asarray(TARGET)
    return jnp.sum(diff * diff), 2.0 * diff


def train_steps(w, start, end, losses):
    for step in range(start, end):
        loss, g = loss_and_grad(w)
        w = w - 0.1 * g
        losses.append(float(loss))
    return w


def main():
    if PHASE == "train":
        from paddle_tpu.runtime import TCPStore
        host, port = MASTER.rsplit(":", 1)
        store = TCPStore(host=host, port=int(port),
                         is_master=(RANK == 0), world_size=WORLD)

        mesh = dist.init_mesh(dp=4)                # save-time layout
        sh = build_shardings(mesh, {"w": np.zeros((8, 4), np.float32)},
                             spec_map={"w": P("dp")})
        w = jax.device_put(jnp.zeros((8, 4), jnp.float32), sh["w"])
        losses = []
        for step in range(TOTAL):
            # lockstep barrier through the store (real cross-process sync)
            store.add(f"bar/{step}", 1)
            deadline = time.time() + 60
            while store.add(f"bar/{step}", 0) < WORLD:
                if time.time() > deadline:
                    raise RuntimeError(f"barrier timeout at step {step}")
                time.sleep(0.02)
            if RANK == CRASH_RANK and step == CRASH_STEP:
                os._exit(17)                        # simulated crash
            loss, g = loss_and_grad(w)
            w = w - 0.1 * g
            losses.append(float(loss))
            if RANK == 0:
                save_sharded({"w": w,
                              "step": jnp.asarray(step + 1, jnp.int32)},
                             os.path.join(CKDIR, f"step_{step + 1}"))
                with open(os.path.join(CKDIR, "LATEST"), "w") as f:
                    f.write(str(step + 1))
        return 0

    # ---- resume on the smaller cluster with a DIFFERENT mesh layout
    with open(os.path.join(CKDIR, "LATEST")) as f:
        last = int(f.read().strip())
    mesh_b = dist.init_mesh(dp=2, mp=2)
    state = load_on_mesh(os.path.join(CKDIR, f"step_{last}"), mesh_b,
                         spec_map={"w": P("dp", "mp")})
    w = state["w"]
    assert w.sharding.spec == P("dp", "mp"), w.sharding
    start = int(state["step"])
    assert start == last, (start, last)
    losses = []
    w = train_steps(w, start, TOTAL, losses)
    with open(os.path.join(CKDIR, "result.json"), "w") as f:
        json.dump({"resumed_from": start, "final_w": np.asarray(w).tolist(),
                   "losses": losses}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
