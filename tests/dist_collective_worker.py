"""Worker for the multi-process collective e2e: launcher env ->
init_parallel_env -> jax.distributed -> cross-process CPU collective."""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu.parallel as dist
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def main():
    env = dist.init_parallel_env()   # consumes the launcher env protocol
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == int(os.environ["PADDLE_TRAINERS_NUM"]), world

    mesh = Mesh(jax.devices(), ("dp",))
    x = jax.make_array_from_callback(
        (world * 4,), NamedSharding(mesh, P("dp")),
        lambda idx: jnp.full((4,), rank + 1.0, jnp.float32))
    total = jax.jit(lambda a: a.sum(),
                    out_shardings=NamedSharding(mesh, P()))(x)
    got = float(total)
    expected = sum(4.0 * (r + 1) for r in range(world))
    assert got == expected, (got, expected)

    out = os.path.join(os.environ["PROBE_DIR"], f"rank{rank}.json")
    json.dump({"rank": rank, "world": world, "sum": got}, open(out, "w"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
