"""Pallas flash-attention kernels vs the XLA oracle (interpret mode on CPU).

Reference test pattern: OpTest check_grad numeric-vs-analytic comparison
(python/paddle/fluid/tests/unittests/eager_op_test.py) for
flash_attn/flash_attn_grad (paddle/phi/kernels/gpu/flash_attn_kernel.cu,
flash_attn_grad_kernel.cu).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as fa


def _rand(bh, s, d, seed):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(bh, s, d).astype(np.float32) * 0.3)


def _oracle(q, k, v, sm_scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, fa.NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_oracle(causal):
    bh, s, d = 2, 256, 64
    q, k, v = (_rand(bh, s, d, i) for i in range(3))
    sm = 1.0 / np.sqrt(d)
    o, lse = fa._flash_fwd_pallas(q, k, v, sm, causal, interpret=True)
    ref = _oracle(q, k, v, sm, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # lse parity: logsumexp of masked scores
    sc = jnp.einsum("bqd,bkd->bqk", q, k) * sm
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        sc = jnp.where(mask, sc, fa.NEG_INF)
    ref_lse = jax.scipy.special.logsumexp(sc, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_oracle(causal):
    bh, s, d = 2, 256, 64
    q, k, v = (_rand(bh, s, d, 10 + i) for i in range(3))
    do = _rand(bh, s, d, 99)
    sm = 1.0 / np.sqrt(d)

    o, lse = fa._flash_fwd_pallas(q, k, v, sm, causal, interpret=True)
    dq, dk, dv = fa._flash_bwd_pallas(q, k, v, o, lse, do, sm, causal,
                                      interpret=True)

    ref_o, vjp = jax.vjp(lambda q_, k_, v_: _oracle(q_, k_, v_, sm, causal),
                         q, k, v)
    rdq, rdk, rdv = vjp(do)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv),
                               rtol=2e-4, atol=2e-4)


def test_backward_rectangular_kv():
    # cross-attention shape: sq != sk
    bh, sq, sk, d = 2, 128, 256, 64
    q = _rand(bh, sq, d, 1)
    k = _rand(bh, sk, d, 2)
    v = _rand(bh, sk, d, 3)
    do = _rand(bh, sq, d, 4)
    sm = 1.0 / np.sqrt(d)
    o, lse = fa._flash_fwd_pallas(q, k, v, sm, False, interpret=True)
    dq, dk, dv = fa._flash_bwd_pallas(q, k, v, o, lse, do, sm, False,
                                      interpret=True)
    _, vjp = jax.vjp(lambda a, b, c: _oracle(a, b, c, sm, False), q, k, v)
    rdq, rdk, rdv = vjp(do)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_with_lse_cotangent(causal):
    """The ring-attention merge backpropagates into lse; the kernel folds
    that cotangent into the delta row. Check against the XLA oracle vjp of
    the (o, lse)-returning reference."""
    bh, s, d = 2, 256, 64
    q, k, v = (_rand(bh, s, d, 20 + i) for i in range(3))
    do = _rand(bh, s, d, 77)
    rng = np.random.RandomState(5)
    dlse = jnp.asarray(rng.randn(bh, s).astype(np.float32))
    sm = 1.0 / np.sqrt(d)

    o, lse = fa._flash_fwd_pallas(q, k, v, sm, causal, interpret=True)
    dq, dk, dv = fa._flash_bwd_pallas(q, k, v, o, lse, do, sm, causal,
                                      interpret=True, dlse=dlse)

    def ref(q_, k_, v_):
        # [bh, s, d] frame of _ref_with_lse
        sc = jnp.einsum("bqd,bkd->bqk", q_, k_) * sm
        if causal:
            mask = jnp.tril(jnp.ones((s, s), dtype=bool))
            sc = jnp.where(mask, sc, fa.NEG_INF)
        l = jax.scipy.special.logsumexp(sc, axis=-1)
        p = jnp.exp(sc - l[..., None])
        return jnp.einsum("bqk,bkd->bqd", p, v_), l

    _, vjp = jax.vjp(ref, q, k, v)
    rdq, rdk, rdv = vjp((do, dlse))
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv),
                               rtol=2e-4, atol=2e-4)


def test_public_api_grad_cpu_fallback():
    # on CPU the public path uses the XLA reference; grads must flow
    b, s, h, d = 2, 64, 2, 32
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))

    def loss(q, k, v):
        return fa.flash_attention(q, k, v, causal=True).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(np.isfinite(np.asarray(x)).all() for x in g)
