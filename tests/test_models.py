"""Model zoo e2e: forward, loss decreases under jitted training."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _train_lm(model, vocab, steps=12, batch=2, seq=32):
    import jax
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    init_fn, update_fn = opt.functional()
    params = model.raw_params()
    state = init_fn(params)
    rng = jax.random.PRNGKey(0)
    ids = np.random.randint(0, vocab, size=(batch, seq)).astype(np.int32)

    from paddle_tpu.jit import functional_call

    def _loss(logits, labels):
        import jax.numpy as jnp
        lg = logits[:, :-1]
        lb = labels[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

    @jax.jit
    def step(params, state, ids, i):
        def loss_fn(ps):
            logits = functional_call(model, ps, ids)
            return _loss(logits, ids)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, new_s = update_fn(grads, params, state, step=i)
        return loss, new_p, new_s

    losses = []
    for i in range(steps):
        loss, params, state = step(params, state, ids, i + 1)
        losses.append(float(loss))
    return losses


def test_gpt_tiny_trains():
    cfg = gpt2_tiny()
    model = GPTForCausalLM(cfg)
    losses = _train_lm(model, cfg.vocab_size)
    assert losses[-1] < losses[0] - 0.5, losses


def test_llama_tiny_trains():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    losses = _train_lm(model, cfg.vocab_size)
    assert losses[-1] < losses[0] - 0.5, losses


def test_llama_eager_forward_matches_jit():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = np.random.randint(0, cfg.vocab_size, size=(1, 16)).astype(np.int32)
    eager = model(pt.to_tensor(ids)).numpy()
    from paddle_tpu.jit import functional_call
    import jax
    jit_out = jax.jit(lambda ps, x: functional_call(model, ps, x))(
        model.raw_params(), ids)
    np.testing.assert_allclose(eager, np.asarray(jit_out), rtol=2e-4,
                               atol=2e-5)


def test_gpt_eager_backward_runs():
    cfg = gpt2_tiny()
    model = GPTForCausalLM(cfg)
    ids = pt.to_tensor(np.random.randint(0, cfg.vocab_size,
                                         size=(2, 16)).astype(np.int32))
    logits = model(ids)
    loss = model.loss(logits, ids)
    loss.backward()
    grads = [p.grad for p in model.parameters() if p.trainable]
    assert all(g is not None for g in grads)
